#!/usr/bin/env python
"""Diff two bench rounds (BENCH_*.json) and flag regressions.

    python scripts/bench_compare.py BENCH_r01.json BENCH_r02.json
    python scripts/bench_compare.py --threshold 0.05 --json old.json new.json

Each input is a driver round wrapper (``{"n", "cmd", "rc", "tail",
"parsed": {...}}``) or a bare bench JSON line (the ``parsed`` object
itself).  Degraded/wedge rounds are EXCLUDED from comparison rather
than compared as if they were numbers: a round with a nonzero ``rc``,
a null headline ``value``, or an ``error`` key measured the failure
mode, not the code under test.

What gets diffed:

- the headline metric (``value``, lower-is-better ms): percent delta,
  regression when the new round is slower by more than ``--threshold``
  (a fraction, default 0.10);
- per-lane p50/p95 (``classes`` from ``BENCH_WORKLOAD=mixed``), each
  lane held to the same threshold;
- phase wall-share shifts (``phase_attribution[phase].share_of_wall``),
  reported in percentage points — attribution drift is a smell, not a
  gate, so shares never trip the exit code;
- the proofs sweep (``sweep`` from ``BENCH_WORKLOAD=proofs``): per
  query-count tpu/host p50/p95, each held to the threshold like the
  headline; the multiproof dedup factor is reported-only (it is a
  property of the query shape, not a latency);
- ``vs_baseline`` (speedup vs the Go CPU baseline), reported only.

Exit codes: 0 compared, within threshold; 1 regression above
threshold; 2 not comparable (degraded round, metric mismatch,
unreadable input).
"""

from __future__ import annotations

import argparse
import json
import sys


def load_round(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    return doc


def classify(doc: dict, label: str) -> tuple[dict | None, str | None]:
    """(parsed bench object, exclusion reason).  Exactly one is None."""
    if "parsed" in doc or "rc" in doc:  # driver round wrapper
        rc = doc.get("rc", 0)
        if rc != 0:
            return None, f"{label}: rc={rc} (bench process failed)"
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            return None, f"{label}: no parsed bench line (wedged run)"
    else:
        parsed = doc
    if parsed.get("error"):
        return None, f"{label}: degraded round: {parsed['error']}"
    if parsed.get("value") is None:
        return None, f"{label}: headline value is null"
    return parsed, None


def _pct(old: float, new: float) -> float | None:
    if not old:
        return None
    return (new - old) / old


def compare(old: dict, new: dict, threshold: float) -> dict:
    """Diff two valid parsed rounds.  ``regressions`` lists every series
    that got slower than ``threshold`` allows (lower-is-better ms)."""
    report: dict = {
        "metric": old.get("metric"),
        "threshold": threshold,
        "regressions": [],
    }
    if old.get("metric") != new.get("metric"):
        report["error"] = (
            f"metric mismatch: {old.get('metric')!r} vs {new.get('metric')!r}"
        )
        return report

    d = _pct(old["value"], new["value"])
    report["headline"] = {
        "old_ms": old["value"],
        "new_ms": new["value"],
        "delta_pct": None if d is None else round(d * 100, 2),
    }
    if d is not None and d > threshold:
        report["regressions"].append(
            f"{old.get('metric')}: {old['value']} -> {new['value']} ms "
            f"({d * +100:+.1f}%)"
        )

    if old.get("vs_baseline") is not None and new.get("vs_baseline") is not None:
        report["vs_baseline"] = {
            "old": old["vs_baseline"],
            "new": new["vs_baseline"],
            "delta": round(new["vs_baseline"] - old["vs_baseline"], 3),
        }

    lanes: dict = {}
    oc, nc = old.get("classes") or {}, new.get("classes") or {}
    for lane in sorted(set(oc) & set(nc)):
        row: dict = {}
        for q in ("p50_ms", "p95_ms"):
            ov, nv = oc[lane].get(q), nc[lane].get(q)
            if ov is None or nv is None:
                continue
            dq = _pct(ov, nv)
            row[q] = {
                "old": ov,
                "new": nv,
                "delta_pct": None if dq is None else round(dq * 100, 2),
            }
            if dq is not None and dq > threshold:
                report["regressions"].append(
                    f"lane {lane} {q}: {ov} -> {nv} ({dq * 100:+.1f}%)"
                )
        if row:
            lanes[lane] = row
    if lanes:
        report["lanes"] = lanes

    if old.get("workload") == "proofs" and new.get("workload") == "proofs":
        sweep: dict = {}
        os_, ns_ = old.get("sweep") or {}, new.get("sweep") or {}
        for size in sorted(set(os_) & set(ns_), key=lambda s: int(s)):
            row = {}
            for q in ("tpu_p50_ms", "tpu_p95_ms", "host_p50_ms", "host_p95_ms"):
                ov, nv = os_[size].get(q), ns_[size].get(q)
                if ov is None or nv is None:
                    continue
                dq = _pct(ov, nv)
                row[q] = {
                    "old": ov,
                    "new": nv,
                    "delta_pct": None if dq is None else round(dq * 100, 2),
                }
                if dq is not None and dq > threshold:
                    report["regressions"].append(
                        f"proofs K={size} {q}: {ov} -> {nv} ({dq * 100:+.1f}%)"
                    )
            ov = os_[size].get("multiproof_dedup_factor")
            nv = ns_[size].get("multiproof_dedup_factor")
            if ov is not None and nv is not None:
                row["multiproof_dedup_factor"] = {
                    "old": ov, "new": nv, "delta": round(nv - ov, 2),
                }
            if row:
                sweep[size] = row
        if sweep:
            report["proofs_sweep"] = sweep

    shares: dict = {}
    oa, na = old.get("phase_attribution") or {}, new.get("phase_attribution") or {}
    for phase in sorted(set(oa) & set(na)):
        ov = (oa[phase] or {}).get("share_of_wall")
        nv = (na[phase] or {}).get("share_of_wall")
        if ov is None or nv is None:
            continue
        shares[phase] = {
            "old": ov,
            "new": nv,
            "shift_pp": round((nv - ov) * 100, 2),
        }
    if shares:
        report["phase_shares"] = shares
    return report


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="diff two bench rounds and flag regressions"
    )
    p.add_argument("old", help="baseline round (BENCH_*.json)")
    p.add_argument("new", help="candidate round (BENCH_*.json)")
    p.add_argument(
        "--threshold", type=float, default=0.10,
        help="regression threshold as a fraction (default 0.10 = 10%%)",
    )
    p.add_argument("--json", action="store_true",
                   help="print the comparison report as JSON")
    args = p.parse_args(argv)

    parsed: list[dict] = []
    for path in (args.old, args.new):
        try:
            doc = load_round(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"bench_compare: {path}: {e}", file=sys.stderr)
            return 2
        obj, reason = classify(doc, path)
        if obj is None:
            print(f"bench_compare: excluded: {reason}", file=sys.stderr)
            return 2
        parsed.append(obj)

    report = compare(parsed[0], parsed[1], args.threshold)
    if "error" in report:
        print(f"bench_compare: {report['error']}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report, indent=1))
    else:
        h = report["headline"]
        print(
            f"{report['metric']}: {h['old_ms']} -> {h['new_ms']} ms "
            f"({h['delta_pct']:+.2f}%)"
            if h["delta_pct"] is not None
            else f"{report['metric']}: {h['old_ms']} -> {h['new_ms']} ms"
        )
        if "vs_baseline" in report:
            vb = report["vs_baseline"]
            print(f"vs_baseline: {vb['old']} -> {vb['new']} ({vb['delta']:+})")
        for lane, row in report.get("lanes", {}).items():
            for q, cell in row.items():
                print(
                    f"lane {lane:>10} {q}: {cell['old']} -> {cell['new']} "
                    f"({cell['delta_pct']:+.2f}%)"
                )
        for size, row in report.get("proofs_sweep", {}).items():
            for q, cell in row.items():
                if q == "multiproof_dedup_factor":
                    print(
                        f"proofs K={size:>5} dedup: {cell['old']} -> "
                        f"{cell['new']} ({cell['delta']:+})"
                    )
                elif cell["delta_pct"] is not None:
                    print(
                        f"proofs K={size:>5} {q}: {cell['old']} -> "
                        f"{cell['new']} ({cell['delta_pct']:+.2f}%)"
                    )
        for phase, cell in report.get("phase_shares", {}).items():
            print(
                f"phase {phase:>14} share: {cell['old']:.3f} -> "
                f"{cell['new']:.3f} ({cell['shift_pp']:+.2f} pp)"
            )
        for r in report["regressions"]:
            print(f"REGRESSION: {r}", file=sys.stderr)
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
