"""BASELINE config 5: blocksync streamed replay — commits of a V-validator
set streamed to the device through the double-buffered pipeline
(cometbft_tpu/blocksync/replay.py).  Prints one JSON line with blocks/s
and sigs/s.  Reference hot path: internal/blocksync/reactor.go:547
(VerifyCommitLight per replayed block, serial on CPU: ~V * 27.5 us).

  BENCH_V       validators per commit   (default 5000)
  BENCH_BLOCKS  commits streamed        (default 64)
  BENCH_DISTINCT distinct commits to synthesize (cycled; default 8)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

V = int(os.environ.get("BENCH_V", "5000"))
BLOCKS = int(os.environ.get("BENCH_BLOCKS", "64"))
DISTINCT = int(os.environ.get("BENCH_DISTINCT", "8"))


def main() -> None:
    from cometbft_tpu.blocksync.replay import CommitStreamVerifier
    from cometbft_tpu.crypto import ed25519 as host
    from cometbft_tpu.models import comb_verifier as cv

    rng = np.random.default_rng(11)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(V)]
    pubs = [k.pub_key().data for k in keys]

    t0 = time.perf_counter()
    entry = cv.global_cache().ensure(pubs)
    build_s = time.perf_counter() - t0

    # a handful of distinct synthetic commits (distinct heights -> distinct
    # sign bytes), cycled through the stream; the device does full work per
    # block either way
    commits = []
    for h in range(DISTINCT):
        items = []
        for i, sk in enumerate(keys):
            msg = (
                b"\x08\x02\x11" + h.to_bytes(8, "little")
                + i.to_bytes(8, "big") + b"|replay-bench"
            )
            items.append((pubs[i], msg, sk.sign(msg)))
        commits.append(items)

    stream = (commits[b % DISTINCT] for b in range(BLOCKS))
    sv = CommitStreamVerifier(entry, depth=2)

    # warmup: one commit end-to-end (compile)
    for out in CommitStreamVerifier(entry, depth=1).run(iter([commits[0]])):
        assert out[0]

    t0 = time.perf_counter()
    n_ok = 0
    for all_ok, per in sv.run(stream):
        assert all_ok and len(per) == V
        n_ok += 1
    dt = time.perf_counter() - t0
    assert n_ok == BLOCKS
    print(
        json.dumps(
            {
                "metric": "blocksync_replay_blocks_per_s",
                "value": round(BLOCKS / dt, 2),
                "unit": "blocks/s",
                "v_validators": V,
                "blocks": BLOCKS,
                "sigs_per_s": round(BLOCKS * V / dt, 1),
                "table_build_s": round(build_s, 1),
                "go_cpu_baseline_blocks_per_s": round(1e6 / (V * 27.5), 2),
                "vs_baseline": round((BLOCKS / dt) * (V * 27.5) / 1e6, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
