"""Profile the flagship VerifyCommit path: host assembly vs device time."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
cache_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
import jax.numpy as jnp

from cometbft_tpu.crypto import ed25519 as host
from cometbft_tpu.ops import sha2, ed25519 as E

N = 10_000
rng = np.random.default_rng(7)
keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(N)]
items = []
for i, sk in enumerate(keys):
    msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-bench"
    items.append((sk.pub_key().data, msg, sk.sign(msg)))

# --- host assembly timing (current loop) ---
def assemble(bucket):
    a = np.zeros((bucket, 32), dtype=np.uint8)
    r = np.zeros((bucket, 32), dtype=np.uint8)
    s = np.zeros((bucket, 32), dtype=np.uint8)
    hashed = []
    for i, (pub, msg, sig) in enumerate(items):
        a[i] = np.frombuffer(pub, dtype=np.uint8)
        r[i] = np.frombuffer(sig[:32], dtype=np.uint8)
        s[i] = np.frombuffer(sig[32:], dtype=np.uint8)
        hashed.append(sig[:32] + pub + msg)
    for i in range(N, bucket):
        a[i], r[i], s[i] = a[0], r[0], s[0]
        hashed.append(hashed[0])
    blocks, active = sha2.pad_messages_sha512(hashed)
    return a, r, s, blocks, active

t0 = time.perf_counter()
a, r, s, blocks, active = assemble(16384)
t1 = time.perf_counter()
print(f"host assembly (16384 bucket): {(t1-t0)*1e3:.1f} ms", flush=True)

# --- host sha512 timing via hashlib ---
import hashlib
t0 = time.perf_counter()
digests = [hashlib.sha512(sig[:32] + pub + msg).digest() for (pub, msg, sig) in items]
t1 = time.perf_counter()
print(f"host hashlib sha512 x10k: {(t1-t0)*1e3:.1f} ms", flush=True)

fn = jax.jit(E.verify_batch)
aj, rj, sj, bj, actj = jnp.asarray(a), jnp.asarray(r), jnp.asarray(s), jnp.asarray(blocks), jnp.asarray(active)

t0 = time.perf_counter()
ok = np.asarray(fn(aj, rj, sj, bj, actj))
t1 = time.perf_counter()
print(f"first call (compile+run): {(t1-t0):.1f} s; ok={ok[:N].all()}", flush=True)

# steady state with device-resident inputs
for _ in range(2):
    fn(aj, rj, sj, bj, actj).block_until_ready()
t0 = time.perf_counter()
for _ in range(5):
    fn(aj, rj, sj, bj, actj).block_until_ready()
t1 = time.perf_counter()
print(f"device-resident kernel: {(t1-t0)/5*1e3:.1f} ms", flush=True)

# with H2D each time
t0 = time.perf_counter()
for _ in range(5):
    fn(jnp.asarray(a), jnp.asarray(r), jnp.asarray(s), jnp.asarray(blocks), jnp.asarray(active)).block_until_ready()
t1 = time.perf_counter()
print(f"H2D + kernel: {(t1-t0)/5*1e3:.1f} ms", flush=True)
print(f"input bytes: a/r/s {3*16384*32}, blocks {blocks.nbytes}, active {active.nbytes}", flush=True)

# sub-kernel split: sha512 on device vs scalar-mul
sha_fn = jax.jit(sha2.sha512_blocks)
dg = sha_fn(bj, actj); dg.block_until_ready()
t0 = time.perf_counter()
for _ in range(5):
    sha_fn(bj, actj).block_until_ready()
t1 = time.perf_counter()
print(f"device sha512 subkernel: {(t1-t0)/5*1e3:.1f} ms", flush=True)

from cometbft_tpu.ops import scalar


def scalarmul_only(a_enc, r_enc, s_bytes, k_digest):
    k_limbs = scalar.reduce_mod_l(scalar.bytes_to_limbs(k_digest, scalar.NL_X))
    k_windows = scalar.limbs_to_windows(k_limbs)
    s_windows = scalar.bytes_to_windows(s_bytes)
    s_ok = scalar.s_lt_l(s_bytes)
    return E.verify_prepared(a_enc, r_enc, s_windows, k_windows, s_ok)

sm_fn = jax.jit(scalarmul_only)
out = sm_fn(aj, rj, sj, dg); out.block_until_ready()
t0 = time.perf_counter()
for _ in range(5):
    sm_fn(aj, rj, sj, dg).block_until_ready()
t1 = time.perf_counter()
print(f"scalar-mul subkernel (incl decompress+table): {(t1-t0)/5*1e3:.1f} ms", flush=True)
