#!/usr/bin/env python
"""verifyd: the shared out-of-process verify plane.

Hosts one VerifyService (priority classes, per-tenant quotas,
weighted-fair interleave, degraded-mode failover) behind the
varint-delimited protobuf surface of cometbft_tpu/verifysvc/wire.py.
Nodes point COMETBFT_TPU_VERIFYRPC_ADDR at it; the client side
(verifysvc/remote.py) owns reconnect backoff, deadline propagation,
idempotent retry, and the circuit breaker back to the in-process host
path — so this process can be killed, stalled, or restarted at any
moment without a node losing a single verification ticket.

    python scripts/verifyd.py --addr 127.0.0.1:29170
    python scripts/verifyd.py                # ephemeral port, printed as
                                             # 'VERIFYD READY addr=...'

Service shape (quotas, batch width, deadlines) comes from the usual
COMETBFT_TPU_VERIFYSVC_* knobs in THIS process's environment — the
plane, not its clients, owns admission control.  SIGTERM/SIGINT stop it
cleanly; kill -9 is a supported operating condition.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.verifysvc.server import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
