"""Run a synthetic VerifyCommit load with span tracing ON and write a
Chrome trace-event JSON that opens in Perfetto (ui.perfetto.dev) or
chrome://tracing — the quickest way to SEE the verification pipeline
(slab fill / H2D+dispatch / device wait / collect, caller vs staging
thread) instead of inferring it from aggregate timings.

Usage:
    JAX_PLATFORMS=cpu python scripts/trace_verify_pipeline.py \
        [--validators 64] [--iters 4] [--out verify_pipeline.trace.json]

The load goes through the real seam — crypto/batch.create_batch_verifier
with the validator set's pubkeys, so large-enough sets route to the
comb-cached verifier and its pipelined submit()/collect() — exactly the
path consensus and blocksync replay drive.  tests/test_tracing.py
smoke-runs run() at a tiny scale so tier-1 catches tracer regressions.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _enable_compile_cache() -> None:
    """Share the repo's persistent XLA compile cache (same recipe as
    bench.py): cold comb/Straus compiles are minutes on a 1-core box; a
    warm cache makes the synthetic load I/O-bound instead."""
    try:
        from __graft_entry__ import _enable_compile_cache as enable

        enable()
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass


def run(
    n_validators: int = 64,
    iters: int = 4,
    out_path: str = "verify_pipeline.trace.json",
) -> dict:
    """Build one validator set, verify `iters` synthetic commits through
    the batch-verifier seam with tracing on, export the trace.  Returns
    {"path", "events", "phases"} (phases = distinct span/instant names).
    Callers that want the comb path at small scale set
    COMETBFT_TPU_COMB_MIN / COMETBFT_TPU_DEVICE_BATCH_MIN first."""
    _enable_compile_cache()
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519 as host
    from cometbft_tpu.utils import tracing

    tracing.set_enabled(True)
    tracing.reset()

    keys = [
        host.PrivKey.from_seed(bytes([40 + (i % 200)]) * 31 + bytes([i // 200]))
        for i in range(n_validators)
    ]
    pubs = [k.pub_key().data for k in keys]

    with tracing.span("trace_script.table_build"):
        crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)

    for it in range(iters):
        bv = crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
        with tracing.span("trace_script.add_loop", {"iter": it}):
            for i, sk in enumerate(keys):
                msg = b"trace-%d-%d" % (it, i)
                bv.add(pubs[i], msg, sk.sign(msg))
        ok, per_sig = bv.verify()
        assert ok and len(per_sig) == n_validators, "synthetic commit must verify"

    n_events = tracing.export_chrome_trace(out_path)
    with open(out_path) as f:
        events = json.load(f)["traceEvents"]
    phases = sorted({e["name"] for e in events if e["ph"] in ("X", "i")})
    return {"path": out_path, "events": n_events, "phases": phases}


def main(argv: list[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--out", default="verify_pipeline.trace.json")
    args = ap.parse_args(argv)
    res = run(args.validators, args.iters, args.out)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
