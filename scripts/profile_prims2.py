"""Micro-benchmarks with forced D2H readback (block_until_ready appears
unreliable through the axon tunnel for short programs)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

cache_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

from cometbft_tpu.ops import field as F

N = 16384


def bench(fn, *args, iters=5, label="", work=0.0):
    out = fn(*args)
    _ = float(np.asarray(out.ravel()[0] if hasattr(out, "ravel") else out))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
        _ = float(np.asarray(out.ravel()[0]))
    dt = (time.perf_counter() - t0) / iters
    msg = f"{label}: {dt*1e3:.2f} ms"
    if work:
        msg += f" -> {work/dt/1e9:.1f} Gop/s"
    print(msg, flush=True)
    return dt


x32 = jnp.asarray(np.random.randint(1, 1000, size=(N, 128), dtype=np.int32))

@jax.jit
def chain_i32(x):
    def body(_, a):
        return (a * a) & 0xFFFF | 1
    return lax.fori_loop(0, 1024, body, x)

bench(chain_i32, x32, label="int32 mul chain 1024x (16k,128)", work=1024*N*128)

xf = jnp.asarray(np.random.uniform(1.0, 1.001, size=(N, 128)).astype(np.float32))

@jax.jit
def chain_f32(x):
    def body(_, a):
        return a * a + 0.25
    return lax.fori_loop(0, 1024, body, x)

bench(chain_f32, xf, label="f32 fma chain 1024x (16k,128)", work=1024*N*128)

a = jnp.asarray(np.random.randn(4096, 4096).astype(np.float32))

@jax.jit
def mm(a):
    b = a
    for _ in range(8):
        b = b @ a * 1e-3
    return b

d = bench(mm, a, label="f32 matmul 8x4096^3")
print(f"  -> {8*2*4096**3/d/1e12:.1f} TFLOP/s", flush=True)

ab = jnp.asarray(np.random.randn(4096, 4096)).astype(jnp.bfloat16)

@jax.jit
def mmb(a):
    b = a
    for _ in range(8):
        b = (b @ a).astype(jnp.bfloat16) * jnp.bfloat16(1e-3)
    return b

d = bench(mmb, ab, label="bf16 matmul 8x4096^3")
print(f"  -> {8*2*4096**3/d/1e12:.1f} TFLOP/s", flush=True)

fx = jnp.asarray(np.random.randint(0, 2000, size=(N, 22), dtype=np.int32))

@jax.jit
def chain_fmul(x):
    def body(_, a):
        return F.mul(a, a)
    return lax.fori_loop(0, 256, body, x)

d = bench(chain_fmul, fx, label="field mul chain 256x (16k,22)")
print(f"  -> {d/256/N*1e9:.2f} ns/fieldmul-row; ~{256*N*484/d/1e9:.0f} G MAC/s", flush=True)
