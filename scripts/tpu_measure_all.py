"""One-shot TPU measurement session: run every round-4 benchmark in a
single process (the device tunnel serializes one client at a time and
wedges if a client is killed, so everything rides one clean process that
writes partial results as it goes and exits normally).

Writes JSON lines to /tmp/tpu_measurements.jsonl as each stage lands:
  layout      — limbs-first vs limbs-minor field-mul chain
  bench_small — verify_commit p50 at BENCH_SMALL_N (fast signal)
  bench_10k   — the flagship 10k-validator VerifyCommit p50 + phases
  blocksync   — streamed replay blocks/s (BASELINE config 5)

Run:  python scripts/tpu_measure_all.py     (full env — axon registered)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
OUT = os.environ.get("TPU_MEASURE_OUT", "/tmp/tpu_measurements.jsonl")
# measure the warm comb path: never route timed calls through the
# async-build Straus fallback
os.environ.setdefault("COMETBFT_TPU_COMB_ASYNC_MIN", str(1 << 30))
# ...and never through the link-aware small-batch host routing: this
# suite measures the DEVICE kernels (production would route sub-2048
# batches to the host through the tunnel; that trade is recorded in
# BASELINE.md, not re-measured here)
os.environ.setdefault("COMETBFT_TPU_DEVICE_BATCH_MIN", "1")


def emit(stage: str, **data) -> None:
    rec = {"stage": stage, "ts": time.time(), **data}
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def main() -> None:
    import numpy as np

    t0 = time.time()
    import jax

    devs = jax.devices()
    emit("backend", platform=devs[0].platform, init_s=round(time.time() - t0, 1))

    # persistent compile cache
    from __graft_entry__ import _enable_compile_cache

    _enable_compile_cache()

    import jax.numpy as jnp
    from jax import lax

    from cometbft_tpu.ops import field as F

    # ---- stage 1: layout micro-proof (chain of muls per layout)
    try:
        V = int(os.environ.get("LAYOUT_V", "10000"))
        CHAIN = int(os.environ.get("LAYOUT_CHAIN", "100"))
        rng = np.random.default_rng(0)
        a_np = rng.integers(0, 2048, size=(F.NLIMBS, V), dtype=np.int32)
        b_np = rng.integers(0, 2048, size=(F.NLIMBS, V), dtype=np.int32)
        a, b = jnp.asarray(a_np), jnp.asarray(b_np)

        @jax.jit
        def chain(x, y):
            return lax.fori_loop(0, CHAIN, lambda _, v: F.mul(v, y), x)

        jax.block_until_ready(chain(a, b))
        ts = []
        for _ in range(5):
            s = time.perf_counter()
            jax.block_until_ready(chain(a, b))
            ts.append(time.perf_counter() - s)
        emit(
            "layout",
            chain=CHAIN,
            chain_ms=round(1e3 * min(ts), 3),
            us_per_mul=round(1e6 * min(ts) / CHAIN, 2),
        )
    except Exception as e:  # noqa: BLE001
        emit("layout", error=str(e))

    # ---- stage 2: small bench (fast end-to-end signal before the big build)
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519 as host

    def run_bench(n: int, iters: int):
        rng = np.random.default_rng(7)
        keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(n)]
        pubs = [k.pub_key().data for k in keys]
        items = []
        for i, sk in enumerate(keys):
            msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|mb"
            items.append((pubs[i], msg, sk.sign(msg)))
        t0 = time.perf_counter()
        crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
        build_s = time.perf_counter() - t0

        def once():
            v = crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
            t0 = time.perf_counter()
            for it in items:
                v.add(*it)
            ok, per = v.verify()
            assert ok and len(per) == n
            return (time.perf_counter() - t0) * 1e3, getattr(v, "last_timings", {})

        once()
        once()
        runs = sorted((once() for _ in range(iters)), key=lambda r: r[0])
        p50, timings = runs[len(runs) // 2]
        return build_s, p50, timings

    try:
        small_n = int(os.environ.get("BENCH_SMALL_N", "1024"))
        build_s, p50, timings = run_bench(small_n, 5)
        emit(
            "bench_small",
            n=small_n,
            p50_ms=round(p50, 2),
            table_build_s=round(build_s, 1),
            **{k: round(v, 2) for k, v in timings.items()},
        )
    except Exception as e:  # noqa: BLE001
        emit("bench_small", error=str(e))

    # ---- stage 2b: VerifyCommitLight @ 150 validators through
    # types/validation.py (BASELINE config 2: the light-client shape) —
    # the REAL path: sign-bytes assembly, power tally, comb verify
    try:
        from cometbft_tpu.types import validation as val
        from cometbft_tpu.types.block import BlockID, Commit, CommitSig, PartSetHeader
        from cometbft_tpu.types.validators import Validator, ValidatorSet
        from cometbft_tpu.types.vote import Vote
        from cometbft_tpu.wire.canonical import PRECOMMIT_TYPE, Timestamp

        nv = 150
        rng = np.random.default_rng(3)
        vkeys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(nv)]
        vals150 = ValidatorSet(
            [Validator(k.pub_key(), 10) for k in vkeys]
        )
        bid = BlockID(
            hash=b"\x42" * 32,
            part_set_header=PartSetHeader(total=1, hash=b"\x24" * 32),
        )
        ts = Timestamp(seconds=1_700_000_000)
        by_addr = {k.pub_key().address(): k for k in vkeys}
        sigs = []
        for i, v in enumerate(vals150.validators):  # set order is sorted
            vote = Vote(
                type=PRECOMMIT_TYPE, height=9, round=0, block_id=bid,
                timestamp=ts, validator_address=v.address, validator_index=i,
            )
            sig = by_addr[v.address].sign(vote.sign_bytes("bench-light"))
            sigs.append(
                CommitSig(
                    block_id_flag=2, validator_address=v.address,
                    timestamp=ts, signature=sig,
                )
            )
        commit150 = Commit(height=9, round=0, block_id=bid, signatures=sigs)
        os.environ["COMETBFT_TPU_COMB_MIN"] = "64"  # route 150 to the comb
        val.verify_commit_light("bench-light", vals150, bid, 9, commit150)
        runs = []
        for _ in range(10):
            t0 = time.perf_counter()
            val.verify_commit_light(
                "bench-light", vals150, bid, 9, commit150,
                count_all_signatures=True,
            )
            runs.append((time.perf_counter() - t0) * 1e3)
        runs.sort()
        emit(
            "light_150",
            p50_ms=round(runs[len(runs) // 2], 2),
            vs_go_cpu=round(150 * 27.5e-3 / runs[len(runs) // 2], 2),
        )
    except Exception as e:  # noqa: BLE001
        emit("light_150", error=str(e))

    # ---- stage 3: the flagship 10k (TPU_MEASURE_SKIP_10K=1 to skip —
    # a 10k table build on the CPU backend is hours)
    if os.environ.get("TPU_MEASURE_SKIP_10K") == "1":
        emit("bench_10k", skipped=True)
    else:
      try:
        build_s, p50, timings = run_bench(10_000, 10)
        emit(
            "bench_10k",
            n=10_000,
            p50_ms=round(p50, 2),
            vs_go_cpu=round(275.0 / p50, 2),
            table_build_s=round(build_s, 1),
            **{k: round(v, 2) for k, v in timings.items()},
        )
      except Exception as e:  # noqa: BLE001
        emit("bench_10k", error=str(e))

    # ---- stage 3b: incremental churn on the 10k set (round-5 verdict
    # item 2: table ready fast after 1% churn; the full build is the
    # r3-measured ~300 s pain point)
    if os.environ.get("TPU_MEASURE_SKIP_10K") != "1":
      try:
        from cometbft_tpu.models import comb_verifier as cv

        rng = np.random.default_rng(7)
        keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(10_000)]
        pubs = [k.pub_key().data for k in keys]  # same set as bench_10k
        cache = cv.global_cache()
        cache.ensure(pubs)  # warm (already built by stage 3)
        for frac, nch in (("1pct", 100), ("10pct", 1000)):
            fresh = [
                host.PrivKey.from_seed(
                    (b"churn" + i.to_bytes(4, "big")).rjust(32, b"\x00")
                ).pub_key().data
                for i in range(nch)
            ]
            churned = pubs[nch:] + fresh
            t0 = time.perf_counter()
            cache.ensure(churned)
            emit(
                "churn",
                frac=frac,
                changed=nch,
                build_s=round(time.perf_counter() - t0, 2),
            )
      except Exception as e:  # noqa: BLE001
        emit("churn", error=str(e))

    # ---- stage 4: blocksync streamed replay (5k validators)
    try:
        from cometbft_tpu.blocksync.replay import CommitStreamVerifier
        from cometbft_tpu.models import comb_verifier as cv

        Vv = int(os.environ.get("BENCH_V", "5000"))
        blocks = int(os.environ.get("BENCH_BLOCKS", "64"))
        rng = np.random.default_rng(11)
        keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(Vv)]
        pubs = [k.pub_key().data for k in keys]
        t0 = time.perf_counter()
        entry = cv.global_cache().ensure(pubs)
        build_s = time.perf_counter() - t0
        commits = []
        for h in range(4):
            items = []
            for i, sk in enumerate(keys):
                msg = (
                    b"\x08\x02\x11" + h.to_bytes(8, "little")
                    + i.to_bytes(8, "big") + b"|replay"
                )
                items.append((pubs[i], msg, sk.sign(msg)))
            commits.append(items)
        for out in CommitStreamVerifier(entry, depth=1).run(iter([commits[0]])):
            assert out[0]
        t0 = time.perf_counter()
        nok = 0
        for all_ok, per in CommitStreamVerifier(entry, depth=2).run(
            commits[b % 4] for b in range(blocks)
        ):
            assert all_ok
            nok += 1
        dt = time.perf_counter() - t0
        assert nok == blocks, f"pipeline yielded {nok}/{blocks}"
        emit(
            "blocksync",
            v=Vv,
            blocks=blocks,
            blocks_per_s=round(blocks / dt, 2),
            sigs_per_s=round(blocks * Vv / dt, 1),
            table_build_s=round(build_s, 1),
            vs_go_cpu=round((blocks / dt) * (Vv * 27.5) / 1e6, 2),
        )
    except Exception as e:  # noqa: BLE001
        emit("blocksync", error=str(e))

    emit("done")


if __name__ == "__main__":
    main()
