"""Disambiguate the comb kernel's ~270 ms V-independent cost: tunnel RPC
latency vs H2D/D2H bandwidth vs deferred device compute.

The r5 phase profile (profile_comb_phases.py on the v5e) showed the FULL
verify_cached at V=1024 taking ~0.0 ms steady-state on device-resident
inputs with block_until_ready, while the end-to-end bench measures
353 ms at the same V.  Either the per-call cost is entirely in the
host<->device path (the axon tunnel), or block_until_ready does not
actually wait under axon and compute happens at fetch time.  This script
separates the terms:

  ping        - trivial jit (x+1 on 8 floats) + 1-element fetch
  h2d_*       - jnp.asarray of N bytes + block
  d2h_*       - np.asarray fetch of a device array of N bytes
  block_vs_fetch - heavy kernel (100k field muls): time block_until_ready
                   separately from the subsequent 4-byte fetch.  If block
                   is ~0 and fetch carries the cost, block lies.

Emits one JSON line per measurement (p50 of 10 runs after 2 warmups).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def emit(**kw):
    print(json.dumps(kw), flush=True)


def p50(f, n=10, warmup=2):
    for _ in range(warmup):
        f()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        f()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return 1e3 * ts[len(ts) // 2]


def main():
    dev = jax.devices()[0]
    emit(stage="backend", platform=dev.platform)

    # --- ping: minimal jit + minimal fetch
    tiny = jnp.ones((8,), jnp.float32)
    inc = jax.jit(lambda x: x + 1)
    inc(tiny).block_until_ready()
    emit(stage="ping_block", ms=round(p50(lambda: inc(tiny).block_until_ready()), 2))
    emit(stage="ping_fetch", ms=round(p50(lambda: np.asarray(inc(tiny))), 2))

    # --- H2D bandwidth
    for nbytes in (32 << 10, 2 << 20, 16 << 20):
        host = np.zeros(nbytes, np.uint8)
        ms = p50(lambda: jnp.asarray(host).block_until_ready())
        emit(stage="h2d", nbytes=nbytes, ms=round(ms, 2),
             mb_s=round(nbytes / 1e6 / (ms / 1e3), 1))

    # --- D2H bandwidth
    for nbytes in (1 << 10, 1 << 20, 16 << 20):
        devarr = jnp.zeros(nbytes, jnp.uint8)
        devarr.block_until_ready()
        ms = p50(lambda: np.asarray(devarr))
        emit(stage="d2h", nbytes=nbytes, ms=round(ms, 2),
             mb_s=round(nbytes / 1e6 / (ms / 1e3), 1))

    # --- does block_until_ready actually wait?
    from cometbft_tpu.ops import field as F

    x = jnp.ones((F.NLIMBS, 8192), jnp.int32)

    @jax.jit
    def heavy(a):
        return lax.fori_loop(0, 100_000, lambda _, v: F.mul(v, a), a)[0, 0]

    heavy(x).block_until_ready()
    t0 = time.perf_counter()
    out = heavy(x)
    dispatch_ms = 1e3 * (time.perf_counter() - t0)
    t0 = time.perf_counter()
    out.block_until_ready()
    block_ms = 1e3 * (time.perf_counter() - t0)
    t0 = time.perf_counter()
    np.asarray(out)
    fetch_ms = 1e3 * (time.perf_counter() - t0)
    emit(stage="block_vs_fetch", dispatch_ms=round(dispatch_ms, 2),
         block_ms=round(block_ms, 2), fetch_after_block_ms=round(fetch_ms, 2))

    # --- dtype: does per-element overhead exist? (same 2 MB, 4x fewer els)
    for dt, n in ((np.uint8, 2 << 20), (np.int32, (2 << 20) // 4)):
        host = np.zeros(n, dt)
        ms = p50(lambda: jnp.asarray(host).block_until_ready())
        emit(stage="h2d_dtype", dtype=np.dtype(dt).name, nbytes=int(host.nbytes),
             ms=round(ms, 2))

    # --- device_put vs asarray
    host = np.zeros(2 << 20, np.uint8)
    ms = p50(lambda: jax.device_put(host).block_until_ready())
    emit(stage="h2d_device_put", nbytes=2 << 20, ms=round(ms, 2))

    # --- do concurrent H2D transfers overlap?
    import threading

    def pair():
        h1 = np.zeros(1 << 20, np.uint8)
        h2 = np.ones(1 << 20, np.uint8)
        out = [None, None]

        def send(i, h):
            out[i] = jnp.asarray(h)

        t1 = threading.Thread(target=send, args=(0, h1))
        t2 = threading.Thread(target=send, args=(1, h2))
        t1.start(); t2.start(); t1.join(); t2.join()
        out[0].block_until_ready()
        out[1].block_until_ready()

    emit(stage="h2d_2x1mb_concurrent", ms=round(p50(pair), 2))
    host2 = np.zeros(2 << 20, np.uint8)
    emit(stage="h2d_1x2mb_serial", ms=round(
        p50(lambda: jnp.asarray(host2).block_until_ready()), 2))

    # --- one fetch vs two fetches of small results
    small1 = jax.jit(lambda x: (x[:1250], x[1250] > 0))(jnp.zeros(4096, jnp.uint8))
    jax.tree_util.tree_map(lambda x: x.block_until_ready(), small1)

    @jax.jit
    def two_out(x):
        return x[:1250], x[1250] > 0

    @jax.jit
    def one_out(x):
        return x[:1251]

    zin = jnp.zeros(4096, jnp.uint8)
    zin.block_until_ready()

    def fetch_two():
        a, b = two_out(zin)
        np.asarray(a); np.asarray(b)

    def fetch_one():
        np.asarray(one_out(zin))

    emit(stage="fetch_two_results", ms=round(p50(fetch_two), 2))
    emit(stage="fetch_one_result", ms=round(p50(fetch_one), 2))

    # --- end-to-end shape of one bench call, decomposed (V=10000 rows)
    V = 10_000
    packed = np.zeros((V, 192), np.uint8)

    @jax.jit
    def touch(p):
        return jnp.packbits(p[:, 0] > 0), jnp.all(p[:, 0] >= 0)

    b, a = touch(jnp.asarray(packed))
    b.block_until_ready()

    def call():
        b, a = touch(jnp.asarray(packed))
        b.block_until_ready()
        np.asarray(b)
        np.asarray(a)

    emit(stage="call_trivial_10k", ms=round(p50(call), 2))
    emit(stage="done")


if __name__ == "__main__":
    main()
