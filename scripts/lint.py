#!/usr/bin/env python
"""Repo linter entry point — the `go vet` of this codebase.

    python scripts/lint.py [paths...] [--json] [--list-checks]
                           [--check ID ...]
    python scripts/lint.py regen-fingerprints
    python scripts/lint.py regen-shardings
    python scripts/lint.py regen-ranges

Runs every check in cometbft_tpu/analysis over the given paths (default:
the cometbft_tpu package), filters through the checked-in allowlist
(cometbft_tpu/analysis/allowlist.txt), and exits non-zero when any
non-allowlisted finding remains.  Stale allowlist entries are reported
on stderr (and under "stale_allowlist" in --json) but don't fail the
run.

--check restricts the run to the named check id(s).  The special id
``kernel`` selects the kernel contract gate: the three kernel-plane AST
checks (untracked-jit, host-sync-in-hot-path, weak-type-literal) PLUS
the kernelcheck trace pass — every manifest kernel abstract-interpreted
under JAX_PLATFORMS=cpu and diffed against the checked-in fingerprints
(docs/kernel_contracts.md).  ``regen-fingerprints`` re-traces everything
and rewrites cometbft_tpu/analysis/kernel_fingerprints.json after a
DELIBERATE kernel change (contract violations still refuse).

The special id ``sharding`` selects the sharded-program contract gate
(docs/sharding_contracts.md): the donated-read-after-dispatch AST check
PLUS the shardcheck trace pass — every mesh-parameterized kernel traced
under a REAL 8-way CPU mesh in a forced-environment subprocess
(XLA_FLAGS=--xla_force_host_platform_device_count=8, JAX_PLATFORMS=cpu,
works on CPU-only hosts) and held to its declared shardings, collective
census, compile-cost budgets, donation discipline, and the checked-in
cometbft_tpu/analysis/shard_fingerprints.json goldens.
``regen-shardings`` re-traces and rewrites the goldens; open contract
findings refuse regeneration — blessing drift never blesses a broken
contract.

The special id ``range`` selects the limb-range contract gate
(docs/limb_headroom.md): the unchecked-shift-width AST check PLUS the
rangecheck interval pass — every manifest kernel abstract-interpreted
over declared input ranges, every intermediate held to its dtype's safe
range (int32 magnitude, the 2^24 f32-exact threshold), declared output
ranges enforced, and the result diffed against the checked-in
cometbft_tpu/analysis/range_fingerprints.json certificates.
``regen-ranges`` re-interprets and rewrites the certificates; open
overflow findings refuse regeneration.

The special id ``taint`` selects the Byzantine-input contract gate
(docs/byzantine_inputs.md): the unbounded-wire-length AST check PLUS
the taintcheck dataflow pass — every decode surface diffed against
taint_manifest.DECODE_SITES in both directions, and every declared
source abstract-interpreted over a taint lattice to prove no untrusted
value reaches a consensus/state/store/dispatch sink without a declared
sanitizer on the path.

Check toggles live in pyproject.toml:

    [tool.cometbft-tpu-lint]
    disable = ["check-id", ...]
    allowlist = "cometbft_tpu/analysis/allowlist.txt"

The gate test (tests/test_static_analysis.py) runs the same machinery,
so a finding that would fail this script also fails the tier-1 suite.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.analysis import linter  # noqa: E402

try:
    import tomllib
except ImportError:  # py3.10 host: the repo's minimal reader
    from cometbft_tpu.utils import minitoml as tomllib


def load_config(pyproject: str) -> dict:
    """The [tool.cometbft-tpu-lint] table, {} when absent.  Handles both
    real tomllib nesting and minitoml's flat dotted-header tables."""
    try:
        with open(pyproject, "rb") as f:
            data = tomllib.load(f)
    except (FileNotFoundError, ValueError):
        return {}
    flat = data.get("tool.cometbft-tpu-lint")
    if isinstance(flat, dict):
        return flat
    nested = data.get("tool", {}).get("cometbft-tpu-lint")
    return nested if isinstance(nested, dict) else {}


def regen_fingerprints() -> int:
    """Re-trace every manifest kernel and rewrite the golden file."""
    from cometbft_tpu.analysis import kernelcheck

    findings, traces = kernelcheck.regenerate()
    for f in findings:
        print(f.render())
    if findings:
        print(
            f"\n{len(findings)} contract finding(s) — regeneration only "
            "blesses drift, never a broken contract; goldens NOT written",
            file=sys.stderr,
        )
        return 1
    print(
        f"traced {len(traces)} kernels -> {kernelcheck.FINGERPRINTS_PATH}"
    )
    return 0


def regen_shardings() -> int:
    """Re-trace every sharded manifest kernel in the forced 8-device
    child and rewrite the shard goldens."""
    from cometbft_tpu.analysis import shardcheck

    findings, data = shardcheck.run_subprocess(regen=True)
    for f in findings:
        print(f.render())
    if findings or not data.get("regen_written"):
        print(
            f"\n{len(findings)} contract finding(s) — regeneration only "
            "blesses drift, never a broken contract; shard goldens NOT "
            "written",
            file=sys.stderr,
        )
        return 1
    print(
        f"traced {len(data.get('kernels', {}))} sharded kernels on "
        f"{data.get('device_count')} devices -> "
        f"{shardcheck.SHARD_FINGERPRINTS_PATH}"
    )
    return 0


def regen_ranges() -> int:
    """Re-interpret every manifest kernel and rewrite the range
    certificates."""
    from cometbft_tpu.analysis import rangecheck

    findings, reports = rangecheck.regenerate()
    for f in findings:
        print(f.render())
    if findings:
        print(
            f"\n{len(findings)} range finding(s) — regeneration only "
            "blesses drift, never an open overflow; certificates NOT "
            "written",
            file=sys.stderr,
        )
        return 1
    print(
        f"interpreted {len(reports)} kernels -> "
        f"{rangecheck.RANGE_FINGERPRINTS_PATH}"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "regen-fingerprints":
        return regen_fingerprints()
    if argv and argv[0] == "regen-shardings":
        return regen_shardings()
    if argv and argv[0] == "regen-ranges":
        return regen_ranges()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument("--list-checks", action="store_true")
    ap.add_argument(
        "--check",
        action="append",
        metavar="ID",
        help="restrict to the given check id(s); 'kernel' = the three "
        "kernel-plane AST checks + the kernelcheck trace/fingerprint gate; "
        "'sharding' = the 8-device shardcheck gate; 'range' = the "
        "unchecked-shift-width AST check + the rangecheck interval gate; "
        "'taint' = the unbounded-wire-length AST check + the taintcheck "
        "Byzantine-input dataflow gate",
    )
    ap.add_argument(
        "--config",
        default=os.path.join(repo_root, "pyproject.toml"),
        help="pyproject.toml with [tool.cometbft-tpu-lint]",
    )
    ap.add_argument(
        "--allowlist",
        default=None,
        help="override the allowlist path (config/default otherwise)",
    )
    args = ap.parse_args(argv)

    checks = linter.all_checks()
    all_ids = set(checks)
    if args.list_checks:
        for cid, m in checks.items():
            print(f"{cid}: {m.SUMMARY}")
        print("kernel: the kernel contract gate (kernel AST checks + "
              "kernelcheck trace/fingerprint pass)")
        print("sharding: the sharded-program contract gate (donated-read "
              "AST check + 8-device shardcheck trace/golden pass)")
        print("range: the limb-range contract gate (unchecked-shift-width "
              "AST check + rangecheck interval/certificate pass)")
        print("taint: the Byzantine-input contract gate (unbounded-wire-"
              "length AST check + taintcheck decode-surface/dataflow pass)")
        return 0

    run_trace = False
    run_shard_trace = False
    run_range_trace = False
    run_taint_trace = False
    if args.check:
        ids: list[str] = []
        for c in args.check:
            if c == "kernel":
                run_trace = True
                ids.extend(linter.KERNEL_CHECK_IDS)
            elif c == "sharding":
                run_shard_trace = True
                ids.extend(linter.SHARDING_CHECK_IDS)
            elif c == "range":
                run_range_trace = True
                ids.extend(linter.RANGE_CHECK_IDS)
            elif c == "taint":
                run_taint_trace = True
                ids.extend(linter.TAINT_CHECK_IDS)
            else:
                ids.append(c)
        unknown_ids = set(ids) - set(checks)
        if unknown_ids:
            print(f"unknown check(s): {sorted(unknown_ids)}", file=sys.stderr)
            return 2
        checks = {cid: m for cid, m in checks.items() if cid in set(ids)}

    cfg = load_config(args.config)
    disable = set(cfg.get("disable", ()))
    unknown = disable - all_ids  # not the --check-restricted subset
    if unknown:
        print(f"config disables unknown check(s): {sorted(unknown)}",
              file=sys.stderr)
        return 2
    allowlist_path = args.allowlist or cfg.get(
        "allowlist", linter.default_allowlist_path()
    )
    if not os.path.isabs(allowlist_path) and not os.path.exists(allowlist_path):
        allowlist_path = os.path.join(repo_root, allowlist_path)

    paths = args.paths or [os.path.join(repo_root, "cometbft_tpu")]
    allowlist = linter.Allowlist.load(allowlist_path)
    try:
        findings, stale = linter.lint_paths(
            paths, checks=checks, allowlist=allowlist, disable=disable
        )
    except FileNotFoundError as e:
        # a typo'd path linting zero files must not read as a clean pass
        print(str(e), file=sys.stderr)
        return 2

    kernel_summary = None
    if run_trace:
        from cometbft_tpu.analysis import kernelcheck

        kfindings, traces = kernelcheck.run_check()
        kfindings = [f for f in kfindings if not allowlist.suppresses(f)]
        findings = findings + kfindings
        kernel_summary = kernelcheck.summary(kfindings, traces)
        stale = allowlist.unused()  # kernel findings may have used entries

    range_summary = None
    if run_range_trace:
        from cometbft_tpu.analysis import rangecheck

        rfindings, reports = rangecheck.run_check()
        rfindings = [f for f in rfindings if not allowlist.suppresses(f)]
        findings = findings + rfindings
        range_summary = rangecheck.summary(rfindings, reports)
        stale = allowlist.unused()

    taint_summary = None
    if run_taint_trace:
        from cometbft_tpu.analysis import taintcheck

        tfindings, treport = taintcheck.run_check()
        tfindings = [f for f in tfindings if not allowlist.suppresses(f)]
        findings = findings + tfindings
        taint_summary = taintcheck.summary(tfindings, treport)
        stale = allowlist.unused()

    shard_summary = None
    if run_shard_trace:
        from cometbft_tpu.analysis import shardcheck

        # the trace runs in a forced-environment child (8 CPU devices)
        # so this works on CPU-only hosts and never touches a wedged
        # accelerator tunnel; the child reports RAW findings and the
        # allowlist — including an --allowlist/--config override — is
        # applied here only, so used/stale entry bookkeeping stays exact
        sfindings, shard_summary = shardcheck.run_subprocess()
        sfindings = [f for f in sfindings if not allowlist.suppresses(f)]
        findings = findings + sfindings
        # the child's "ok" predates the allowlist; recompute both fields
        # post-filter so a blessed state reads green here too
        shard_summary = {
            **shard_summary, "ok": not sfindings, "findings": len(sfindings),
        }
        stale = allowlist.unused()

    if args.check:
        # a restricted run must not call entries for checks that never
        # ran "stale" — only full runs can prove an entry matches nothing
        enabled_ids = set(checks)
        if run_trace:
            from cometbft_tpu.analysis import kernelcheck

            enabled_ids |= set(kernelcheck.FINDING_CHECK_IDS)
        if run_shard_trace:
            from cometbft_tpu.analysis import shardcheck

            enabled_ids |= set(shardcheck.FINDING_CHECK_IDS)
        if run_range_trace:
            from cometbft_tpu.analysis import rangecheck

            enabled_ids |= set(rangecheck.FINDING_CHECK_IDS)
        if run_taint_trace:
            from cometbft_tpu.analysis import taintcheck

            enabled_ids |= set(taintcheck.FINDING_CHECK_IDS)
        stale = [e for e in stale if e.check in enabled_ids]

    if args.json:
        print(json.dumps(
            {
                "findings": [
                    {
                        "check": f.check, "path": f.path, "line": f.line,
                        "col": f.col, "message": f.message,
                    }
                    for f in findings
                ],
                "stale_allowlist": [
                    {"check": e.check, "path": e.path, "line": e.line,
                     "allowlist_line": e.lineno}
                    for e in stale
                ],
                "ok": not findings,
                **({"kernel": kernel_summary} if kernel_summary else {}),
                **({"sharding": shard_summary} if shard_summary else {}),
                **({"range": range_summary} if range_summary else {}),
                **({"taint": taint_summary} if taint_summary else {}),
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(
                f"stale allowlist entry (line {e.lineno}): {e.check} "
                f"{e.path}{':' + str(e.line) if e.line else ''} — "
                "matched nothing; remove it",
                file=sys.stderr,
            )
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
