#!/usr/bin/env python
"""Multi-tenant verify-plane soak driver: sustained mixed load from M
in-process chains over ONE shared verify service, a rogue tenant's
mempool flood, and mid-soak fault injections (device-wedge failover
cycles; optionally a full chaos scenario — node crash + WAL replay — as
a concurrent subprocess), with a machine-readable per-tenant SLO
artifact asserting no starvation, quota isolation, no leak, no drift,
and fault endurance (cometbft_tpu/e2e/soak.py).

    python scripts/soak.py                              # 5 min, 3 tenants
    python scripts/soak.py --duration 3600 --tenants 8  # the long haul
    python scripts/soak.py --duration 30 --no-chaos --json out/soak.json
    python scripts/soak.py --smoke                      # tier-1 shape, ~10 s
    python scripts/soak.py --remote-plane               # out-of-process
                                                        # verifyd, kill -9'd
                                                        # and revived mid-soak

``--remote-plane`` spawns a verifyd subprocess and routes every
tenant's batches over the RPC surface (verifysvc/remote.py): quotas
are enforced server-side, each mid-soak fault cycle kill -9s the plane
with batches in flight (breaker trip -> host fallback -> restart ->
probation restore), and the default concurrent chaos scenario becomes
``plane_crash`` — REAL node processes sharing their own verifyd that
dies and returns mid-height.

Exit status: 0 iff every SLO assertion held.  ``--json`` (default
``out/soak.json``) writes the full report; the assertions block is also
printed to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    # CPU determinism + warm compile cache for any real-plane run and
    # for the chaos subprocess's nodes (same reasoning as chaos.py:
    # setdefault so an operator's environment always wins; chaos-private
    # cache dir so a kill -9-torn write can't corrupt tier-1's cache)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "COMETBFT_TPU_COMPILE_CACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", ".jax_cache_chaos",
        ),
    )
    from cometbft_tpu.e2e.soak import SoakConfig, run_soak

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tenants", type=int, default=3)
    p.add_argument("--validators", type=int, default=16,
                   help="validator-set size per chain (commit width)")
    p.add_argument("--duration", type=float, default=300.0,
                   help="soak length in seconds (default 300 = 5 min)")
    p.add_argument("--seed", type=int, default=7,
                   help="deterministic workload seed (keys, tamper pattern)")
    p.add_argument("--rogue", default="",
                   help="tenant that floods (default: the last chain)")
    p.add_argument("--flood-senders", type=int, default=3)
    p.add_argument("--flood-batch-sigs", type=int, default=8)
    p.add_argument("--quota", type=int, default=128,
                   help="per-(tenant, class) signature quota")
    p.add_argument("--wedge-cycles", type=int, default=2,
                   help="mid-soak device-wedge failover cycles")
    p.add_argument("--plane", choices=("fake", "real"), default="fake",
                   help="data plane: fake = deterministic CPU device "
                        "(production scheduling, host crypto), real = "
                        "the jitted kernels")
    p.add_argument("--chaos-scenario", action="append", default=[],
                   help="chaos scenario(s) to run as concurrent "
                        "subprocesses mid-soak (repeatable); default "
                        "crash_replay unless --no-chaos")
    p.add_argument("--no-chaos", action="store_true",
                   help="skip the concurrent chaos subprocess")
    p.add_argument("--starvation-factor", type=float, default=2.0)
    p.add_argument("--starvation-floor-ms", type=float, default=0.0)
    p.add_argument("--json", default="out/soak.json",
                   help="SLO artifact path ('' disables)")
    p.add_argument("--out", default="",
                   help="artifact dir for forensics/chaos (default: tmp)")
    p.add_argument("--base-port", type=int, default=29400,
                   help="base port for the chaos subprocess's nodes")
    p.add_argument("--smoke", action="store_true",
                   help="the fast tier-1 shape: 2 tenants, ~10 s, one "
                        "wedge cycle, no chaos subprocess")
    p.add_argument("--remote-plane", action="store_true",
                   help="spawn a verifyd subprocess and run the soak "
                        "over the RPC surface; fault cycles kill -9 the "
                        "plane instead of wedging a fake device")
    p.add_argument("--verifyd-port", type=int, default=29900,
                   help="port the spawned verifyd listens on (0 = "
                        "ephemeral)")
    args = p.parse_args(argv)

    if args.smoke:
        cfg = SoakConfig(
            tenants=2, validators_per_chain=4, duration_s=10.0,
            seed=args.seed, flood_senders=2, flood_batch_sigs=8,
            tenant_quota=48, wedge_cycles=1, wedge_hold_s=1.0,
            probation_ok=2, probe_period_s=0.1, batch_deadline_s=0.5,
            starvation_floor_ms=max(args.starvation_floor_ms, 250.0),
            leak_check=False, commit_pause_s=0.02, checktx_period_s=0.1,
            artifact_dir=args.out, json_path=args.json,
            remote_plane=args.remote_plane, verifyd_port=args.verifyd_port,
        )
    else:
        chaos = tuple(args.chaos_scenario) or (
            () if args.no_chaos
            else (("plane_crash",) if args.remote_plane else ("crash_replay",))
        )
        cfg = SoakConfig(
            tenants=args.tenants,
            validators_per_chain=args.validators,
            duration_s=args.duration,
            seed=args.seed,
            rogue=args.rogue,
            flood_senders=args.flood_senders,
            flood_batch_sigs=args.flood_batch_sigs,
            tenant_quota=args.quota,
            wedge_cycles=args.wedge_cycles,
            data_plane=args.plane,
            starvation_factor=args.starvation_factor,
            starvation_floor_ms=args.starvation_floor_ms,
            chaos_scenarios=chaos,
            chaos_base_port=args.base_port,
            artifact_dir=args.out,
            json_path=args.json,
            remote_plane=args.remote_plane,
            verifyd_port=args.verifyd_port,
        )

    report = run_soak(cfg)
    print(json.dumps(
        {"ok": report["ok"], "duration_s": report["duration_s"],
         "assertions": report["assertions"]},
        indent=1, default=str,
    ))
    if args.json:
        print(f"soak: full SLO artifact at {args.json}", file=sys.stderr)
    print(
        f"soak: {'PASS' if report['ok'] else 'FAIL'} "
        f"({report['duration_s']}s, {cfg.tenants} tenants, "
        f"{len(report['assertions'])} assertions)",
        file=sys.stderr,
    )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
