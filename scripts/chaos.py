#!/usr/bin/env python
"""Chaos scenario driver: run the named e2e fault scenarios
(cometbft_tpu/e2e/scenarios.py) and emit a machine-readable pass/fail
artifact per scenario.

    python scripts/chaos.py                      # the 5 full scenarios
    python scripts/chaos.py --scenario wedge --scenario double_sign
    python scripts/chaos.py --smoke              # fast single-node smoke
    python scripts/chaos.py --json out/chaos.json --out out/artifacts
    python scripts/chaos.py --list

Exit status: 0 iff every selected scenario passed.  ``--json`` writes
``{"ok": bool, "scenarios": [ScenarioResult...]}``; each scenario also
leaves a per-node artifact directory (flight-recorder dump, health
snapshot, verify-service stats, node logs) under ``--out`` so a failed
run is diagnosable without a rerun.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    # Persistent XLA compile cache for every node the scenarios spawn
    # (children inherit the env; `python -m cometbft_tpu` calls
    # utils/compilecache.maybe_enable at startup): repeated chaos runs
    # stop paying the kernel recompiles.  setdefault — an operator's
    # COMETBFT_TPU_COMPILE_CACHE always wins.  The dir is chaos-private
    # (not tests/.jax_cache): these scenarios kill -9 nodes mid-flight,
    # and a write torn by a kill must never be able to corrupt the
    # tier-1 suite's shared cache (a corrupt entry can crash jax's
    # cache read path).
    os.environ.setdefault(
        "COMETBFT_TPU_COMPILE_CACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", ".jax_cache_chaos",
        ),
    )
    from cometbft_tpu.e2e import scenarios as sc

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--scenario", action="append", default=[],
        help="scenario name (repeatable); default: the 5 full scenarios",
    )
    p.add_argument(
        "--smoke", action="store_true",
        help="run only the fast single-node wedge_smoke",
    )
    p.add_argument("--list", action="store_true", help="list scenarios and exit")
    p.add_argument("--json", default="", help="write the machine-readable verdict here")
    p.add_argument("--out", default="", help="artifact directory (default: a tmp dir)")
    p.add_argument(
        "--base-port", type=int, default=0,
        help="override the per-scenario default port ranges",
    )
    args = p.parse_args(argv)

    if args.list:
        for name in sc.SCENARIOS:
            print(name)
        return 0

    names = args.scenario or (
        ["wedge_smoke"] if args.smoke else list(sc.DEFAULT_SCENARIOS)
    )
    unknown = [n for n in names if n not in sc.SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {', '.join(unknown)}", file=sys.stderr)
        print(f"known: {', '.join(sc.SCENARIOS)}", file=sys.stderr)
        return 2

    out_dir = args.out or tempfile.mkdtemp(prefix="cometbft-chaos-")
    os.makedirs(out_dir, exist_ok=True)

    results = []
    t0 = time.monotonic()
    for i, name in enumerate(names):
        base_port = (args.base_port + i * 200) if args.base_port else None
        res = sc.run_scenario(name, out_dir, base_port=base_port)
        results.append(res)
        print(json.dumps(res.to_dict()), flush=True)  # one line per scenario

    verdict = {
        "ok": all(r.ok for r in results),
        "elapsed_s": round(time.monotonic() - t0, 1),
        "artifact_dir": out_dir,
        "scenarios": [r.to_dict() for r in results],
    }
    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)), exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(verdict, f, indent=1)
    print(
        f"chaos: {sum(r.ok for r in results)}/{len(results)} scenarios passed "
        f"in {verdict['elapsed_s']}s (artifacts: {out_dir})",
        file=sys.stderr,
    )
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
