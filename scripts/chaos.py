#!/usr/bin/env python
"""Chaos scenario driver: run the named e2e fault scenarios
(cometbft_tpu/e2e/scenarios.py) and emit a machine-readable pass/fail
artifact per scenario.

    python scripts/chaos.py                      # the 5 full scenarios
    python scripts/chaos.py --scenario wedge --scenario double_sign
    python scripts/chaos.py --smoke              # fast single-node smoke
    python scripts/chaos.py --json out/chaos.json --out out/artifacts
    python scripts/chaos.py --repeat 3 --seed 42 # deterministic cycling
    python scripts/chaos.py --list

Exit status: 0 iff every selected scenario passed; 1 when one or more
scenarios ran and FAILED their assertions; 3 when one or more scenarios
CRASHED (raised — a harness/environment breakage, not a chaos verdict).
The distinction lets a driver (the soak harness, CI retry logic) treat
"the network forked" differently from "the runner threw".

``--repeat N`` runs the selected scenario list N times (ports offset
per iteration so iterations never collide) and ``--seed`` pins the
deterministic load-round numbering — together they make scenarios
reusable as repeated mid-soak fault injections.  ``--json`` writes
``{"ok": bool, "scenarios": [ScenarioResult...]}``; each scenario also
leaves a per-node artifact directory (flight-recorder dump, health
snapshot, verify-service stats, node logs) under ``--out`` so a failed
run is diagnosable without a rerun.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    # Persistent XLA compile cache for every node the scenarios spawn
    # (children inherit the env; `python -m cometbft_tpu` calls
    # utils/compilecache.maybe_enable at startup): repeated chaos runs
    # stop paying the kernel recompiles.  setdefault — an operator's
    # COMETBFT_TPU_COMPILE_CACHE always wins.  The dir is chaos-private
    # (not tests/.jax_cache): these scenarios kill -9 nodes mid-flight,
    # and a write torn by a kill must never be able to corrupt the
    # tier-1 suite's shared cache (a corrupt entry can crash jax's
    # cache read path).
    os.environ.setdefault(
        "COMETBFT_TPU_COMPILE_CACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", ".jax_cache_chaos",
        ),
    )
    from cometbft_tpu.e2e import scenarios as sc

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--scenario", action="append", default=[],
        help="scenario name (repeatable); default: the 5 full scenarios",
    )
    p.add_argument(
        "--smoke", action="store_true",
        help="run only the fast single-node wedge_smoke",
    )
    p.add_argument("--list", action="store_true", help="list scenarios and exit")
    p.add_argument("--json", default="", help="write the machine-readable verdict here")
    p.add_argument("--out", default="", help="artifact directory (default: a tmp dir)")
    p.add_argument(
        "--base-port", type=int, default=0,
        help="override the per-scenario default port ranges",
    )
    p.add_argument(
        "--repeat", type=int, default=1,
        help="run the selected scenario list N times (ports offset per "
             "iteration); the mid-soak fault-injection shape",
    )
    p.add_argument(
        "--seed", type=int, default=None,
        help="deterministic load-round numbering (repeat runs submit "
             "identical tx streams)",
    )
    args = p.parse_args(argv)

    if args.list:
        for name in sc.SCENARIOS:
            print(name)
        return 0

    names = args.scenario or (
        ["wedge_smoke"] if args.smoke else list(sc.DEFAULT_SCENARIOS)
    )
    unknown = [n for n in names if n not in sc.SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {', '.join(unknown)}", file=sys.stderr)
        print(f"known: {', '.join(sc.SCENARIOS)}", file=sys.stderr)
        return 2
    if args.repeat < 1:
        print(f"--repeat must be >= 1, got {args.repeat}", file=sys.stderr)
        return 2

    out_dir = args.out or tempfile.mkdtemp(prefix="cometbft-chaos-")
    os.makedirs(out_dir, exist_ok=True)

    results = []
    t0 = time.monotonic()
    for rep in range(args.repeat):
        rep_dir = (
            out_dir if args.repeat == 1
            else os.path.join(out_dir, f"rep{rep}")
        )
        for i, name in enumerate(names):
            # each (iteration, scenario) slot gets its own port range so
            # a lingering listener from a previous run never collides.
            # Without --base-port the scenarios' built-in defaults are
            # already disjoint within one rep, but reps would reuse
            # them — so repeats anchor above the built-in ranges.
            slot = rep * len(names) + i
            anchor = args.base_port or (27400 if args.repeat > 1 else None)
            base_port = (anchor + slot * 200) if anchor else None
            res = sc.run_scenario(
                name, rep_dir, base_port=base_port, seed=args.seed
            )
            if args.repeat > 1:
                res.details["repeat"] = rep
            results.append(res)
            print(json.dumps(res.to_dict()), flush=True)  # one line each

    verdict = {
        "ok": all(r.ok for r in results),
        "crashed": any(r.crashed for r in results),
        "repeat": args.repeat,
        "seed": args.seed,
        "elapsed_s": round(time.monotonic() - t0, 1),
        "artifact_dir": out_dir,
        "scenarios": [r.to_dict() for r in results],
    }
    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)), exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(verdict, f, indent=1)
    print(
        f"chaos: {sum(r.ok for r in results)}/{len(results)} scenarios passed "
        f"in {verdict['elapsed_s']}s (artifacts: {out_dir})",
        file=sys.stderr,
    )
    if verdict["ok"]:
        return 0
    # crash (scenario raised) vs failure (assertions failed): distinct
    # exit codes so drivers can tell a broken harness from a bad verdict
    return 3 if verdict["crashed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
