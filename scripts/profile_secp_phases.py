"""Phase profiler for the MODE_SECP CheckTx ingest lane: where does a
batched secp256k1/ECDSA dispatch actually spend its wall time?

Phases (models/secp_verifier.LAST_PHASES, filled per device dispatch):
  hash_ms     — HOST side of hashing: the SHA-256/Keccak-256 digest
                loop on the host-hash path; just the block padding on
                the fused path (digests then ride inside kernel_ms)
  decode_ms   — pubkey decode (field sqrt per compressed key; cached —
                iteration 1 pays the sqrt, steady state hits the cache
                like repeat-sender ingest does)
  assembly_ms — the rest of the host staging loop + limb scatter
  h2d_ms      — jnp.asarray transfers of the packed arrays
  kernel_ms   — jitted program dispatch to blocked result
  fetch_ms    — the one device->host verdict readback

Configs sweep the two static axes of the kernel (the before/after
story of the GLV + hashing-residency PR):
  noglv+host — the PR-15 baseline: Shamir double-scalar walk, digests
               on host
  glv+host   — GLV endomorphism quad-scalar walk, digests on host
  glv+fused  — GLV + on-device hashing (the default production shape)

Each config compiles its own program variant (~minutes cold on the CPU
backend; warm COMETBFT_TPU_COMPILE_CACHE removes it), so the default
sweep is opt-down via SECPPROF_CONFIGS.

Env: SECPPROF_N (rows, default 512), SECPPROF_ITERS (timed reps, 5),
SECPPROF_CONFIGS (comma list from the three above), SECPPROF_JSON
(path: also dump the table as JSON).
"""
import json
import os
import statistics
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

N = int(os.environ.get("SECPPROF_N", "512"))
ITERS = int(os.environ.get("SECPPROF_ITERS", "5"))
CONFIGS = [
    c.strip()
    for c in os.environ.get(
        "SECPPROF_CONFIGS", "noglv+host,glv+host,glv+fused"
    ).split(",")
    if c.strip()
]

from cometbft_tpu.crypto import secp256k1 as cosmos  # noqa: E402
from cometbft_tpu.crypto import secp256k1eth as eth  # noqa: E402
from cometbft_tpu.models import secp_verifier as sv  # noqa: E402

# a CheckTx-shaped mixed corpus: all three wire formats interleaved,
# repeat senders (8 keys per type) so the decode cache behaves like
# real ingest
rng = np.random.default_rng(16)
ck = [cosmos.PrivKey.from_seed(rng.bytes(32)) for _ in range(8)]
ek = [eth.PrivKey.from_seed(rng.bytes(32)) for _ in range(8)]
rk = [eth.RecoverPrivKey.from_seed(rng.bytes(32)) for _ in range(8)]
items = []
for i in range(N):
    msg = b"profile tx %d" % i + rng.bytes(24)
    sk = (ck, ek, rk)[i % 3][i // 3 % 8]
    items.append((sk.pub_key().bytes(), msg, sk.sign(msg)))

_KNOBS = {
    "noglv+host": {"COMETBFT_TPU_SECP_GLV": "0",
                   "COMETBFT_TPU_SECP_HASH_DEVICE_MIN": "0"},
    "glv+host": {"COMETBFT_TPU_SECP_GLV": "1",
                 "COMETBFT_TPU_SECP_HASH_DEVICE_MIN": "0"},
    "glv+fused": {"COMETBFT_TPU_SECP_GLV": "1",
                  "COMETBFT_TPU_SECP_HASH_DEVICE_MIN": "1"},
}
PHASE_KEYS = ("hash_ms", "decode_ms", "assembly_ms",
              "h2d_ms", "kernel_ms", "fetch_ms")

report = {"rows": N, "iters": ITERS, "configs": {}}
for cfg in CONFIGS:
    if cfg not in _KNOBS:
        print(f"unknown config {cfg!r}; pick from {sorted(_KNOBS)}")
        raise SystemExit(2)
    os.environ.update(_KNOBS[cfg])
    sv.reset_caches()
    t0 = time.perf_counter()
    _, first = sv._verify_items(items, use_device=True)
    warm_s = time.perf_counter() - t0
    assert all(first), "profiler corpus must verify clean"
    cold = dict(sv.LAST_PHASES)
    samples = {k: [] for k in PHASE_KEYS}
    walls = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        sv._verify_items(items, use_device=True)
        walls.append((time.perf_counter() - t0) * 1e3)
        for k in PHASE_KEYS:
            samples[k].append(sv.LAST_PHASES.get(k, 0.0))
    wall = statistics.median(walls)
    row = {"wall_ms": round(wall, 3), "first_call_s": round(warm_s, 1)}
    print(f"\n{cfg}  ({N} rows, wall p50 {wall:.1f} ms, "
          f"first call {warm_s:.1f} s incl. compile)")
    for k in PHASE_KEYS:
        p50 = statistics.median(samples[k])
        row[k] = {
            "p50_ms": round(p50, 3),
            "share_of_wall": round(p50 / wall, 3) if wall else 0.0,
        }
        print(f"  {k:12s} {p50:10.3f} ms  "
              f"({row[k]['share_of_wall']:.1%} of wall)")
    print(f"  decode_ms cold (cache-miss sqrt): "
          f"{cold.get('decode_ms', 0.0):.3f} ms")
    row["decode_ms_cold"] = round(cold.get("decode_ms", 0.0), 3)
    report["configs"][cfg] = row

if os.environ.get("SECPPROF_JSON"):
    with open(os.environ["SECPPROF_JSON"], "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    print(f"\nwrote {os.environ['SECPPROF_JSON']}")
