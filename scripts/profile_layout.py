"""Layout experiment: is the (V, 22) limbs-minor layout wasting TPU lanes?

TPU vregs tile (8 sublanes x 128 lanes) over the two minor dims.  With
field elements shaped (V, 22) the 22-limb axis sits on the 128-lane minor
dim (83% lane waste); transposed (22, V) puts V on lanes (full) and limbs
on sublanes (22 -> 24, 8% waste).  This script times a chain of field
muls in both layouts on whatever backend is live, to decide whether the
limbs-first refactor of ops/field.py is worth it.

Run:  python scripts/profile_layout.py [V] [CHAIN]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from cometbft_tpu.ops import field as F

V = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
CHAIN = int(sys.argv[2]) if len(sys.argv) > 2 else 100

RADIX, BITS, MASK = F.RADIX, F.BITS, F.MASK
FOLD, FOLD2_SHIFTED, NLIMBS = F.FOLD, F.FOLD2_SHIFTED, F.NLIMBS


# ---------------- transposed (limbs-first) field mul, inline ----------------

def _convT(a, b):
    c = jnp.zeros((2 * NLIMBS - 1,) + a.shape[1:], jnp.int32)
    for i in range(NLIMBS):
        c = c.at[i : i + NLIMBS].add(a * b[i])
    return c


def _carry_roundT(c):
    q = lax.shift_right_arithmetic(c + (RADIX >> 1), BITS)
    c = c - lax.shift_left(q, BITS)
    carry_in = jnp.pad(q[:-1], [(1, 0)] + [(0, 0)] * (q.ndim - 1))
    return c + carry_in, q[-1]


def _fold_topT(c, q):
    v = q * 19
    c = c.at[0].add((v & 7) * (1 << 9))
    c = c.at[1].add(lax.shift_right_arithmetic(v, 3))
    return c


def carryT(a, rounds=3):
    c = a
    for _ in range(rounds):
        c, top = _carry_roundT(c)
        c = _fold_topT(c, top)
    return c


def _reduce_convT(c):
    lo = c[:NLIMBS]
    hi = jnp.pad(c[NLIMBS:], [(0, 3)] + [(0, 0)] * (c.ndim - 1))
    for _ in range(3):
        hi, _ = _carry_roundT(hi)
    lo = lo + hi[:NLIMBS] * FOLD
    lo = lo.at[1].add(hi[NLIMBS] * FOLD2_SHIFTED)
    lo = lo.at[2].add(hi[NLIMBS + 1] * FOLD2_SHIFTED)
    return carryT(lo, rounds=3)


def mulT(a, b):
    return _reduce_convT(_convT(a, b))


# --------------------------------------------------------------- harness

def bench(name, fn, *args):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    first = time.perf_counter() - t0
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    per_mul = 1e3 * min(ts) / CHAIN
    print(
        f"{name}: {1e3 * min(ts):8.2f} ms total  {per_mul:7.4f} ms/mul "
        f"(compile {first:.1f}s)",
        flush=True,
    )
    return min(ts)


def main():
    print(f"backend={jax.default_backend()} devices={jax.devices()} "
          f"V={V} chain={CHAIN}", flush=True)
    rng = np.random.default_rng(0)
    a_np = rng.integers(0, 2048, size=(V, NLIMBS), dtype=np.int32)
    b_np = rng.integers(0, 2048, size=(V, NLIMBS), dtype=np.int32)

    a = jnp.asarray(a_np)
    b = jnp.asarray(b_np)
    aT = jnp.asarray(a_np.T.copy())
    bT = jnp.asarray(b_np.T.copy())

    @jax.jit
    def chain_cur(x, y):
        return lax.fori_loop(0, CHAIN, lambda _, v: F.mul(v, y), x)

    @jax.jit
    def chain_T(x, y):
        return lax.fori_loop(0, CHAIN, lambda _, v: mulT(v, y), x)

    t_cur = bench("limbs-minor (V,22)", chain_cur, a, b)
    t_T = bench("limbs-first (22,V)", chain_T, aT, bT)

    # correctness cross-check on a few rows
    got = np.asarray(chain_T(aT, bT)).T
    want = np.asarray(chain_cur(a, b))
    assert np.array_equal(
        np.asarray([F.from_limbs(r) % F.P for r in got[:8]]),
        np.asarray([F.from_limbs(r) % F.P for r in want[:8]]),
    ), "transposed mul disagrees with field.mul"
    print(f"speedup (cur/T): {t_cur / t_T:.2f}x ; results agree", flush=True)


if __name__ == "__main__":
    main()
