"""Slope-based micro-benchmarks: vary inner iteration count and diff, so
fixed dispatch/tunnel overhead cancels out."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from functools import partial

cache_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

from cometbft_tpu.ops import field as F

N = 16384


def timeit(fn, *args, iters=3):
    out = fn(*args)
    _ = np.asarray(out.ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
        _ = np.asarray(out.ravel()[0])
    return (time.perf_counter() - t0) / iters


@jax.jit
def noop(x):
    return x[:1, :1]

x32 = jnp.asarray(np.random.randint(1, 1000, size=(N, 128), dtype=np.int32))
print(f"noop round-trip: {timeit(noop, x32)*1e3:.2f} ms", flush=True)


@partial(jax.jit, static_argnums=1)
def chain_i32(x, n):
    return lax.fori_loop(0, n, lambda _, a: (a * a) & 0xFFFF | 1, x)

t1 = timeit(chain_i32, x32, 256)
t2 = timeit(chain_i32, x32, 4096)
rate = (4096 - 256) * N * 128 / (t2 - t1)
print(f"int32 mul: lo={t1*1e3:.1f} hi={t2*1e3:.1f} ms -> {rate/1e9:.1f} G/s", flush=True)

xf = jnp.asarray(np.random.uniform(1.0, 1.001, size=(N, 128)).astype(np.float32))

@partial(jax.jit, static_argnums=1)
def chain_f32(x, n):
    return lax.fori_loop(0, n, lambda _, a: a * a + 0.25, x)

t1 = timeit(chain_f32, xf, 256)
t2 = timeit(chain_f32, xf, 4096)
rate = (4096 - 256) * N * 128 / (t2 - t1)
print(f"f32 fma: lo={t1*1e3:.1f} hi={t2*1e3:.1f} ms -> {rate/1e9:.1f} G/s", flush=True)

ab = jnp.asarray(np.random.randn(2048, 2048)).astype(jnp.bfloat16)

@partial(jax.jit, static_argnums=1)
def mmb(a, n):
    def body(_, b):
        return (b @ a).astype(jnp.bfloat16) * jnp.bfloat16(1e-3)
    return lax.fori_loop(0, n, body, a)

t1 = timeit(mmb, ab, 4)
t2 = timeit(mmb, ab, 64)
rate = (64 - 4) * 2 * 2048**3 / (t2 - t1)
print(f"bf16 mm 2048: lo={t1*1e3:.1f} hi={t2*1e3:.1f} ms -> {rate/1e12:.1f} TF/s", flush=True)

fx = jnp.asarray(np.random.randint(0, 2000, size=(N, 22), dtype=np.int32))

@partial(jax.jit, static_argnums=1)
def chain_fmul(x, n):
    return lax.fori_loop(0, n, lambda _, a: F.mul(a, a), x)

t1 = timeit(chain_fmul, fx, 64)
t2 = timeit(chain_fmul, fx, 1024)
per = (t2 - t1) / (1024 - 64) / N
print(f"field mul: lo={t1*1e3:.1f} hi={t2*1e3:.1f} ms -> {per*1e9:.2f} ns/row-mul", flush=True)

# Straus window-step cost estimate: 3700 muls/sig target check
print(f"  => 10k sigs x 3700 muls ~= {3700*10000*per*1e3:.0f} ms", flush=True)

# point double and add-niels chain for direct cost
from cometbft_tpu.ops import ed25519 as E

pt = E.identity((N,))

@partial(jax.jit, static_argnums=1)
def chain_dbl(p, n):
    return lax.fori_loop(0, n, lambda _, q: E.double(q), p)

t1 = timeit(lambda p, n: chain_dbl(p, n).x, pt, 32)
t2 = timeit(lambda p, n: chain_dbl(p, n).x, pt, 256)
per = (t2 - t1) / (256 - 32) / N
print(f"point double: lo={t1*1e3:.1f} hi={t2*1e3:.1f} ms -> {per*1e9:.1f} ns/row-double", flush=True)
print(f"  => 256 doubles x 16384 = {256*16384*per*1e3:.0f} ms", flush=True)
