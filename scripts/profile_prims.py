"""Micro-benchmarks: primitive op throughput + H2D bandwidth on the chip."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

cache_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

from cometbft_tpu.ops import field as F

N = 16384


def bench(fn, *args, iters=5, label=""):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{label}: {dt*1e3:.2f} ms", flush=True)
    return dt


# H2D bandwidth
for sz in (1 << 20, 4 << 20, 16 << 20):
    buf = np.random.randint(0, 255, size=sz, dtype=np.uint8)
    jnp.asarray(buf).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(3):
        jnp.asarray(buf).block_until_ready()
    dt = (time.perf_counter() - t0) / 3
    print(f"H2D {sz>>20} MiB: {dt*1e3:.1f} ms = {sz/dt/1e6:.0f} MB/s", flush=True)

# chained int32 multiplies (VPU int path)
x32 = jnp.asarray(np.random.randint(1, 1000, size=(N, 128), dtype=np.int32))

@jax.jit
def chain_i32(x):
    def body(_, a):
        return (a * a) & 0xFFFF | 1
    return lax.fori_loop(0, 256, body, x)

d = bench(chain_i32, x32, label="int32 mul+and chain 256x (N,128)")
print(f"  -> {256*N*128/d/1e9:.1f} G int32-mul/s", flush=True)

# chained f32 FMA
xf = jnp.asarray(np.random.uniform(1.0, 1.001, size=(N, 128)).astype(np.float32))

@jax.jit
def chain_f32(x):
    def body(_, a):
        return a * a + 0.25
    return lax.fori_loop(0, 256, body, x)

d = bench(chain_f32, xf, label="f32 fma chain 256x (N,128)")
print(f"  -> {256*N*128/d/1e9:.1f} G f32-fma/s", flush=True)

# bf16->f32 matmul MXU reference
a = jnp.asarray(np.random.randn(4096, 4096).astype(np.float32))

@jax.jit
def mm(a):
    return a @ a

d = bench(mm, a, label="f32 matmul 4096^3")
print(f"  -> {2*4096**3/d/1e12:.1f} TFLOP/s", flush=True)

# our field mul chained
fx = jnp.asarray(np.random.randint(0, 2000, size=(N, 22), dtype=np.int32))

@jax.jit
def chain_fmul(x):
    def body(_, a):
        return F.mul(a, a)
    return lax.fori_loop(0, 64, body, x)

d = bench(chain_fmul, fx, label="field mul chain 64x (N,22)")
print(f"  -> {64*N/d/1e6:.2f} M fieldmul/s; {d/64/N*1e9:.1f} ns/fieldmul-row", flush=True)

# field squaring chain for comparison
@jax.jit
def chain_fsq(x):
    def body(_, a):
        return F.square(a)
    return lax.fori_loop(0, 64, body, x)

bench(chain_fsq, fx, label="field square chain 64x (N,22)")

# int16 mul chain (does VPU do int16 better?)
x16 = jnp.asarray(np.random.randint(1, 100, size=(N, 128), dtype=np.int16))

@jax.jit
def chain_i16(x):
    def body(_, a):
        return (a * a) & 0xFF | 1
    return lax.fori_loop(0, 256, body, x)

d = bench(chain_i16, x16, label="int16 mul chain 256x (N,128)")
print(f"  -> {256*N*128/d/1e9:.1f} G int16-mul/s", flush=True)

# elementwise int32 multiply, one shot over big array (memory bound check)
big = jnp.asarray(np.random.randint(0, 1000, size=(N, 2048), dtype=np.int32))

@jax.jit
def one_mul(x):
    return x * x

d = bench(one_mul, big, label="single int32 mul (N,2048)")
print(f"  -> {N*2048*4*2/d/1e9:.0f} GB/s effective", flush=True)
