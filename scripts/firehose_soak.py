"""CheckTx firehose soak driver: >=100k mixed secp ingest through one
verify plane with adversarial storm windows — the acceptance run of
the Ethereum-rate ingest lane (e2e/firehose.py has the SLO contract).

    python scripts/firehose_soak.py --json /tmp/firehose.json

Defaults come from the COMETBFT_TPU_SECP_FIREHOSE_TXS / _SENDERS knobs
(100000 txs, 32 senders per key type); exit code is nonzero when any
SLO assertion fails, so the run gates CI the same way scripts/soak.py
does.
"""
import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from cometbft_tpu.e2e.firehose import FirehoseConfig, run_firehose  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--txs", type=int, default=0,
                    help="total txs (0 = COMETBFT_TPU_SECP_FIREHOSE_TXS)")
    ap.add_argument("--senders", type=int, default=0,
                    help="senders per key type (0 = _SENDERS knob)")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--storm-every", type=int, default=5000)
    ap.add_argument("--storm-len", type=int, default=128)
    ap.add_argument("--batch-max", type=int, default=16)
    ap.add_argument("--slo-p99-ms", type=float, default=500.0)
    ap.add_argument("--cache-hit-min", type=float, default=0.9)
    ap.add_argument("--no-cache-check", action="store_true",
                    help="skip the pubkey-cache SLO (host-path runs "
                         "never touch the decode cache)")
    ap.add_argument("--seed", type=int, default=16)
    ap.add_argument("--json", default="", help="write the SLO artifact here")
    args = ap.parse_args()

    cfg = FirehoseConfig(
        total_txs=args.txs,
        senders_per_type=args.senders,
        workers=args.workers,
        storm_every=args.storm_every,
        storm_len=args.storm_len,
        batch_max=args.batch_max,
        slo_p99_ms=args.slo_p99_ms,
        cache_hit_min=args.cache_hit_min,
        cache_check=not args.no_cache_check,
        seed=args.seed,
        json_path=args.json,
    )
    report = run_firehose(cfg)
    print(json.dumps(
        {
            "ok": report["ok"],
            "wall_s": report["wall_s"],
            "txs_per_s": report["txs_per_s"],
            "assertions": {
                k: v["ok"] for k, v in report["assertions"].items()
            },
        },
        indent=1,
    ))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
