"""4-node localnet throughput/latency benchmark (BASELINE config 3;
reference: test/e2e/runner/benchmark.go:109 — mean/σ block interval over
a live testnet, plus the loadtime latency report).

Runs a real 4-process localnet, drives timestamped load through the
loadtime generator, and reports:
  block_interval_mean_s / stddev   (benchmark.go's headline stats)
  tx_per_s committed               (loadtime report)
  latency avg/max                  (block time - payload time)

Run:  python scripts/bench_localnet.py [duration_s] [rate_tx_s]
"""

import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from cometbft_tpu.e2e import Manifest, NodeSpec, Runner  # noqa: E402
from cometbft_tpu.e2e.loadtime import LoadGenerator, report  # noqa: E402
from cometbft_tpu.rpc.client import HTTPClient  # noqa: E402

OUT = os.environ.get("LOCALNET_BENCH_OUT", "/tmp/localnet_bench.json")


def main() -> None:
    duration = float(sys.argv[1]) if len(sys.argv) > 1 else 60.0
    rate = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    out_dir = tempfile.mkdtemp(prefix="lbench-")
    m = Manifest(
        chain_id="localnet-bench",
        nodes=[NodeSpec(f"v{i}") for i in range(4)],
        target_height=3,
    )
    r = Runner(m, out_dir, base_port=21800)
    rec: dict = {}
    try:
        r.setup()
        r.start()
        assert r.wait_for_height(3, timeout=120), "net never started"
        addr = f"127.0.0.1:{r.nodes[0].rpc_port}"
        gen = LoadGenerator(
            lambda: HTTPClient(addr), connections=2, rate=rate // 2, size=256
        )
        rpc = HTTPClient(addr)
        h_start = int(rpc.status()["sync_info"]["latest_block_height"])
        t0 = time.monotonic()
        load = gen.run(duration)
        wall = time.monotonic() - t0
        h_end = int(rpc.status()["sync_info"]["latest_block_height"])
        time.sleep(3)  # let the tail commit

        rep = report(rpc)
        # block intervals over the LOADED window only (benchmark.go
        # measures the testnet under load, not startup/settle idling)
        last = int(rpc.status()["sync_info"]["latest_block_height"])
        times = []
        for h in range(max(1, h_start), min(h_end, last) + 1):
            bt = rpc.block(h)["block"]["header"]["time"]
            import datetime

            base_s, _, frac = bt.rstrip("Z").partition(".")
            dt = datetime.datetime.strptime(
                base_s, "%Y-%m-%dT%H:%M:%S"
            ).replace(tzinfo=datetime.timezone.utc)
            times.append(
                int(dt.timestamp()) * 10**9
                + int((frac or "0").ljust(9, "0")[:9])
            )
        ivals = [
            (b - a) / 1e9 for a, b in zip(times, times[1:]) if b > a
        ]
        rec = {
            "nodes": 4,
            "duration_s": round(wall, 1),
            "rate_target_tx_s": rate,
            "sent": load.sent,
            "accepted": load.accepted,
            "committed": rep["payload_txs"],
            "tx_per_s": rep["throughput_txs_per_s"],
            "blocks": last,
            "block_interval_mean_s": round(statistics.fmean(ivals), 3)
            if ivals
            else None,
            "block_interval_stddev_s": round(statistics.pstdev(ivals), 3)
            if len(ivals) > 1
            else None,
            "latency": {
                k: {"avg_s": v["avg_s"], "max_s": v["max_s"]}
                for k, v in rep["experiments"].items()
            },
            "errors": load.errors[:3],
        }
        print(json.dumps(rec), flush=True)
        with open(OUT, "w") as f:
            json.dump(rec, f)
    finally:
        r.stop_all()
        shutil.rmtree(out_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
