"""Soak the perturbed localnet scenario N times and count consensus
watchdog fires (round-5 verdict item 5: 'watchdog never fires across
>=50 perturbed e2e runs').

Each iteration is the slow-tier perturbed manifest (kill + pause + WAN
late-joiner).  Appends one JSON line per run to SOAK_OUT so partial
progress survives interruption.

Run:  python scripts/soak_perturbed.py [N]
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from cometbft_tpu.e2e import Manifest, NodeSpec, Runner  # noqa: E402

OUT = os.environ.get("SOAK_OUT", "/tmp/soak_perturbed.jsonl")


def one_run(i: int, base_port: int) -> dict:
    out_dir = tempfile.mkdtemp(prefix=f"soak{i}-")
    variant = os.environ.get("SOAK_VARIANT", "full")
    if variant == "kill":
        # kill-focused: maximize post-restart catchup interleavings (the
        # run-41 stall class: killed node wedges at its handoff height)
        nodes = [
            NodeSpec("stable0"),
            NodeSpec("killed1", perturbations=["kill"]),
            NodeSpec("killed2", perturbations=["kill"]),
            NodeSpec("stable1"),
        ]
    else:
        nodes = [
            NodeSpec("stable0", perturbations=["disconnect"]),
            NodeSpec("killed", perturbations=["kill"]),
            NodeSpec("paused", perturbations=["pause"], abci="socket"),
            NodeSpec("late", start_at=4, latency_ms=60, latency_jitter_ms=20),
        ]
    m = Manifest(
        chain_id=f"soak-{i}",
        nodes=nodes,
        target_height=6,
        load_tx_per_round=3,
    )
    r = Runner(m, out_dir, base_port=base_port)
    t0 = time.monotonic()
    rec = {"run": i, "ok": False, "fires": [], "problems": []}
    try:
        r.setup()
        r.start()
        deadline = time.monotonic() + 420
        perturbed = False
        round_id = 0
        while time.monotonic() < deadline:
            r.start_late_nodes()
            hs = r._heights(only_running=True)
            if hs and max(hs) >= 4 and not perturbed:
                r.perturb()
                perturbed = True
            r.load(round_id)
            round_id += 1
            if (
                hs
                and min(hs) >= m.target_height
                and all(n.proc is not None for n in r.nodes)
                and len(hs) == len(r.nodes)
            ):
                break
            time.sleep(2.0)
        heights = r._heights(only_running=True)
        rec["heights"] = heights
        rec["perturbed"] = perturbed
        rec["problems"] = r.check_invariants(upto=m.target_height)
        rec["fires"] = r.check_watchdog_fires()
        rec["ok"] = (
            perturbed
            and len(heights) == 4
            and min(heights) >= m.target_height
            and not rec["problems"]
            and not rec["fires"]
        )
    except Exception as e:  # noqa: BLE001
        rec["error"] = f"{type(e).__name__}: {e}"
    finally:
        if not rec["ok"]:
            # capture the stalled nodes' thread dumps + p2p state BEFORE
            # teardown — a failing interleaving is rare and the logs are
            # the only evidence
            diag = {}
            for node in r.nodes:
                if node.proc is None:
                    diag[node.name] = "not running"
                    continue
                try:
                    diag[node.name] = {
                        "height": node.height(),
                        "net_info": node.rpc("net_info"),
                    }
                except Exception as de:  # noqa: BLE001
                    diag[node.name] = f"rpc dead: {de}"
            rec["diag"] = diag
            r.dump_stalled(10**9)
        r.stop_all()
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        if rec["ok"]:
            shutil.rmtree(out_dir, ignore_errors=True)
        else:
            keep = f"/tmp/soak-fail-{i}-{int(time.time())}"
            shutil.move(out_dir, keep)
            rec["kept_dir"] = keep
            print(f"KEPT failing run dir: {keep}", flush=True)
    return rec


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    fails = 0
    for i in range(n):
        # keep every derived port family (p2p=base, rpc=+1000,
        # pprof=+2000, abci=+3000) BELOW the Linux ephemeral range
        # (32768+): an outbound socket that randomly lands on a node's
        # listen port would otherwise break that node's restart
        rec = one_run(i, base_port=20000 + (i % 40) * 100)
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
        if not rec["ok"]:
            fails += 1
    print(f"SOAK DONE: {n - fails}/{n} clean", flush=True)
    sys.exit(1 if fails else 0)


if __name__ == "__main__":
    main()
