#!/usr/bin/env python
"""Merge per-process Chrome trace exports into one Perfetto timeline.

    python scripts/trace_merge.py --out merged.trace.json \
        node0/trace.json node1/trace.json verifyd.trace.json

Each input is a utils/tracing export (its ``wall_clock_anchor`` record
rebases the process's monotonic timestamps onto the wall clock); the
output opens in Perfetto (ui.perfetto.dev) with one process track per
input and all spans on one common timeline.  Spans recorded under a
propagated span context carry ``trace_id`` args — search a trace_id in
Perfetto to follow one verify batch from the consensus-side submit into
the remote plane's scheduler and back.  Anchor skew between the inputs
(how far the processes' wall/monotonic offsets disagree) is printed per
input and embedded under ``otherData.anchor_skew_ns``.

Exit codes: 0 merged; 1 nothing mergeable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.utils import tracemerge  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="merge per-process Chrome trace exports into one "
        "Perfetto timeline"
    )
    p.add_argument("inputs", nargs="+", help="per-process trace JSON files")
    p.add_argument("--out", default="merged.trace.json",
                   help="merged timeline path (default: merged.trace.json)")
    p.add_argument("--json", action="store_true",
                   help="print the merge report as JSON")
    args = p.parse_args(argv)
    try:
        report = tracemerge.merge_files(args.inputs, args.out)
    except tracemerge.MergeError as e:
        print(f"trace_merge: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(f"merged {report['total_events']} events from "
              f"{len(report['processes'])} process(es) -> {report['out']}")
        for proc in report["processes"]:
            skew_ms = proc["anchor_skew_ns"] / 1e6
            print(f"  pid {proc['pid']:>7}  {proc['events']:>6} events  "
                  f"skew {skew_ms:+.3f} ms  {proc['label']}")
        for s in report.get("skipped", []):
            print(f"  skipped {s['label']}: {s['error']}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
