"""Probe whether the TPU backend is reachable WITHOUT risking a wedge.

Killing a device-attached process wedges the tunnel for hours, so this
probe never gets killed externally: a SIGALRM fires inside the process
and os._exit(2)s before any external timeout would. Exit codes:
  0 — TPU visible (prints platform + device)
  2 — timed out (tunnel wedged / unreachable)
  3 — backend error (prints it)
"""
import os
import threading

TIMEOUT_S = int(os.environ.get("TPU_PROBE_TIMEOUT", "60"))


def _bail() -> None:
    # os._exit is a raw syscall and works from a daemon thread even while
    # the main thread is blocked inside PJRT C++ discovery (where Python
    # signal handlers would be deferred indefinitely).
    print(f"PROBE_TIMEOUT after {TIMEOUT_S}s", flush=True)
    os._exit(2)


def main() -> None:
    t = threading.Timer(TIMEOUT_S, _bail)
    t.daemon = True
    t.start()
    try:
        import jax

        devs = jax.devices()
    except Exception as e:  # noqa: BLE001
        print(f"PROBE_ERROR {type(e).__name__}: {e}", flush=True)
        os._exit(3)
    t.cancel()
    print(f"PROBE_OK platform={devs[0].platform} n={len(devs)} {devs[0]}", flush=True)
    os._exit(0)


if __name__ == "__main__":
    main()
