"""Phase profiler for the comb-cached VerifyCommit path: host assembly,
H2D+dispatch, kernel (tree-reduced AND sequential accumulation), result
fetch, table build, scalar reduce, R decompression, A/B comb loops,
single field ops — run on the real chip to direct optimization (numbers
recorded in BASELINE.md).

The headline lines:
  assembly_ms   — host staging-slab fill (models/comb_verifier), the
                  phase the round-5 capture measured at ~22 ms
  kernel tree/seq — verify_cached with the log-depth tree fold
                  (acc depth 7) vs the 87-step sequential chain
  fetch_ms      — the one packed device->host result readback

Layout note: field elements are limbs-first (..., 22, V) since round 4
(see ops/field.py); the comb tables are (64, 9, 3, 22, V)."""
import sys, os, time, hashlib
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
from cometbft_tpu.ops import comb, ed25519 as E, field as F, scalar, sha2
from cometbft_tpu.crypto import ed25519 as host

V = int(os.environ.get("COMBPROF_V", "10000"))
TDIR = "/tmp/combprof"
rng = np.random.default_rng(7)
keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(V)]
pubs = [k.pub_key().data for k in keys]

# ---- table_build phase: the cold-start cost (PR-11), attributable per
# sub-phase.  COMBPROF_TABLE_BUILD=host|device|both|skip (default: host
# at small V, device at large V — the models/comb_verifier routing).
# host  = build_a_tables_host (bigint precompute) + device_put H2D
# device = build_a_tables_jit (compile + arithmetic; the compile half
#          vanishes with a warm COMETBFT_TPU_COMPILE_CACHE)
_tb_mode = os.environ.get("COMBPROF_TABLE_BUILD", "")
if not _tb_mode:
    _tb_mode = "host" if V <= 2048 else "device"
a = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(-1, 32)
tables = valid = None
if _tb_mode in ("host", "both"):
    t0 = time.time()
    th, vh = comb.build_a_tables_host(a)
    t1 = time.time()
    tables = jax.device_put(th); valid = jax.device_put(vh)
    tables.block_until_ready(); valid.block_until_ready()
    t2 = time.time()
    print(
        f"table_build (host): precompute {t1-t0:.1f} s + device_put H2D "
        f"{t2-t1:.1f} s = {t2-t0:.1f} s  ({(t2-t0)/max(V,1)*1e3:.1f} ms/validator)",
        flush=True,
    )
if _tb_mode in ("device", "both"):
    t0 = time.time()
    tables, valid = comb.build_a_tables_jit(jnp.asarray(a))
    tables.block_until_ready()
    print(
        f"table_build (device, compile+run): {time.time()-t0:.1f} s "
        "(warm COMETBFT_TPU_COMPILE_CACHE removes the compile half)",
        flush=True,
    )
tp, vp = os.path.join(TDIR, f"tablesT{V}.npy"), os.path.join(TDIR, f"validT{V}.npy")
if tables is None and os.path.exists(tp) and os.path.exists(vp):
    t0=time.time()
    tables = jnp.asarray(np.load(tp, mmap_mode="r"))
    valid = jnp.asarray(np.load(vp))
    tables.block_until_ready()
    print("tables loaded from disk", round(time.time()-t0,1), "s", flush=True)
elif tables is None:
    t0=time.time()
    tables, valid = comb.build_a_tables_jit(jnp.asarray(a))
    tables.block_until_ready()
    print("tables built", round(time.time()-t0,1), "s", flush=True)
    if os.environ.get("COMBPROF_SAVE") == "1":
        # 2.7 GB device->host fetch: minutes over the tunnel, so opt-in
        os.makedirs(TDIR, exist_ok=True)
        np.save(tp, np.asarray(tables))
        np.save(vp, np.asarray(valid))

r_all=np.zeros((V,32),np.uint8); s_all=np.zeros((V,32),np.uint8); dig_all=np.zeros((V,64),np.uint8)
for i,sk in enumerate(keys):
    msg=b"m%d"%i; sig=sk.sign(msg)
    r_all[i]=np.frombuffer(sig[:32],np.uint8); s_all[i]=np.frombuffer(sig[32:],np.uint8)
    dig_all[i]=np.frombuffer(hashlib.sha512(sig[:32]+pubs[i]+msg).digest(),np.uint8)
ra,sa,da = jnp.asarray(r_all), jnp.asarray(s_all), jnp.asarray(dig_all)
bt = comb.get_b_tables()

def timeit(name, f, *args):
    t0=time.perf_counter()
    o = f(*args); jax.tree_util.tree_map(lambda x: x.block_until_ready(), o)
    compile_s = time.perf_counter()-t0
    ts=[]
    for _ in range(5):
        t0=time.perf_counter(); o=f(*args); jax.tree_util.tree_map(lambda x: x.block_until_ready(), o); ts.append(time.perf_counter()-t0)
    print(f"{name}: {1e3*min(ts):.1f} ms   (first {compile_s:.1f}s)", flush=True)

print(
    f"accumulation: tree={comb.tree_enabled()} "
    f"dependent_depth={comb.accumulation_depth()} "
    f"(sequential chain would be {comb.NPOS_A + comb.NPOS_B + 1})",
    flush=True,
)
timeit(
    "full verify_cached (tree)",
    jax.jit(lambda *x: comb.verify_cached(*x, tree=True)),
    tables, valid, ra, sa, da, bt,
)
timeit(
    "full verify_cached (seq)",
    jax.jit(lambda *x: comb.verify_cached(*x, tree=False)),
    tables, valid, ra, sa, da, bt,
)

# ---- host assembly phase: the staging-slab fill the engine's submit()
# runs (models/comb_verifier._fill_payload) on a commit-shaped batch —
# all V validators signing ~100-byte sign-bytes in row order.  First
# call allocates + writes every column; steady-state calls (same row
# layout) rewrite only R | s | msg.  The ~22 ms round-5 capture is the
# number this phase replaces.
from cometbft_tpu.models import comb_verifier as _cv

items = []
for i, sk in enumerate(keys):
    msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|prof-comb"
    sig = sk.sign(msg)
    items.append((pubs[i], msg, sig))
rows = np.arange(V, dtype=np.int64)
slab = _cv._PayloadSlab(V, _cv._payload_width(items))
t0 = time.perf_counter(); _cv._fill_payload(slab, items, rows)
cold = (time.perf_counter() - t0) * 1e3
ts = []
for _ in range(5):
    t0 = time.perf_counter(); payload_host = _cv._fill_payload(slab, items, rows)
    ts.append((time.perf_counter() - t0) * 1e3)
print(f"assembly_ms (slab fill): {min(ts):.2f} ms   (cold {cold:.2f} ms)", flush=True)

# H2D + dispatch and the single packed result fetch, measured around the
# jitted engine program on the same payload
pl_dev = jnp.asarray(payload_host); pl_dev.block_until_ready()
t0 = time.perf_counter(); pl_dev = jnp.asarray(payload_host); pl_dev.block_until_ready()
print(f"h2d_ms (payload transfer): {(time.perf_counter()-t0)*1e3:.2f} ms", flush=True)
_vc = jax.jit(
    lambda *x: jnp.concatenate(
        [jnp.packbits(comb.verify_cached(*x)), jnp.ones((1,), jnp.uint8)]
    )
)  # the engine's packed [bitmap | all_ok] single-fetch contract
out = _vc(tables, valid, ra, sa, da, bt); out.block_until_ready()
t0 = time.perf_counter(); _ = np.asarray(out)
print(f"fetch_ms (packed result readback): {(time.perf_counter()-t0)*1e3:.2f} ms", flush=True)

# device SHA-512 digest phase (the engine path hashes on device now)
msgs = [b"m%d" % i for i in range(V)]
blocks, active = sha2.pad_messages_sha512([s_all[i].tobytes() for i in range(V)])
timeit("sha512 digests", jax.jit(sha2.sha512_blocks), jnp.asarray(blocks), jnp.asarray(active))

timeit("scalar+nibbles", jax.jit(lambda d: scalar.nibbles_lsb(scalar.reduce_mod_l(scalar.bytes_to_limbs(d, scalar.NL_X)), comb.NPOS_A)), da)
timeit("decompress R", jax.jit(lambda r: E.decompress(r)[0].x), ra)

@jax.jit
def a_loop(tables, dig):
    k_dig = scalar.signed_digits_radix16(scalar.reduce_mod_l(scalar.bytes_to_limbs(dig, scalar.NL_X)), comb.NPOS_A)
    ents = jnp.arange(comb.NENT_A, dtype=jnp.int32)[:, None]
    def a_body(i, acc):
        slab = lax.dynamic_index_in_dim(tables, i, axis=0, keepdims=False)
        d = lax.dynamic_index_in_dim(k_dig, i, axis=0, keepdims=False)
        neg = d < 0
        onehot=(ents == jnp.abs(d)[None,:]).astype(jnp.int32)
        sel=jnp.sum(slab*onehot[:,None,None,:],axis=0)
        return E.add_niels(acc, E.Niels(F.select(neg, sel[1], sel[0]), F.select(neg, sel[0], sel[1]), F.select(neg, -sel[2], sel[2])))
    return lax.fori_loop(0, comb.NPOS_A, a_body, E.identity((dig.shape[0],))).x
timeit("A loop", a_loop, tables, da)

@jax.jit
def b_loop(bt, s):
    s_dig = scalar.bytes_to_limbs(s, comb.NPOS_B)
    ents = jnp.arange(comb.NENT_B, dtype=jnp.int32)[:, None]
    def b_body(i, acc):
        slab = lax.dynamic_index_in_dim(bt, i, axis=0, keepdims=False)
        d = lax.dynamic_index_in_dim(s_dig, i, axis=0, keepdims=False)
        onehot=(ents == d[None,:]).astype(jnp.float32)
        sel=jnp.matmul(slab,onehot,precision=lax.Precision.HIGHEST).astype(jnp.int32)
        return E.add_niels(acc, E.Niels(sel[0:22],sel[22:44],sel[44:66]))
    return lax.fori_loop(0, comb.NPOS_B, b_body, E.identity((s.shape[0],))).x
timeit("B loop", b_loop, bt, sa)

x = jnp.ones((F.NLIMBS, V), jnp.int32)
timeit("1 field mul", jax.jit(F.mul), x, x)
timeit("100 field muls", jax.jit(lambda a,b: lax.fori_loop(0,100,lambda _,v: F.mul(v,b), a)), x, x)
nl = E.Niels(x, x, x)
timeit("1 add_niels", jax.jit(lambda p, a,b,c: E.add_niels(p, E.Niels(a,b,c)).x), E.identity((V,)), x,x,x)
