"""Benchmark the native storage engine at blocksync-replay scale
(round-5 verdict item 8; reference: store/bench_test.go + pebbledb.go).

Simulates the block-store write pattern of a 50k-block catch-up: per
height one batch of meta + parts + commit (BLOCK_KB of payload split
into part-sized values), interleaved periodic reads, then pruning half
the range and compacting.  Reports write/read/prune throughput, max
single-batch stall, compaction pause, and the engine's resident index
cost (RSS growth per key).

Run:  python scripts/bench_native_store.py [n_blocks] [block_kb]
Appends one JSON line per stage to NATIVE_BENCH_OUT
(default /tmp/native_store_bench.jsonl).
"""

import json
import os
import resource
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.store.native_db import NativeDB  # noqa: E402

OUT = os.environ.get("NATIVE_BENCH_OUT", "/tmp/native_store_bench.jsonl")


def emit(stage: str, **data) -> None:
    rec = {"stage": stage, **data}
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main() -> None:
    n_blocks = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    block_kb = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    part_size = 4096
    payload = os.urandom(block_kb * 1024)
    parts = [
        payload[i : i + part_size] for i in range(0, len(payload), part_size)
    ]
    home = tempfile.mkdtemp(prefix="native-bench-")
    db = NativeDB(os.path.join(home, "blockstore.db"))
    try:
        rss0 = rss_mb()
        t0 = time.perf_counter()
        worst_batch = 0.0
        for h in range(1, n_blocks + 1):
            hb = h.to_bytes(8, "big")
            sets = [(b"H:" + hb, b"meta" * 8), (b"C:" + hb, payload[:512])]
            for i, part in enumerate(parts):
                sets.append((b"P:" + hb + i.to_bytes(2, "big"), part))
            tb = time.perf_counter()
            db.write_batch(sets)
            worst_batch = max(worst_batch, time.perf_counter() - tb)
            if h % 997 == 0:  # interleaved reads, like gossip serving
                for rh in (1, h // 2, h):
                    db.get(b"H:" + rh.to_bytes(8, "big"))
        dt = time.perf_counter() - t0
        keys = n_blocks * (2 + len(parts))
        emit(
            "write",
            blocks=n_blocks,
            block_kb=block_kb,
            blocks_per_s=round(n_blocks / dt, 1),
            mb_per_s=round(n_blocks * block_kb / 1024 / dt, 1),
            worst_batch_ms=round(worst_batch * 1e3, 1),
            keys=keys,
            index_rss_mb=round(rss_mb() - rss0, 1),
            rss_bytes_per_key=round((rss_mb() - rss0) * 1048576 / keys, 1),
        )

        t0 = time.perf_counter()
        nreads = 5_000
        for i in range(nreads):
            h = 1 + (i * 9973) % n_blocks
            hb = h.to_bytes(8, "big")
            assert db.get(b"H:" + hb) is not None
            db.get(b"P:" + hb + (0).to_bytes(2, "big"))
        dt = time.perf_counter() - t0
        emit("read", reads=2 * nreads, reads_per_s=round(2 * nreads / dt, 1))

        # iterate a 1000-block range (RPC blockchain_info pattern)
        t0 = time.perf_counter()
        n = sum(
            1
            for _ in db.iterator(
                b"H:" + (1).to_bytes(8, "big"),
                b"H:" + (1001).to_bytes(8, "big"),
            )
        )
        emit("scan", rows=n, seconds=round(time.perf_counter() - t0, 3))

        # prune the first half (retain-height advance), then compact
        t0 = time.perf_counter()
        for h in range(1, n_blocks // 2 + 1):
            hb = h.to_bytes(8, "big")
            dels = [b"H:" + hb, b"C:" + hb] + [
                b"P:" + hb + i.to_bytes(2, "big") for i in range(len(parts))
            ]
            db.write_batch([], dels)
        prune_s = time.perf_counter() - t0

        # compaction runs freeze-and-chase: the metric that matters is
        # the WRITER stall while it runs, not its wall time — keep
        # writing during compact() and record the worst batch latency
        import threading

        t0 = time.perf_counter()
        done = threading.Event()
        stall = {"worst_ms": 0.0, "writes": 0}

        def write_during_compact():
            h = n_blocks
            while not done.is_set():
                h += 1
                hb = h.to_bytes(8, "big")
                tb = time.perf_counter()
                db.write_batch([(b"H:" + hb, b"meta" * 8)])
                stall["worst_ms"] = max(
                    stall["worst_ms"], (time.perf_counter() - tb) * 1e3
                )
                stall["writes"] += 1

        wt = threading.Thread(target=write_during_compact)
        wt.start()
        try:
            db.compact()
        finally:
            # the writer must stop BEFORE any close: a batch in flight
            # against a freed native handle is a use-after-free
            compact_s = time.perf_counter() - t0
            done.set()
            wt.join()
        emit(
            "prune",
            pruned_blocks=n_blocks // 2,
            prune_s=round(prune_s, 1),
            compact_total_s=round(compact_s, 2),
            worst_write_stall_ms=round(stall["worst_ms"], 1),
            writes_during_compact=stall["writes"],
            disk_mb=round(
                sum(
                    os.path.getsize(os.path.join(home, f))
                    for f in os.listdir(home)
                    if os.path.isfile(os.path.join(home, f))
                )
                / 1048576,
                1,
            ),
        )

        # survivors still readable after compaction
        hb = (n_blocks).to_bytes(8, "big")
        assert db.get(b"H:" + hb) is not None
        assert db.get(b"H:" + (1).to_bytes(8, "big")) is None
        emit("done", ok=True)
    finally:
        db.close()
        shutil.rmtree(home, ignore_errors=True)


if __name__ == "__main__":
    main()
