"""Pure device-side comb kernel time at the flagship 10k shape.

Times _device_verify on DEVICE-RESIDENT inputs (block_until_ready, no
host->device transfer or result fetch inside the timed region) — i.e.
the number a locally attached chip would see for the compute itself,
isolating the tunnel terms recorded in BASELINE.md.  Writes one JSON
line per stage like tpu_measure_all.py.
"""
import json
import os
import sys
import threading
import time

# Hard self-timeout: a wedged tunnel blocks PJRT calls in C++ where
# Python signal handlers never run; a daemon timer + os._exit is the only
# reliable bail (same pattern as tpu_probe.py).  Exiting is safe — a
# wedged session is lost either way, and a zombie profiler would hold
# its claim forever in front of the round-end bench.
_DEADLINE_S = int(os.environ.get("KERNEL_PROF_TIMEOUT", "1800"))
_watchdog = threading.Timer(
    _DEADLINE_S,
    lambda: (print(f"TIMEOUT after {_DEADLINE_S}s", flush=True), os._exit(3)),
)
_watchdog.daemon = True
_watchdog.start()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

OUT = os.environ.get("KERNEL_PROF_OUT", "/tmp/kernel_10k.jsonl")


def emit(**kw):
    rec = {"ts": time.time(), **kw}
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def main():
    import jax

    emit(stage="backend", platform=jax.devices()[0].platform)
    from __graft_entry__ import _enable_compile_cache

    _enable_compile_cache()
    import jax.numpy as jnp

    from cometbft_tpu.crypto import ed25519 as host
    from cometbft_tpu.models import comb_verifier as cv

    V = int(os.environ.get("KERNEL_PROF_V", "10000"))
    rng = np.random.default_rng(7)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(V)]
    pubs = [k.pub_key().data for k in keys]
    t0 = time.perf_counter()
    entry = cv.global_cache().ensure(pubs)
    emit(stage="table_build", v=V, s=round(time.perf_counter() - t0, 1))

    bv = cv.CombBatchVerifier(entry)
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|kp"
        bv.add(pubs[i], msg, sk.sign(msg))
    # reuse submit()'s own assembly, then re-run the jitted program on the
    # SAME device arrays to time compute alone
    ticket = bv.submit()
    all_ok, per = bv.collect(ticket)
    assert all_ok and len(per) == V

    # rebuild the device args exactly as submit() does, staged once
    payload = cv.assemble_payload(
        bv._items, np.asarray(bv._rows, np.int64), entry.vpad
    )
    dev_payload = jnp.asarray(payload)
    dev_payload.block_until_ready()

    fn = bv._verify_fn()
    out = fn(entry.tables, entry.valid, entry.pubs, dev_payload)
    out.block_until_ready()
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        out = fn(entry.tables, entry.valid, entry.pubs, dev_payload)
        out.block_until_ready()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    emit(
        stage="kernel_device_resident",
        v=V,
        p50_ms=round(1e3 * ts[len(ts) // 2], 2),
        min_ms=round(1e3 * ts[0], 2),
        max_ms=round(1e3 * ts[-1], 2),
    )
    # the residual end-to-end call on the same process for comparison
    t0 = time.perf_counter()
    ok2, _ = bv.collect(bv.submit())
    emit(
        stage="full_call_same_process",
        ok=bool(ok2),
        ms=round(1e3 * (time.perf_counter() - t0), 2),
    )
    emit(stage="done")


if __name__ == "__main__":
    main()
