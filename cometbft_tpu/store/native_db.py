"""ctypes binding for the native C++ storage engine (native/kvstore.cc).

The native engine is the analogue of the reference's pebble backend
(db/pebbledb.go): an ordered, batched, crash-safe persistent KV store —
append-only CRC-framed value log + in-memory ordered index, compacted in
place.  Batches are fsync'd, so the per-height write unit is durable the
way the reference's pebble WAL makes it.

The shared object is built from source on first use when missing (the
repo ships no binaries); `make -C native` does the same.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .db import DB

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libcometkv.so"))

_lib = None
_lib_mtx = threading.Lock()


class NativeDBError(Exception):
    pass


def _load_lib():
    global _lib
    with _lib_mtx:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH):
            src = os.path.join(_NATIVE_DIR, "kvstore.cc")
            if not os.path.exists(src):
                raise NativeDBError(f"native source missing: {src}")
            subprocess.run(
                [
                    os.environ.get("CXX", "g++"),
                    "-O2", "-fPIC", "-std=c++17", "-shared",
                    "-o", _SO_PATH, src,
                ],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO_PATH)
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_open.argtypes = [ctypes.c_char_p]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_get.restype = ctypes.c_int64
        lib.kv_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.kv_free.argtypes = [ctypes.c_void_p]
        lib.kv_has.restype = ctypes.c_int
        lib.kv_has.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.kv_write_batch.restype = ctypes.c_int
        lib.kv_write_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.kv_range.restype = ctypes.c_void_p
        lib.kv_range.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int,
        ]
        lib.kv_iter_next.restype = ctypes.c_int
        lib.kv_iter_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kv_iter_close.argtypes = [ctypes.c_void_p]
        lib.kv_size.restype = ctypes.c_uint64
        lib.kv_size.argtypes = [ctypes.c_void_p]
        lib.kv_compact.restype = ctypes.c_int
        lib.kv_compact.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeDB(DB):
    """DB interface over the C++ engine."""

    def __init__(self, path: str):
        self._lib = _load_lib()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._h = self._lib.kv_open(path.encode())
        if not self._h:
            raise NativeDBError(f"failed to open native store at {path}")

    def get(self, key: bytes) -> bytes | None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.kv_get(self._h, key, len(key), ctypes.byref(out))
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.kv_free(out)

    def has(self, key: bytes) -> bool:
        return bool(self._lib.kv_has(self._h, key, len(key)))

    def set(self, key: bytes, value: bytes) -> None:
        self.write_batch([(key, value)])

    def delete(self, key: bytes) -> None:
        self.write_batch([], [key])

    def write_batch(self, sets, deletes=()) -> None:
        buf = bytearray()
        for k, v in sets:
            buf += bytes([1])
            buf += len(k).to_bytes(4, "little")
            buf += len(v).to_bytes(4, "little")
            buf += k
            buf += v
        for k in deletes:
            buf += bytes([2])
            buf += len(k).to_bytes(4, "little")
            buf += (0).to_bytes(4, "little")
            buf += k
        if not buf:
            return
        if not self._lib.kv_write_batch(self._h, bytes(buf), len(buf)):
            raise NativeDBError("batch write failed")

    def _iter(self, start, end, reverse):
        it = self._lib.kv_range(
            self._h,
            start or b"", len(start or b""),
            end or b"", len(end or b""),
            1 if reverse else 0,
        )
        try:
            kp = ctypes.POINTER(ctypes.c_uint8)()
            vp = ctypes.POINTER(ctypes.c_uint8)()
            kn = ctypes.c_uint64()
            vn = ctypes.c_uint64()
            while self._lib.kv_iter_next(
                it, ctypes.byref(kp), ctypes.byref(kn),
                ctypes.byref(vp), ctypes.byref(vn),
            ):
                k = ctypes.string_at(kp, kn.value)
                v = ctypes.string_at(vp, vn.value)
                self._lib.kv_free(kp)
                self._lib.kv_free(vp)
                yield k, v
        finally:
            self._lib.kv_iter_close(it)

    def iterator(self, start=None, end=None):
        return self._iter(start, end, False)

    def reverse_iterator(self, start=None, end=None):
        return self._iter(start, end, True)

    def compact(self) -> None:
        # blocks the CALLER until a full pass reclaims space (waiting
        # out any in-flight background run); concurrent writers only
        # stall for the final tail-copy + rename
        if self._lib.kv_compact(self._h) == 0:
            raise NativeDBError("compaction failed")

    def size(self) -> int:
        return int(self._lib.kv_size(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None
