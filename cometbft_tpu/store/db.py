"""KV database abstraction (reference: db/db.go:24 — Get/Set/Delete/
Iterator/ReverseIterator/Batch/Close, plus prefixdb namespacing
db/prefixdb.go).

Backends:
  MemDB    — sorted in-memory dict (reference NewInMem, used by tests and
             statesync temp state).
  SQLiteDB — persistent single-file store (stands in for the reference's
             pebble LSM; swap-in point for the C++ engine).
  PrefixDB — key-namespace view over another DB.
"""

from __future__ import annotations

import bisect
import sqlite3
import threading
from typing import Iterator


class DB:
    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def iterator(self, start: bytes | None = None, end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Ascending iteration over [start, end)."""
        raise NotImplementedError

    def reverse_iterator(self, start: bytes | None = None, end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Descending iteration over [start, end)."""
        raise NotImplementedError

    def write_batch(self, sets: list[tuple[bytes, bytes]], deletes: list[bytes] = ()) -> None:
        """Atomic batch write (db.go Batch)."""
        raise NotImplementedError

    def close(self) -> None: ...

    def compact(self) -> None: ...


class MemDB(DB):
    def __init__(self):
        self._d: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._d.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if key is None or value is None:
            raise ValueError("nil key or value")
        with self._mtx:
            if key not in self._d:
                bisect.insort(self._keys, key)
            self._d[key] = value

    def delete(self, key: bytes) -> None:
        with self._mtx:
            if key in self._d:
                del self._d[key]
                i = bisect.bisect_left(self._keys, key)
                del self._keys[i]

    def _range(self, start, end):
        lo = bisect.bisect_left(self._keys, start) if start is not None else 0
        hi = bisect.bisect_left(self._keys, end) if end is not None else len(self._keys)
        return lo, hi

    def iterator(self, start=None, end=None):
        with self._mtx:
            lo, hi = self._range(start, end)
            snapshot = [(k, self._d[k]) for k in self._keys[lo:hi]]
        yield from snapshot

    def reverse_iterator(self, start=None, end=None):
        with self._mtx:
            lo, hi = self._range(start, end)
            snapshot = [(k, self._d[k]) for k in reversed(self._keys[lo:hi])]
        yield from snapshot

    def write_batch(self, sets, deletes=()):
        with self._mtx:
            for k, v in sets:
                self.set(k, v)
            for k in deletes:
                self.delete(k)


class SQLiteDB(DB):
    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mtx = threading.RLock()
        with self._mtx:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.commit()

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            row = self._conn.execute("SELECT v FROM kv WHERE k=?", (key,)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (key, value),
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._conn.execute("DELETE FROM kv WHERE k=?", (key,))
            self._conn.commit()

    def _bounds(self, start, end, desc=False):
        cond, args = [], []
        if start is not None:
            cond.append("k >= ?")
            args.append(start)
        if end is not None:
            cond.append("k < ?")
            args.append(end)
        where = (" WHERE " + " AND ".join(cond)) if cond else ""
        order = " ORDER BY k DESC" if desc else " ORDER BY k ASC"
        return f"SELECT k, v FROM kv{where}{order}", args

    def iterator(self, start=None, end=None):
        q, args = self._bounds(start, end)
        with self._mtx:
            rows = self._conn.execute(q, args).fetchall()
        yield from ((bytes(k), bytes(v)) for k, v in rows)

    def reverse_iterator(self, start=None, end=None):
        q, args = self._bounds(start, end, desc=True)
        with self._mtx:
            rows = self._conn.execute(q, args).fetchall()
        yield from ((bytes(k), bytes(v)) for k, v in rows)

    def write_batch(self, sets, deletes=()):
        with self._mtx:
            self._conn.executemany(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                list(sets),
            )
            if deletes:
                self._conn.executemany("DELETE FROM kv WHERE k=?", [(k,) for k in deletes])
            self._conn.commit()

    def close(self) -> None:
        with self._mtx:
            self._conn.close()

    def compact(self) -> None:
        with self._mtx:
            self._conn.execute("VACUUM")
            self._conn.commit()


def _prefix_end(prefix: bytes) -> bytes | None:
    """Smallest byte string greater than every key with this prefix."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return None


class PrefixDB(DB):
    """Namespaced view (db/prefixdb.go)."""

    def __init__(self, db: DB, prefix: bytes):
        self._db = db
        self._prefix = prefix

    def _k(self, key: bytes) -> bytes:
        return self._prefix + key

    def get(self, key):
        return self._db.get(self._k(key))

    def set(self, key, value):
        self._db.set(self._k(key), value)

    def delete(self, key):
        self._db.delete(self._k(key))

    def _strip(self, it):
        n = len(self._prefix)
        for k, v in it:
            yield k[n:], v

    def iterator(self, start=None, end=None):
        s = self._k(start) if start is not None else self._prefix
        e = self._k(end) if end is not None else _prefix_end(self._prefix)
        return self._strip(self._db.iterator(s, e))

    def reverse_iterator(self, start=None, end=None):
        s = self._k(start) if start is not None else self._prefix
        e = self._k(end) if end is not None else _prefix_end(self._prefix)
        return self._strip(self._db.reverse_iterator(s, e))

    def write_batch(self, sets, deletes=()):
        self._db.write_batch(
            [(self._k(k), v) for k, v in sets], [self._k(k) for k in deletes]
        )


def new_db(name: str, backend: str = "sqlite", db_dir: str = ".") -> DB:
    """DBProvider (reference config/db.go:30)."""
    if backend in ("mem", "memdb"):
        return MemDB()
    if backend == "sqlite":
        import os

        os.makedirs(db_dir, exist_ok=True)
        return SQLiteDB(os.path.join(db_dir, f"{name}.db"))
    raise ValueError(f"unknown db backend {backend!r}")
