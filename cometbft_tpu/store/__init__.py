"""L4 storage: KV DB abstraction + block store.

Reference: db/ (pebble-backed KV, db/db.go:24), store/ (block store,
store/store.go).  Backends here: in-memory (tests, statesync temp stores)
and SQLite-backed persistent store; the C++ LSM backend slots in behind
the same interface.
"""

from .db import DB, MemDB, SQLiteDB, PrefixDB, new_db
from .block_store import BlockStore
