"""BlockStore: persisted blocks as meta + parts + commits, keyed by height
and hash (reference: store/store.go — SaveBlock:587, LoadBlock:222,
LoadBlockCommit:372, LoadSeenCommit:440, PruneBlocks:474).
"""

from __future__ import annotations

import struct
import threading

from ..types.block import Block, BlockID, Commit, ExtendedCommit
from ..types.part_set import Part, PartSet
from ..wire import types_pb as pb
from .db import DB
from ..utils.metrics import hub as _metrics_hub


def _timed(fn):
    """Store-op latency observer (reference: store metricsgen
    BlockStoreAccessDurationSeconds, labeled by method)."""
    import functools
    import time as _t

    @functools.wraps(fn)
    def wrap(*a, **kw):
        t0 = _t.perf_counter()
        try:
            return fn(*a, **kw)
        finally:
            _metrics_hub().store_access_seconds.observe(
                _t.perf_counter() - t0, method=fn.__name__
            )

    return wrap

_STATE_KEY = b"blockStore"


def _h(prefix: bytes, height: int) -> bytes:
    return prefix + struct.pack(">q", height)


class BlockStore:
    """Thread-safe block store with base/height tracking and pruning."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.RLock()
        self.base = 0
        self.height = 0
        raw = db.get(_STATE_KEY)
        if raw:
            self.base, self.height = struct.unpack(">qq", raw)

    def _save_state(self) -> list[tuple[bytes, bytes]]:
        return [(_STATE_KEY, struct.pack(">qq", self.base, self.height))]

    def size(self) -> int:
        with self._mtx:
            return self.height - self.base + 1 if self.height else 0

    # ------------------------------------------------------------- save

    @_timed
    def save_block(self, block: Block, part_set: PartSet, seen_commit: Commit) -> None:
        """(store.go:587)."""
        self._save(block, part_set, seen_commit, None)

    @_timed
    def save_block_with_extended_commit(
        self, block: Block, part_set: PartSet, seen_extended_commit: ExtendedCommit
    ) -> None:
        """(store.go:619)."""
        self._save(block, part_set, seen_extended_commit.to_commit(), seen_extended_commit)

    def _save(self, block, part_set, seen_commit, ext_commit):
        height = block.header.height
        with self._mtx:
            if self.height > 0 and height != self.height + 1:
                raise ValueError(
                    f"BlockStore can only save contiguous blocks. Wanted {self.height + 1}, got {height}"
                )
            if not part_set.is_complete():
                raise ValueError("BlockStore can only save complete block part sets")
            block_id = BlockID(hash=block.hash(), part_set_header=part_set.header)
            meta = pb.BlockMeta(
                block_id=block_id.to_proto(),
                block_size=part_set.byte_size,
                header=block.header.to_proto(),
                num_txs=len(block.data.txs),
            )
            sets = [
                (_h(b"H:", height), meta.encode()),
                (b"BH:" + block.hash(), struct.pack(">q", height)),
                (_h(b"SC:", height), seen_commit.to_proto().encode()),
            ]
            for i in range(part_set.header.total):
                part = part_set.get_part(i)
                sets.append((_h(b"P:", height) + struct.pack(">I", i), part.to_proto().encode()))
            if block.last_commit is not None:
                sets.append((_h(b"C:", height - 1), block.last_commit.to_proto().encode()))
            if ext_commit is not None:
                sets.append((_h(b"EC:", height), ext_commit.to_proto().encode()))
            if self.base == 0:
                self.base = height
            self.height = height
            sets += self._save_state()
            self._db.write_batch(sets)

    def save_seen_commit(self, height: int, seen_commit: Commit) -> None:
        self._db.set(_h(b"SC:", height), seen_commit.to_proto().encode())

    def delete_latest_block(self) -> None:
        """Drop the newest block (store.go DeleteLatestBlock; rollback)."""
        with self._mtx:
            height = self.height
            if height == 0:
                raise ValueError("block store is empty")
            meta = self.load_block_meta(height)
            deletes = [_h(b"H:", height), _h(b"SC:", height), _h(b"EC:", height), _h(b"C:", height - 1)]
            if meta is not None and meta.block_id is not None:
                deletes.append(b"BH:" + meta.block_id.hash)
                total = (meta.block_id.part_set_header or pb.PartSetHeader()).total
                for i in range(total):
                    deletes.append(_h(b"P:", height) + struct.pack(">I", i))
            self.height = height - 1
            if self.height < self.base:
                self.base = self.height
            self._db.write_batch(self._save_state(), deletes)

    # ------------------------------------------------------------- load

    def load_block_meta(self, height: int) -> pb.BlockMeta | None:
        raw = self._db.get(_h(b"H:", height))
        return pb.BlockMeta.decode(raw) if raw else None

    @_timed
    def load_block(self, height: int) -> Block | None:
        """Reassemble a block from its parts (store.go:222)."""
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        chunks = []
        total = (meta.block_id.part_set_header or pb.PartSetHeader()).total
        for i in range(total):
            raw = self._db.get(_h(b"P:", height) + struct.pack(">I", i))
            if raw is None:
                return None
            chunks.append(pb.Part.decode(raw).bytes)
        return Block.decode(b"".join(chunks))

    def load_block_by_hash(self, block_hash: bytes) -> Block | None:
        raw = self._db.get(b"BH:" + block_hash)
        if raw is None:
            return None
        return self.load_block(struct.unpack(">q", raw)[0])

    def load_block_part(self, height: int, index: int) -> Part | None:
        raw = self._db.get(_h(b"P:", height) + struct.pack(">I", index))
        return Part.from_proto(pb.Part.decode(raw)) if raw else None

    def load_block_commit(self, height: int) -> Commit | None:
        """The canonical +2/3 commit FOR height (in block height+1's
        LastCommit) (store.go:372)."""
        raw = self._db.get(_h(b"C:", height))
        return Commit.from_proto(pb.Commit.decode(raw)) if raw else None

    def load_seen_commit(self, height: int) -> Commit | None:
        """(store.go:440)."""
        raw = self._db.get(_h(b"SC:", height))
        return Commit.from_proto(pb.Commit.decode(raw)) if raw else None

    def load_block_extended_commit(self, height: int) -> ExtendedCommit | None:
        raw = self._db.get(_h(b"EC:", height))
        return ExtendedCommit.from_proto(pb.ExtendedCommit.decode(raw)) if raw else None

    def load_base_meta(self) -> pb.BlockMeta | None:
        with self._mtx:
            return self.load_block_meta(self.base) if self.base else None

    # ------------------------------------------------------------- prune

    def prune_blocks(self, retain_height: int) -> int:
        """Remove blocks below retain_height (store.go:474)."""
        with self._mtx:
            if retain_height <= self.base:
                return 0
            if retain_height > self.height:
                raise ValueError("cannot prune beyond the latest height")
            pruned = 0
            deletes = []
            for h in range(self.base, retain_height):
                meta = self.load_block_meta(h)
                if meta is None:
                    continue
                deletes.append(_h(b"H:", h))
                deletes.append(b"BH:" + (meta.block_id.hash if meta.block_id else b""))
                deletes.append(_h(b"SC:", h))
                deletes.append(_h(b"C:", h - 1))
                deletes.append(_h(b"EC:", h))
                total = (meta.block_id.part_set_header or pb.PartSetHeader()).total
                for i in range(total):
                    deletes.append(_h(b"P:", h) + struct.pack(">I", i))
                pruned += 1
            self.base = retain_height
            self._db.write_batch(self._save_state(), deletes)
            return pruned
