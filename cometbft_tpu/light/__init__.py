"""Light client (reference: light/): verifier, bisection client,
divergence detector, providers, trusted store.
"""

from .client import (
    SEQUENTIAL,
    SKIPPING,
    Client,
    ErrNoWitnesses,
    TrustOptions,
)
from .detector import (
    DivergenceError,
    ErrFailedHeaderCrossReferencing,
    ErrLightClientAttackDetected,
    detect_divergence,
)
from .provider import (
    BlockStoreProvider,
    ErrBadLightBlock,
    ErrHeightTooHigh,
    ErrLightBlockNotFound,
    Provider,
    ProviderError,
)
from .store import LightStore
from .verifier import (
    DEFAULT_TRUST_LEVEL,
    ErrInvalidHeader,
    ErrNewValSetCantBeTrusted,
    ErrOldHeaderExpired,
    LightClientError,
    header_expired,
    validate_trust_level,
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)

__all__ = [
    "Client",
    "TrustOptions",
    "SEQUENTIAL",
    "SKIPPING",
    "ErrNoWitnesses",
    "LightStore",
    "Provider",
    "BlockStoreProvider",
    "ProviderError",
    "ErrLightBlockNotFound",
    "ErrHeightTooHigh",
    "ErrBadLightBlock",
    "detect_divergence",
    "DivergenceError",
    "ErrLightClientAttackDetected",
    "ErrFailedHeaderCrossReferencing",
    "verify",
    "verify_adjacent",
    "verify_non_adjacent",
    "verify_backwards",
    "validate_trust_level",
    "header_expired",
    "DEFAULT_TRUST_LEVEL",
    "LightClientError",
    "ErrInvalidHeader",
    "ErrOldHeaderExpired",
    "ErrNewValSetCantBeTrusted",
]
