"""Light-client verification math (reference: light/verifier.go).

verify_adjacent / verify_non_adjacent / verify sit directly on the
commit-verification family (types/validation.py), which routes large
validator sets to the TPU batch verifier; the two passes of a
non-adjacent check (1/3-trusting over the old set, then 2/3 over the
new) share a SignatureCache so no signature is verified twice
(verifier.go:57,72).
"""

from __future__ import annotations

from fractions import Fraction

from ..types.validation import (
    NotEnoughVotingPowerError,
    SignatureCache,
    verify_commit_light,
    verify_commit_light_trusting,
)
from ..verifysvc.service import Klass as _VerifyKlass

DEFAULT_TRUST_LEVEL = Fraction(1, 3)
DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * 1_000_000_000  # client.go:38

NS = 1_000_000_000


class LightClientError(Exception):
    pass


class ErrOldHeaderExpired(LightClientError):
    def __init__(self, expired_at_ns: int, now_ns: int):
        super().__init__(
            f"old header expired at {expired_at_ns} (now {now_ns}): outside "
            "of trusting period"
        )


class ErrInvalidHeader(LightClientError):
    pass


class ErrNewValSetCantBeTrusted(LightClientError):
    """< trustLevel of the trusted set signed the new header — bisect."""


class ErrInvalidTrustLevel(LightClientError):
    pass


def validate_trust_level(lvl: Fraction) -> None:
    """[1/3, 1] (verifier.go:160)."""
    if (
        lvl.denominator == 0
        or lvl.numerator * 3 < lvl.denominator
        or lvl.numerator > lvl.denominator
    ):
        raise ErrInvalidTrustLevel(f"trust level {lvl} not in [1/3, 1]")


def header_expired(signed_header, trusting_period_ns: int, now_ns: int) -> bool:
    """verifier.go:176."""
    return signed_header.header.time.unix_ns() + trusting_period_ns <= now_ns


def _verify_new_header_and_vals(
    untrusted_sh, untrusted_vals, trusted_sh, now_ns: int, max_clock_drift_ns: int
) -> None:
    """verifier.go:135."""
    try:
        untrusted_sh.validate_basic(trusted_sh.header.chain_id)
    except Exception as e:  # noqa: BLE001
        raise ErrInvalidHeader(f"header validate basic: {e}") from e
    if untrusted_sh.header.height <= trusted_sh.header.height:
        raise ErrInvalidHeader(
            f"header height {untrusted_sh.header.height} not greater than "
            f"trusted {trusted_sh.header.height}"
        )
    if untrusted_sh.header.time.unix_ns() <= trusted_sh.header.time.unix_ns():
        raise ErrInvalidHeader("header time not monotonically increasing")
    if untrusted_sh.header.time.unix_ns() >= now_ns + max_clock_drift_ns:
        raise ErrInvalidHeader(
            f"new header time {untrusted_sh.header.time} exceeds max clock "
            f"drift past now"
        )
    if untrusted_sh.header.validators_hash != untrusted_vals.hash():
        raise ErrInvalidHeader(
            f"header validators hash {untrusted_sh.header.validators_hash.hex()} "
            f"does not match supplied set {untrusted_vals.hash().hex()}"
        )


def verify_adjacent(
    trusted_sh,
    untrusted_sh,
    untrusted_vals,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
) -> None:
    """verifier.go:92 — next-vals linkage + 2/3 of the new set."""
    if untrusted_sh.header.height != trusted_sh.header.height + 1:
        raise ErrInvalidHeader("headers must be adjacent in height")
    if header_expired(trusted_sh, trusting_period_ns, now_ns):
        raise ErrOldHeaderExpired(
            trusted_sh.header.time.unix_ns() + trusting_period_ns, now_ns
        )
    _verify_new_header_and_vals(
        untrusted_sh, untrusted_vals, trusted_sh, now_ns, max_clock_drift_ns
    )
    if untrusted_sh.header.validators_hash != trusted_sh.header.next_validators_hash:
        raise ErrInvalidHeader(
            f"header next validators {trusted_sh.header.next_validators_hash.hex()} "
            f"do not match new validators {untrusted_sh.header.validators_hash.hex()}"
        )
    try:
        verify_commit_light(
            trusted_sh.header.chain_id,
            untrusted_vals,
            untrusted_sh.commit.block_id,
            untrusted_sh.header.height,
            untrusted_sh.commit,
            klass=_VerifyKlass.BACKGROUND,
        )
    except Exception as e:  # noqa: BLE001
        raise ErrInvalidHeader(f"invalid commit: {e}") from e


def verify_non_adjacent(
    trusted_sh,
    trusted_vals,
    untrusted_sh,
    untrusted_vals,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """verifier.go:30 — 1/3-trusting of the old set + 2/3 of the new,
    sharing one SignatureCache across the two passes."""
    if untrusted_sh.header.height == trusted_sh.header.height + 1:
        raise ErrInvalidHeader("headers must be non-adjacent in height")
    if header_expired(trusted_sh, trusting_period_ns, now_ns):
        raise ErrOldHeaderExpired(
            trusted_sh.header.time.unix_ns() + trusting_period_ns, now_ns
        )
    _verify_new_header_and_vals(
        untrusted_sh, untrusted_vals, trusted_sh, now_ns, max_clock_drift_ns
    )

    cache = SignatureCache()
    try:
        verify_commit_light_trusting(
            trusted_sh.header.chain_id,
            trusted_vals,
            untrusted_sh.commit,
            trust_level,
            cache=cache,
            klass=_VerifyKlass.BACKGROUND,
        )
    except NotEnoughVotingPowerError as e:
        raise ErrNewValSetCantBeTrusted(str(e)) from e

    # always last: untrusted_vals can be made arbitrarily large to DoS
    try:
        verify_commit_light(
            trusted_sh.header.chain_id,
            untrusted_vals,
            untrusted_sh.commit.block_id,
            untrusted_sh.header.height,
            untrusted_sh.commit,
            cache=cache,
            klass=_VerifyKlass.BACKGROUND,
        )
    except Exception as e:  # noqa: BLE001
        raise ErrInvalidHeader(f"invalid commit: {e}") from e


def verify(
    trusted_sh,
    trusted_vals,
    untrusted_sh,
    untrusted_vals,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """verifier.go:130 — dispatch on adjacency."""
    if untrusted_sh.header.height != trusted_sh.header.height + 1:
        verify_non_adjacent(
            trusted_sh,
            trusted_vals,
            untrusted_sh,
            untrusted_vals,
            trusting_period_ns,
            now_ns,
            max_clock_drift_ns,
            trust_level,
        )
    else:
        verify_adjacent(
            trusted_sh,
            untrusted_sh,
            untrusted_vals,
            trusting_period_ns,
            now_ns,
            max_clock_drift_ns,
        )


def verify_backwards(untrusted_header, trusted_header) -> None:
    """verifier.go:205 — hash-linked walk to an earlier height."""
    try:
        untrusted_header.validate_basic()
    except Exception as e:  # noqa: BLE001
        raise ErrInvalidHeader(str(e)) from e
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if untrusted_header.time.unix_ns() >= trusted_header.time.unix_ns():
        raise ErrInvalidHeader(
            "expected older header to have a time before the trusted header"
        )
    if trusted_header.last_block_id.hash != untrusted_header.hash():
        raise ErrInvalidHeader(
            f"trusted header's LastBlockID {trusted_header.last_block_id.hash.hex()} "
            f"does not match older header's hash {untrusted_header.hash().hex()}"
        )
