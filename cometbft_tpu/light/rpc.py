"""Light-client-verified RPC: an RPC client whose answers are checked
against light-client-verified headers before being returned
(reference: light/rpc/client.go, 676 LoC), plus the HTTP light-block
provider that feeds the light client from a full node's RPC
(light/provider/http).

Every result that commits to chain state is cross-checked:
  - block/commit: the fetched header must hash to the light client's
    verified header hash at that height (client.go Block/Commit).
  - validators: the fetched set must hash to the verified header's
    validators_hash (client.go Validators).
  - tx: the tx bytes must Merkle-prove into the verified header's
    data_hash (client.go Tx with inclusion proof).
  - abci_query: prove=True is forced and the ValueOp proof chain must
    verify against the app_hash of the NEXT verified header; responses
    without a verifiable proof are rejected (fail closed, matching
    light/rpc/client.go:129-134).  The kvstore app serves proofs when
    constructed with merkle_state=True; the plain parity-mode kvstore
    ships none, so verified queries against it error rather than trust.
"""

from __future__ import annotations

import base64
import datetime

from ..crypto.encoding import pubkey_from_type_and_bytes
from ..utils.log import get_logger
from ..types.tx import tx_hash, tx_proof
from ..types.block import BlockID, Commit, CommitSig, Header, PartSetHeader
from ..types.light_block import LightBlock, SignedHeader
from ..types.validators import Validator, ValidatorSet
from ..wire import types_pb as pb
from ..wire.canonical import Timestamp
from .provider import ErrBadLightBlock, ErrHeightTooHigh, ErrLightBlockNotFound

_log = get_logger("light.rpc")

_AMINO_TO_KEY_TYPE = {
    "tendermint/PubKeyEd25519": "ed25519",
    "tendermint/PubKeySecp256k1": "secp256k1",
    "cometbft/PubKeyBls12_381": "bls12_381",
    "cometbft/PubKeySecp256k1eth": "secp256k1eth",
}


# ---------------------------------------------------------------- parsers
# exact inverses of rpc/serializers.py


def _ts_from_rfc3339(s: str) -> Timestamp:
    # "1-01-01" tolerates pre-fix serializers whose %Y didn't zero-pad
    if not s or s.startswith("0001-01-01") or s.startswith("1-01-01"):
        return Timestamp()
    frac_ns = 0
    if "." in s:
        base, rest = s.split(".", 1)
        digits = rest.rstrip("Z")
        frac_ns = int(digits.ljust(9, "0")[:9])
        s = base + "Z"
    dt = datetime.datetime.strptime(s, "%Y-%m-%dT%H:%M:%SZ").replace(
        tzinfo=datetime.timezone.utc
    )
    return Timestamp.from_unix_ns(int(dt.timestamp()) * 10**9 + frac_ns)


def block_id_from_json(j: dict) -> BlockID:
    return BlockID(
        hash=bytes.fromhex(j["hash"]),
        part_set_header=PartSetHeader(
            total=j["parts"]["total"], hash=bytes.fromhex(j["parts"]["hash"])
        ),
    )


def header_from_json(j: dict) -> Header:
    return Header(
        version=pb.Consensus(
            block=int(j["version"]["block"]), app=int(j["version"].get("app", 0))
        ),
        chain_id=j["chain_id"],
        height=int(j["height"]),
        time=_ts_from_rfc3339(j["time"]),
        last_block_id=block_id_from_json(j["last_block_id"]),
        last_commit_hash=bytes.fromhex(j["last_commit_hash"]),
        data_hash=bytes.fromhex(j["data_hash"]),
        validators_hash=bytes.fromhex(j["validators_hash"]),
        next_validators_hash=bytes.fromhex(j["next_validators_hash"]),
        consensus_hash=bytes.fromhex(j["consensus_hash"]),
        app_hash=bytes.fromhex(j["app_hash"]),
        last_results_hash=bytes.fromhex(j["last_results_hash"]),
        evidence_hash=bytes.fromhex(j["evidence_hash"]),
        proposer_address=bytes.fromhex(j["proposer_address"]),
    )


def commit_from_json(j: dict) -> Commit:
    return Commit(
        height=int(j["height"]),
        round=j["round"],
        block_id=block_id_from_json(j["block_id"]),
        signatures=[
            CommitSig(
                block_id_flag=s["block_id_flag"],
                validator_address=bytes.fromhex(s["validator_address"]),
                timestamp=_ts_from_rfc3339(s["timestamp"]),
                signature=base64.b64decode(s["signature"]) if s["signature"] else b"",
            )
            for s in j["signatures"]
        ],
    )


def validator_set_from_json(vals_json: list[dict]) -> ValidatorSet:
    vals = []
    for v in vals_json:
        kt = _AMINO_TO_KEY_TYPE.get(v["pub_key"]["type"], v["pub_key"]["type"])
        pk = pubkey_from_type_and_bytes(kt, base64.b64decode(v["pub_key"]["value"]))
        val = Validator(
            pk, int(v["voting_power"]), int(v.get("proposer_priority", 0))
        )
        vals.append(val)
    return ValidatorSet(vals)


# --------------------------------------------------------------- provider


def _fetch_all_validators(rpc, height) -> list[dict]:
    """Page through /validators until the full set is in hand — the server
    clamps per_page, and a truncated set would fail the validators_hash
    check on every light block (provider/http paginates the same way)."""
    out: list[dict] = []
    page = 1
    while True:
        resp = rpc.validators(height, page=page, per_page=100)
        out.extend(resp["validators"])
        total = int(resp.get("total", len(out)))
        if len(out) >= total or not resp["validators"]:
            return out
        page += 1


class HTTPProvider:
    """light.Provider over a full node's JSON-RPC
    (reference: light/provider/http/http.go)."""

    def __init__(self, chain_id: str, rpc_client):
        self._chain_id = chain_id
        self.rpc = rpc_client

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        from ..rpc.client import RPCClientError

        try:
            commit_resp = self.rpc.commit(height or None)
            vals_json = _fetch_all_validators(self.rpc, height or None)
        except RPCClientError as e:
            if "not in store range" in str(e) or "must be less" in str(e):
                raise ErrHeightTooHigh(str(e)) from e
            raise ErrLightBlockNotFound(str(e)) from e
        sh = SignedHeader(
            header_from_json(commit_resp["signed_header"]["header"]),
            commit_from_json(commit_resp["signed_header"]["commit"]),
        )
        vs = validator_set_from_json(vals_json)
        lb = LightBlock(sh, vs)
        try:
            lb.validate_basic(self._chain_id)
        except Exception as e:  # noqa: BLE001
            raise ErrBadLightBlock(str(e)) from e
        return lb

    def report_evidence(self, ev) -> None:
        # broadcast_evidence over RPC (provider/http reports attacks back)
        try:
            self.rpc.call("broadcast_evidence", evidence=ev)
        except Exception as e:  # noqa: BLE001 — best-effort report to one provider
            _log.warning(f"evidence report to provider failed: {e!r}")

    def consensus_params(self, height: int):
        """params_source seam for the statesync state provider
        (statesync/stateprovider.go fetches params over RPC the same
        way); the caller verifies the result against the light-verified
        header's consensus_hash, so this source is untrusted."""
        from ..types.params import (
            BlockParams,
            ConsensusParams,
            EvidenceParams,
            FeatureParams,
            SynchronyParams,
            ValidatorParams,
            VersionParams,
        )

        j = self.rpc.call("consensus_params", height=height)["consensus_params"]
        return ConsensusParams(
            block=BlockParams(
                max_bytes=int(j["block"]["max_bytes"]),
                max_gas=int(j["block"]["max_gas"]),
            ),
            evidence=EvidenceParams(
                max_age_num_blocks=int(j["evidence"]["max_age_num_blocks"]),
                max_age_duration_ns=int(j["evidence"]["max_age_duration"]),
                max_bytes=int(j["evidence"]["max_bytes"]),
            ),
            validator=ValidatorParams(
                pub_key_types=list(j["validator"]["pub_key_types"])
            ),
            version=VersionParams(app=int(j.get("version", {}).get("app", 0))),
            synchrony=SynchronyParams(
                precision_ns=int(j["synchrony"]["precision"]),
                message_delay_ns=int(j["synchrony"]["message_delay"]),
            ),
            feature=FeatureParams(
                vote_extensions_enable_height=int(
                    j["feature"]["vote_extensions_enable_height"]
                ),
                pbts_enable_height=int(j["feature"]["pbts_enable_height"]),
            ),
        )


# ----------------------------------------------------------- verifying client


class AppQueryError(Exception):
    """abci_query returned a non-zero code.  The error itself is
    app-level and unverifiable, so nothing from the response may be
    trusted; the reference errors the same way (light/rpc/client.go
    ABCIQueryWithOptions: resp.IsErr() -> err)."""

    def __init__(self, code: int, log: str) -> None:
        super().__init__(f"abci_query failed: code={code} log={log!r}")
        self.code = code
        self.log = log


class VerificationFailed(Exception):
    pass


class VerifyingClient:
    """RPC client that refuses to return state it cannot verify
    (reference: light/rpc/client.go)."""

    def __init__(self, rpc_client, light_client, next_header_timeout: float = 15.0):
        self.rpc = rpc_client
        self.lc = light_client
        # how long abci_query waits for the header anchoring a fresh
        # query result (one block interval on a live chain)
        self.next_header_timeout = next_header_timeout

    # -- helpers

    def _resolve_height(self, height: int) -> int:
        """0/None = the chain's latest height (then verified like any
        other — the reference resolves latest the same way)."""
        if height:
            return height
        return int(self.rpc.status()["sync_info"]["latest_block_height"])

    def _verified_header(self, height: int) -> Header:
        lb = self.lc.verify_light_block_at_height(height)
        return lb.signed_header.header

    def status(self) -> dict:
        return self.rpc.status()

    def block(self, height: int = 0) -> dict:
        height = self._resolve_height(height)
        resp = self.rpc.block(height)
        got = header_from_json(resp["block"]["header"])
        want = self._verified_header(height)
        if got.hash() != want.hash():
            raise VerificationFailed(
                f"block {height}: header hash {got.hash().hex()} != verified "
                f"{want.hash().hex()}"
            )
        return resp

    def commit(self, height: int = 0) -> dict:
        height = self._resolve_height(height)
        resp = self.rpc.commit(height)
        got = header_from_json(resp["signed_header"]["header"])
        want = self._verified_header(height)
        if got.hash() != want.hash():
            raise VerificationFailed(f"commit {height}: header mismatch")
        return resp

    def validators(self, height: int = 0) -> dict:
        height = self._resolve_height(height)
        vals_json = _fetch_all_validators(self.rpc, height)
        vs = validator_set_from_json(vals_json)
        want = self._verified_header(height)
        if vs.hash() != want.validators_hash:
            raise VerificationFailed(
                f"validators {height}: set hash does not match verified header"
            )
        return {"block_height": str(height), "validators": vals_json,
                "count": str(len(vals_json)), "total": str(len(vals_json))}

    def tx(self, tx_hash_hex: str) -> dict:
        """Fetch a tx and prove its inclusion in the verified block's
        data_hash (client.go Tx: requires the proof)."""
        resp = self.rpc.call("tx", hash=tx_hash_hex)
        height = int(resp["height"])
        tx = base64.b64decode(resp["tx"])
        index = int(resp.get("index", 0))
        hdr = self._verified_header(height)
        blk = self.rpc.block(height)
        txs = [base64.b64decode(t) for t in blk["block"]["data"]["txs"]]
        if index >= len(txs) or txs[index] != tx:
            raise VerificationFailed("tx not at claimed index")
        root, proof = tx_proof(txs, index)
        if root != hdr.data_hash:
            raise VerificationFailed("tx set does not hash to verified data_hash")
        proof.verify(root, tx_hash(tx))  # leaves are TxIDs (types/tx.go:51)
        return resp

    def data_proof(self, height: int = 0, index: int = 0) -> dict:
        """Fetch an inclusion proof for tx ``index`` of block ``height``
        (the merkle_proof route, served by the node's PROOF plane) and
        verify it against the light-client-verified header's data_hash
        before returning it.  Unlike tx(), this never downloads the
        block's tx set — the proof alone anchors the returned leaf_hash
        (the TxID) to verified chain state; a caller holding the tx
        bytes completes the chain with tx_hash(tx) == leaf_hash."""
        from ..crypto import merkle

        height = self._resolve_height(height)
        want = self._verified_header(height)
        resp = self.rpc.call("merkle_proof", height=height, indices=str(index))
        # Everything the serving node controls parses inside this try:
        # malformed hex/base64/ints must surface as the same fail-closed
        # VerificationFailed as a wrong proof.
        try:
            root = bytes.fromhex(resp["root_hash"])
            total = int(resp["total"])
            rows = resp["proofs"]
            if len(rows) != 1:
                raise VerificationFailed(
                    f"data_proof: expected 1 proof, got {len(rows)}"
                )
            pj = rows[0]
            proof = merkle.Proof(
                total=int(pj["total"]),
                index=int(pj["index"]),
                leaf_hash=base64.b64decode(pj["leaf_hash"]),
                aunts=[base64.b64decode(a) for a in pj.get("aunts") or []],
            )
        except VerificationFailed:
            raise
        except Exception as e:  # noqa: BLE001 — fail closed on any garbage
            raise VerificationFailed(
                f"data_proof: malformed response: {e}"
            ) from e
        if proof.total != total or proof.index != index:
            raise VerificationFailed("data_proof: proof row does not match query")
        if root != want.data_hash:
            raise VerificationFailed(
                f"data_proof {height}: root {root.hex()} != verified "
                f"data_hash {want.data_hash.hex()}"
            )
        if proof.compute_root_hash() != want.data_hash:
            raise VerificationFailed(
                "data_proof: proof does not verify against data_hash"
            )
        return resp

    def abci_query(self, path: str, data: bytes, height: int = 0) -> dict:
        """Fail-closed verified query (reference: light/rpc/client.go:110-160
        ABCIQueryWithOptions forces opts.Prove and errors when the proof is
        missing or unverifiable).

        prove=True is always requested; the response's ValueOp proof chain
        is verified against the app hash of the NEXT verified header (the
        app hash of state at height h lands in header h+1).  Responses
        without a verifiable proof — including apps that ship no proofs,
        like the plain kvstore — are rejected, never trusted."""
        from ..crypto import merkle
        from ..wire import types_pb as tpb

        resp = self.rpc.abci_query(path, data, height=height, prove=True)
        r = resp["response"]
        # Everything a byzantine server controls parses inside this try:
        # malformed heights, base64, or proof bytes must surface as the
        # same fail-closed VerificationFailed as a wrong proof.
        try:
            code = int(r.get("code", 0) or 0)
            if code != 0:
                # Error responses carry no proof and cannot be verified;
                # returning them would hand a byzantine node's value/log/
                # height to callers that skip the code check.  Fail like
                # the reference (resp.IsErr() -> error).
                raise AppQueryError(code, str(r.get("log", "")))
            rh = int(r.get("height", 0) or 0)
            if rh <= 0:
                raise VerificationFailed("abci_query: response carries no height")
            value = base64.b64decode(r.get("value") or "")
            key = base64.b64decode(r.get("key") or "")
            ops_json = (r.get("proof_ops") or {}).get("ops") or []
            if not value:
                raise VerificationFailed(
                    "abci_query: empty value (absence proofs not supported)"
                )
            if not ops_json:
                raise VerificationFailed(
                    "abci_query: response carries no proof (fail closed)"
                )
            ops: list[merkle.ProofOp] = []
            for op in ops_json:
                if op.get("type") != "simple:v":
                    raise VerificationFailed(
                        f"abci_query: unregistered proof op {op.get('type')!r}"
                    )
                vop = tpb.ValueOpProto.decode(base64.b64decode(op["data"]))
                proof = merkle.Proof(
                    total=vop.proof.total,
                    index=vop.proof.index,
                    leaf_hash=vop.proof.leaf_hash,
                    aunts=list(vop.proof.aunts),
                )
                ops.append(merkle.ValueOp(base64.b64decode(op["key"]), proof))
        except (VerificationFailed, AppQueryError):
            raise
        except Exception as e:  # noqa: BLE001 — fail closed on any garbage
            raise VerificationFailed(f"abci_query: malformed response: {e}") from e
        # The proven root is the app hash of the NEXT header, which only
        # exists once block rh+1 commits — on a live chain that's one
        # block interval away.  Wait for it briefly instead of failing:
        # the captured value+proof stay anchored to state rh regardless
        # of later writes (client.go waits for the next header the same
        # way via WaitForHeight).
        import time as _time

        hdr = None
        deadline = _time.monotonic() + self.next_header_timeout
        while True:
            try:
                hdr = self._verified_header(rh + 1)
                break
            except (ErrHeightTooHigh, ErrLightBlockNotFound) as e:
                # genuinely not produced yet: wait one block interval
                if _time.monotonic() >= deadline:
                    raise VerificationFailed(
                        f"abci_query: header {rh + 1} unavailable: {e}"
                    ) from e
                _time.sleep(0.25)
            except Exception as e:  # noqa: BLE001
                # anything else (bad header, failed commit verification,
                # divergence) is a real verification failure: fail fast,
                # don't spin re-verifying a forged header for the timeout
                raise VerificationFailed(
                    f"abci_query: header {rh + 1} failed verification: {e}"
                ) from e
        keypath = merkle.key_path_to_string([key])
        try:
            merkle.ProofOperators(ops).verify_value(hdr.app_hash, keypath, value)
        except Exception as e:  # noqa: BLE001
            raise VerificationFailed(f"abci_query: proof invalid: {e}") from e
        return resp


# --------------------------------------------------------------- the proxy


class LightProxy:
    """`light` daemon: a JSON-RPC server whose handlers go through the
    VerifyingClient (reference: light/proxy/proxy.go + routes.go)."""

    def __init__(self, verifying_client: VerifyingClient):
        self.vc = verifying_client
        self._httpd = None
        self.listen_addr: str | None = None

    def start(self, addr: str) -> None:
        import json as _json
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        vc = self.vc

        ROUTES = {
            "status": lambda p: vc.status(),
            "block": lambda p: vc.block(int(p.get("height") or 0)),
            "commit": lambda p: vc.commit(int(p.get("height") or 0)),
            "validators": lambda p: vc.validators(int(p.get("height") or 0)),
            "tx": lambda p: vc.tx(p["hash"]),
            "data_proof": lambda p: vc.data_proof(
                int(p.get("height") or 0), int(p.get("index") or 0)
            ),
            "abci_query": lambda p: vc.abci_query(
                p.get("path", ""),
                base64.b64decode(p.get("data", "")),
                height=int(p.get("height") or 0),
            ),
            "broadcast_tx_sync": lambda p: vc.rpc.broadcast_tx_sync(
                base64.b64decode(p["tx"])
            ),
            "broadcast_tx_commit": lambda p: vc.rpc.broadcast_tx_commit(
                base64.b64decode(p["tx"])
            ),
        }

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = _json.loads(self.rfile.read(n))
                    method = req.get("method", "")
                    params = req.get("params") or {}
                    fn = ROUTES.get(method)
                    if fn is None:
                        out = {
                            "jsonrpc": "2.0",
                            "id": req.get("id"),
                            "error": {"code": -32601, "message": "method not found"},
                        }
                    else:
                        out = {
                            "jsonrpc": "2.0",
                            "id": req.get("id"),
                            "result": fn(params),
                        }
                except Exception as e:  # noqa: BLE001
                    out = {
                        "jsonrpc": "2.0",
                        "id": None,
                        "error": {"code": -32603, "message": str(e)},
                    }
                body = _json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        host, _, port = addr.rpartition(":")
        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)
        self.listen_addr = f"{self._httpd.server_address[0]}:{self._httpd.server_address[1]}"
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="light-proxy"
        ).start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
