"""Light-block providers (reference: light/provider/).

A provider serves LightBlocks for a chain and accepts evidence reports.
The in-process BlockStoreProvider (the analogue of the reference's
`provider/http` pointed at a local node) backs tests and statesync's
state provider; an RPC-backed provider slots in once the RPC layer
lands, behind the same three methods.
"""

from __future__ import annotations

from typing import Protocol

from ..types.light_block import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class ErrLightBlockNotFound(ProviderError):
    """Benign: the provider simply doesn't have the requested height."""


class ErrHeightTooHigh(ProviderError):
    """Benign: the provider hasn't reached the requested height yet."""


class ErrBadLightBlock(ProviderError):
    """Malevolent or broken provider: drop it."""


class Provider(Protocol):
    def chain_id(self) -> str: ...

    def light_block(self, height: int) -> LightBlock:
        """Height 0 means the latest (provider.go LightBlock)."""
        ...

    def report_evidence(self, ev) -> None: ...


class BlockStoreProvider:
    """Serves light blocks straight from a node's stores — used by tests
    and by statesync against the local blocksync'd store."""

    def __init__(self, chain_id: str, block_store, state_store):
        self._chain_id = chain_id
        self.block_store = block_store
        self.state_store = state_store
        self.reported_evidence: list = []

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        from ..types.block import Header

        if height == 0:
            height = self.block_store.height
        if height > self.block_store.height:
            raise ErrHeightTooHigh(f"height {height} > {self.block_store.height}")
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            raise ErrLightBlockNotFound(f"no light block at height {height}")
        lb = LightBlock(
            SignedHeader(Header.from_proto(meta.header), commit), vals
        )
        try:
            lb.validate_basic(self._chain_id)
        except Exception as e:  # noqa: BLE001
            raise ErrBadLightBlock(str(e)) from e
        return lb

    def consensus_params(self, height: int):
        """Params effective at a height (statesync's state provider
        cross-checks the result against the verified header hash)."""
        return self.state_store.load_consensus_params(height)

    def report_evidence(self, ev) -> None:
        self.reported_evidence.append(ev)
