"""Fork/attack detection for the light client
(reference: light/detector.go:27 detectDivergence).

After a verification trace lands, every witness is asked for the target
height; a witness serving a different hash triggers the bifurcation
search (examine_conflicting_header_against_trace), evidence construction
(newLightClientAttackEvidence, detector.go:414), and evidence submission
to both the primary and the witness.
"""

from __future__ import annotations

from ..types.evidence import LightClientAttackEvidence
from ..utils.log import get_logger
from .provider import ErrHeightTooHigh, ErrLightBlockNotFound, ProviderError

logger = get_logger("light-detector")


class DivergenceError(Exception):
    pass


class ErrLightClientAttackDetected(DivergenceError):
    def __init__(self, evidence):
        super().__init__("light client attack detected and evidence submitted")
        self.evidence = evidence


class ErrFailedHeaderCrossReferencing(DivergenceError):
    def __init__(self):
        super().__init__(
            "all witnesses failed to confirm the header — cannot proceed"
        )


def detect_divergence(client, primary_trace, now_ns: int) -> None:
    """detector.go:27 — cross-check the last verified block against every
    witness; at least one must agree."""
    if not primary_trace or len(primary_trace) < 2:
        raise DivergenceError("nil or single block primary trace")
    if not client.witnesses:
        from .client import ErrNoWitnesses

        raise ErrNoWitnesses("divergence detection requires witnesses")
    last = primary_trace[-1]
    header_matched = False
    to_remove = []
    for i, witness in enumerate(client.witnesses):
        try:
            w_lb = witness.light_block(last.height)
        except (ErrLightBlockNotFound, ErrHeightTooHigh):
            continue  # benign: witness is behind
        except ProviderError as e:
            logger.info(f"witness {i} errored during comparison: {e}")
            to_remove.append(i)
            continue
        if w_lb.hash == last.hash:
            header_matched = True
            continue
        # conflicting headers: find the bifurcation and build evidence
        try:
            _handle_conflicting_headers(client, primary_trace, w_lb, witness, now_ns)
        except ErrLightClientAttackDetected:
            raise
        except DivergenceError as e:
            logger.info(f"witness {i} could not substantiate its header: {e}")
            to_remove.append(i)
    client.remove_witnesses(to_remove)
    if not header_matched:
        raise ErrFailedHeaderCrossReferencing()


def _handle_conflicting_headers(
    client, primary_trace, challenging_block, witness, now_ns: int
) -> None:
    """detector.go:215 handleConflictingHeaders."""
    witness_trace, primary_block = _examine_trace(
        client, primary_trace, challenging_block, witness, now_ns
    )
    common, trusted = witness_trace[0], witness_trace[-1]
    ev_against_primary = _new_attack_evidence(primary_block, trusted, common)
    logger.error(
        "ATTEMPTED ATTACK DETECTED — submitting evidence against the primary"
    )
    witness.report_evidence(ev_against_primary)

    # reverse roles: validate the witness's trace against the primary and
    # build the mirror evidence (the witness itself may be the liar)
    evidence = [ev_against_primary]
    try:
        primary_rev_trace, witness_block = _examine_trace(
            client, witness_trace, primary_trace[-1], client.primary, now_ns
        )
        ev_against_witness = _new_attack_evidence(
            witness_block, primary_rev_trace[-1], primary_rev_trace[0]
        )
        client.primary.report_evidence(ev_against_witness)
        evidence.append(ev_against_witness)
    except DivergenceError as e:
        logger.info(f"error validating primary's divergent header: {e}")
    raise ErrLightClientAttackDetected(evidence)


def _examine_trace(client, trace, target_block, source, now_ns: int):
    """detector.go:301 examineConflictingHeaderAgainstTrace — verify the
    source at each intermediate trace height until the hashes diverge;
    returns (source_trace_to_bifurcation, divergent_trace_block)."""
    if target_block.height < trace[0].height:
        raise DivergenceError(
            f"target height {target_block.height} below trusted trace root "
            f"{trace[0].height}"
        )
    prev = None
    source_trace = []
    for idx, trace_block in enumerate(trace):
        if trace_block.height > target_block.height:
            # forward lunatic: the next trace block past the target is the
            # divergent one — but its time must not exceed the target's
            if trace_block.time.unix_ns() > target_block.time.unix_ns():
                raise DivergenceError("invalid block time in trace")
            if prev.height != target_block.height:
                source_trace = client._verify_skipping(
                    source, prev, target_block, now_ns
                )
            return source_trace, trace_block
        if trace_block.height == target_block.height:
            source_block = target_block
        else:
            try:
                source_block = source.light_block(trace_block.height)
            except ProviderError as e:
                raise DivergenceError(f"examining trace: {e}") from e
        if idx == 0:
            if source_block.hash != trace_block.hash:
                raise DivergenceError("trace root mismatch between providers")
            prev = source_block
            continue
        try:
            source_trace = client._verify_skipping(source, prev, source_block, now_ns)
        except Exception as e:  # noqa: BLE001
            raise DivergenceError(f"verify skipping failed: {e}") from e
        if source_block.hash != trace_block.hash:
            return source_trace, trace_block  # bifurcation point
        prev = source_block
    raise DivergenceError("no divergence found in trace")


def _new_attack_evidence(conflicted, trusted, common) -> LightClientAttackEvidence:
    """detector.go:414."""
    ev = LightClientAttackEvidence(
        conflicting_block=conflicted,
        common_height=0,
    )
    if ev.conflicting_header_is_invalid(trusted.signed_header.header):
        ev.common_height = common.height
        ev.timestamp = common.signed_header.header.time
        ev.total_voting_power = common.validator_set.total_voting_power()
    else:
        ev.common_height = trusted.height
        ev.timestamp = trusted.signed_header.header.time
        ev.total_voting_power = trusted.validator_set.total_voting_power()
    ev.byzantine_validators = ev.get_byzantine_validators(
        common.validator_set, trusted.signed_header
    )
    return ev
