"""Light client: trusted-header tracking with sequential or skipping
(bisection) verification (reference: light/client.go:133).

The client holds one primary provider and a set of witnesses.  Every
newly verified block is cross-checked against the witnesses by the
divergence detector (detector.py); a witness that serves a conflicting
header yields LightClientAttackEvidence reported to both sides.

The commit checks all route through light/verifier.py and therefore the
TPU batch path for large sets — the 150-validator light-block config in
BASELINE.json rides the same kernels as consensus.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from fractions import Fraction

from ..types.light_block import LightBlock
from ..utils.log import get_logger
from . import detector as detector_mod
from .provider import (
    ErrHeightTooHigh,
    ErrLightBlockNotFound,
    Provider,
    ProviderError,
)
from .store import LightStore
from .verifier import (
    DEFAULT_MAX_CLOCK_DRIFT_NS,
    DEFAULT_TRUST_LEVEL,
    ErrNewValSetCantBeTrusted,
    LightClientError,
    validate_trust_level,
    verify,
    verify_backwards,
)

SEQUENTIAL = "sequential"
SKIPPING = "skipping"

# pivot fraction for bisection (client.go:28-32)
SKIP_NUMERATOR, SKIP_DENOMINATOR = 9, 16
DEFAULT_PRUNING_SIZE = 1000


@dataclass
class TrustOptions:
    """Social-consensus root of trust (client.go TrustOptions)."""

    period_ns: int
    height: int
    hash: bytes


class ErrNoWitnesses(LightClientError):
    pass


class ErrLightClientAttack(LightClientError):
    pass


class Client:
    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: list[Provider],
        store: LightStore,
        mode: str = SKIPPING,
        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        now_fn=None,
    ):
        validate_trust_level(trust_level)
        self.chain_id = chain_id
        self.trusting_period_ns = trust_options.period_ns
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.mode = mode
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = store
        self.pruning_size = pruning_size
        self.logger = get_logger("light")
        self._mtx = threading.Lock()
        if now_fn is None:
            import time

            now_fn = time.time_ns
        self.now_ns = now_fn
        self._initialize(trust_options)

    # ------------------------------------------------------------ trust init

    def _initialize(self, opts: TrustOptions) -> None:
        """client.go:357 initializeWithTrustOptions: fetch the trusted
        block, check the hash matches the social-consensus root, verify
        self-consistency."""
        existing = self.store.light_block(opts.height)
        if existing is not None:
            if existing.hash == opts.hash:
                return
            # the store disagrees with the new social-consensus root: every
            # block in it descends from a now-untrusted lineage — purge it
            # all before re-rooting (client.go checkTrustedHeaderUsingOptions)
            self.logger.error(
                f"stored header at trust height {opts.height} conflicts with "
                "the new trust options; purging the light store"
            )
            self.store.delete_after(0)
        lb = self.primary.light_block(opts.height)
        if lb.hash != opts.hash:
            raise LightClientError(
                f"expected header hash {opts.hash.hex()} at height "
                f"{opts.height}, got {lb.hash.hex()}"
            )
        lb.validate_basic(self.chain_id)
        # 2/3 of its own claimed set must have signed it
        from ..types.validation import verify_commit_light
        from ..verifysvc.service import Klass

        verify_commit_light(
            self.chain_id,
            lb.validator_set,
            lb.signed_header.commit.block_id,
            lb.height,
            lb.signed_header.commit,
            klass=Klass.BACKGROUND,
        )
        self.store.save_light_block(lb)

    # --------------------------------------------------------------- queries

    def trusted_light_block(self, height: int) -> LightBlock | None:
        return self.store.light_block(height)

    def last_trusted_height(self) -> int:
        return self.store.latest_height()

    # ------------------------------------------------------------- verifying

    def update(self, now_ns: int | None = None) -> LightBlock | None:
        """Fetch + verify the primary's latest block if newer than our
        latest trusted one (client.go:431)."""
        now_ns = self.now_ns() if now_ns is None else now_ns
        latest_trusted = self.store.latest_light_block()
        if latest_trusted is None:
            raise LightClientError("no trusted state — initialize first")
        latest = self.primary.light_block(0)
        if latest.height <= latest_trusted.height:
            return None
        self._verify_light_block(latest, now_ns)
        return latest

    def verify_light_block_at_height(
        self, height: int, now_ns: int | None = None
    ) -> LightBlock:
        """client.go:469 — returns the verified block, fetching it from
        the primary if we don't already trust it."""
        if height <= 0:
            raise LightClientError("height must be positive")
        now_ns = self.now_ns() if now_ns is None else now_ns
        lb = self.store.light_block(height)
        if lb is not None:
            return lb
        lb = self.primary.light_block(height)
        self._verify_light_block(lb, now_ns)
        return lb

    def _verify_light_block(self, new_lb: LightBlock, now_ns: int) -> None:
        """client.go:553 — pick the verification path by position."""
        new_lb.validate_basic(self.chain_id)
        closest_under = self.store.light_block_before(new_lb.height + 1)
        if closest_under is not None and closest_under.height == new_lb.height:
            return  # already trusted
        if closest_under is None:
            # target is below our first trusted block: walk backwards
            first = self.store.first_light_block()
            if first is None:
                raise LightClientError("no trusted state")
            self._backwards(first, new_lb)
            self.store.save_light_block(new_lb)
            return

        if self.mode == SEQUENTIAL:
            trace = self._verify_sequential(closest_under, new_lb, now_ns)
        else:
            trace = self._verify_skipping(self.primary, closest_under, new_lb, now_ns)

        # cross-examine the witnesses over the verification trace
        if self.witnesses:
            detector_mod.detect_divergence(self, trace, now_ns)
        else:
            self.logger.error(
                "no witnesses configured: a lying primary cannot be detected"
            )

        for lb in trace[1:]:
            self.store.save_light_block(lb)
        if self.pruning_size > 0:
            self.store.prune(self.pruning_size)

    def _verify_sequential(
        self, trusted: LightBlock, new_lb: LightBlock, now_ns: int
    ) -> list[LightBlock]:
        """client.go:608 — verify every height in ascending order."""
        trace = [trusted]
        verified = trusted
        for h in range(trusted.height + 1, new_lb.height + 1):
            lb = new_lb if h == new_lb.height else self.primary.light_block(h)
            verify(
                verified.signed_header,
                verified.validator_set,
                lb.signed_header,
                lb.validator_set,
                self.trusting_period_ns,
                now_ns,
                self.max_clock_drift_ns,
                self.trust_level,
            )
            verified = lb
            trace.append(lb)
        return trace

    def _verify_skipping(
        self, source: Provider, trusted: LightBlock, new_lb: LightBlock, now_ns: int
    ) -> list[LightBlock]:
        """client.go:701 verifySkipping — bisection over the trust gap."""
        block_cache = [new_lb]
        depth = 0
        verified = trusted
        trace = [trusted]
        while True:
            try:
                verify(
                    verified.signed_header,
                    verified.validator_set,
                    block_cache[depth].signed_header,
                    block_cache[depth].validator_set,
                    self.trusting_period_ns,
                    now_ns,
                    self.max_clock_drift_ns,
                    self.trust_level,
                )
            except ErrNewValSetCantBeTrusted:
                # not enough trust to jump: bisect at 9/16 of the gap
                if depth == len(block_cache) - 1:
                    pivot = (
                        verified.height
                        + (block_cache[depth].height - verified.height)
                        * SKIP_NUMERATOR
                        // SKIP_DENOMINATOR
                    )
                    try:
                        interim = source.light_block(pivot)
                    except (ErrLightBlockNotFound, ErrHeightTooHigh) as e:
                        raise ErrNewValSetCantBeTrusted(str(e)) from e
                    except ProviderError as e:
                        raise LightClientError(
                            f"verification failed fetching pivot {pivot}: {e}"
                        ) from e
                    block_cache.append(interim)
                depth += 1
                continue
            # verified this hop
            if depth == 0:
                trace.append(new_lb)
                return trace
            verified = block_cache[depth]
            block_cache = block_cache[:depth]
            depth = 0
            trace.append(verified)

    def _backwards(self, trusted: LightBlock, new_lb: LightBlock) -> None:
        """client.go:923 — hash-linked walk below the first trusted block."""
        verified_header = trusted.signed_header.header
        while verified_header.height > new_lb.height:
            h = verified_header.height - 1
            interim = (
                new_lb
                if h == new_lb.height
                else self.primary.light_block(h)
            )
            verify_backwards(interim.signed_header.header, verified_header)
            verified_header = interim.signed_header.header

    # -------------------------------------------------------------- witnesses

    def remove_witnesses(self, indexes: list[int]) -> None:
        """client.go:1009 — drop forked/unresponsive witnesses."""
        if len(indexes) >= len(self.witnesses) and self.witnesses:
            self.logger.error("removing every witness — detection disabled")
        for i in sorted(set(indexes), reverse=True):
            if 0 <= i < len(self.witnesses):
                self.witnesses.pop(i)
