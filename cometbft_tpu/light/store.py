"""Trusted light-block store (reference: light/store/db/db.go).

Heights are stored big-endian so the db's ordered iterators give
first/latest directly; the store only ever holds VERIFIED blocks.
"""

from __future__ import annotations

import struct
import threading

from ..types.light_block import LightBlock
from ..wire import types_pb as pb

_PREFIX = b"lb:"


def _key(height: int) -> bytes:
    return _PREFIX + struct.pack(">q", height)


class LightStore:
    def __init__(self, db):
        self.db = db
        self._mtx = threading.Lock()

    def save_light_block(self, lb: LightBlock) -> None:
        with self._mtx:
            self.db.set(_key(lb.height), lb.to_proto().encode())

    def light_block(self, height: int) -> LightBlock | None:
        raw = self.db.get(_key(height))
        if raw is None:
            return None
        return LightBlock.from_proto(pb.LightBlockProto.decode(raw))

    def latest_light_block(self) -> LightBlock | None:
        for _, raw in self.db.reverse_iterator(_PREFIX, _PREFIX + b"\xff"):
            return LightBlock.from_proto(pb.LightBlockProto.decode(raw))
        return None

    def first_light_block(self) -> LightBlock | None:
        for _, raw in self.db.iterator(_PREFIX, _PREFIX + b"\xff"):
            return LightBlock.from_proto(pb.LightBlockProto.decode(raw))
        return None

    def latest_height(self) -> int:
        lb = self.latest_light_block()
        return lb.height if lb else 0

    def light_block_before(self, height: int) -> LightBlock | None:
        """Closest verified block strictly below height (db.go)."""
        with self._mtx:
            for _, raw in self.db.reverse_iterator(_PREFIX, _key(height)):
                return LightBlock.from_proto(pb.LightBlockProto.decode(raw))
        return None

    def prune(self, keep: int) -> int:
        """Keep only the newest `keep` blocks (db.go Prune)."""
        if keep <= 0:
            return 0
        with self._mtx:
            keys = [k for k, _ in self.db.iterator(_PREFIX, _PREFIX + b"\xff")]
            excess = len(keys) - keep
            if excess <= 0:
                return 0
            self.db.write_batch([], keys[:excess])
            return excess

    def delete_after(self, height: int) -> int:
        """Drop verified blocks above height (used on reset/rollback)."""
        with self._mtx:
            keys = [
                k
                for k, _ in self.db.iterator(_key(height + 1), _PREFIX + b"\xff")
            ]
            if keys:
                self.db.write_batch([], keys)
            return len(keys)

    def size(self) -> int:
        return sum(1 for _ in self.db.iterator(_PREFIX, _PREFIX + b"\xff"))
