"""Crash-tolerant client for the out-of-process verify plane (verifyd).

:class:`RemotePlaneClient` owns one connection to a verifyd
(``COMETBFT_TPU_VERIFYRPC_ADDR``) and the machinery that makes losing
that process survivable; :class:`RemoteBatchVerifier` is the thin
BatchVerifier-shaped adapter the VerifyService dispatches through, so
the scheduler/collector/ticket plumbing — and the PR-8 first-wins
settlement that makes a late remote answer harmless — is reused
unchanged across the process boundary.

The survival contract, end to end:

  * **Deadline propagation** — each request gets a budget
    (``COMETBFT_TPU_VERIFYRPC_BUDGET_MS``) pinned to THIS process's
    monotonic clock at submit; every send and idempotent resend carries
    the REMAINING budget in ms, never a wall-clock deadline, so skew
    between node and plane cannot stretch or strangle a request.
  * **Idempotent retry** — a connection death is indistinguishable from
    a plane death, so the io thread reconnects (jittered exponential
    backoff, ``COMETBFT_TPU_VERIFYRPC_BACKOFF_MS`` base) and RESENDS
    every pending request under its original (request_id, digest)
    idempotency key.  The server's dedup window guarantees a batch that
    actually got verified before the crash is answered from cache — the
    same verdicts in the same blame order, never a second verification.
  * **Circuit breaker** — ``COMETBFT_TPU_VERIFYRPC_BREAKER_FAILS``
    consecutive connection-level failures, or a single request deadline
    breach, trip the breaker OPEN: every pending request fails
    immediately (the service host-re-verifies each batch with
    per-signature blame in each request's OWN add() order; first-wins
    ticket settlement discards the remote answer if it ever lands), all
    subsequent batches route straight to the in-process host path, ONE
    forensics artifact + flightrec event records the trip.  While open,
    the io thread **probation-probes** the plane (one ping round trip
    per ``COMETBFT_TPU_VERIFYRPC_PROBE_PERIOD_MS``); after
    ``COMETBFT_TPU_VERIFYRPC_PROBATION_OK`` consecutive successes the
    breaker closes and batches flow remotely again.

Module-level helpers :func:`plane_ping` / :func:`plane_status` /
:func:`plane_arm_fault` give harnesses one-shot access to a plane
without standing up a client.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import uuid

from ..utils import envknobs, fail, tracing
from ..utils.flightrec import recorder as _flightrec
from ..utils.log import get_logger
from ..utils.metrics import hub as _mhub
from . import wire
from .service import DEFAULT_TENANT, Klass, VerifyServiceBackpressure

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
_BREAKER_CODE = {BREAKER_CLOSED: 0, BREAKER_OPEN: 1}


class RemotePlaneError(RuntimeError):
    """Transport/deadline failure of a remote verify request.  The
    service's error path (_fail_or_reverify) answers it with a host
    re-verification — callers never see this exception."""


class _Pending:
    __slots__ = (
        "rid", "digest", "items", "klass", "tenant", "deadline",
        "key_type", "trace_ctx", "kind", "trees",
        "event", "response", "error", "attempts", "sent_on_gen", "_done_cb",
    )

    def __init__(
        self, rid, digest, items, klass, tenant, deadline,
        key_type: str = "ed25519", trace_ctx: str = "",
        kind: str = "verify", trees=None,
    ):
        self.rid = rid
        self.digest = digest
        self.items = items
        self.klass = klass
        self.tenant = tenant
        self.deadline = deadline
        self.key_type = key_type
        # "verify" -> VerifyRequest frames; "proof" -> ProofRequest
        # frames (items then holds the (tree, index) query pairs and
        # trees the leaf lists, kept on the pending so every idempotent
        # resend rebuilds the SAME frame under the same idempotency key)
        self.kind = kind
        self.trees = trees
        # serialized span context (traceparent); rides EVERY send of
        # this request, idempotent resends included, so the plane's
        # spans join the submitter's trace whichever attempt lands
        self.trace_ctx = trace_ctx
        self.event = threading.Event()
        self.response: tuple[bool, list[bool]] | None = None
        self.error: BaseException | None = None
        self.attempts = 0
        self.sent_on_gen = -1  # conn generation this was last sent on
        self._done_cb = None

    def remaining(self) -> float:
        return self.deadline - time.monotonic()

    def settle(self, response=None, error=None) -> None:
        if self.event.is_set():
            return
        self.response = response
        self.error = error
        self.event.set()
        cb, self._done_cb = self._done_cb, None
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a callback bug must not kill the io thread
                get_logger("verifyrpc").exception(
                    "remote pending done-callback raised"
                )

    def add_done_callback(self, cb) -> None:
        """Fire ``cb`` once this pending settles (immediately if it
        already has).  ONE callback per pending — the service's deferred
        -collect hook; it runs on whichever thread settles (the io
        thread for responses/expiry, the submitter on breaker-open)."""
        self._done_cb = cb
        if self.event.is_set():
            cb, self._done_cb = self._done_cb, None
            if cb is not None:
                cb()


class RemoteBatchVerifier:
    """The BatchVerifier seam over the wire.  ``_entry = None`` routes
    submit() through the service's class-priority host worker (network
    IO must never run on the scheduler thread), and ``inflight_where =
    "remote"`` keeps the local failover watchdog's device deadline off
    these batches — the remote client owns its own deadline, and the
    local watchdog tripping the whole service to cpu_fallback over a
    slow PLANE would conflate two different failure domains."""

    _entry = None
    _fallback = None
    inflight_where = "remote"

    def __init__(self, client: "RemotePlaneClient", key_type: str = "ed25519"):
        self._client = client
        self._klass = Klass.CONSENSUS
        self._tenant = DEFAULT_TENANT
        # the batch's validator key type rides the wire so the PLANE
        # routes it to the matching verifier lane (MODE_BLS / MODE_SECP
        # batches must never reach an ed25519 verifier on the other
        # side; both secp wire shapes ride as "secp256k1" — the lane
        # discriminates rows by pubkey length, service.mode_for_key_type)
        self._key_type = key_type
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def bind_request(self, klass: Klass, tenant: str) -> None:
        """Called by the service's dispatch right after construction —
        _make_verifier only sees the mode, but the wire request carries
        (tenant, class) for the plane's server-side scheduling."""
        self._klass = klass
        self._tenant = tenant

    def add(self, pub: bytes, msg: bytes, sig: bytes) -> None:
        self._items.append((pub, msg, sig))

    def submit(self):
        return ("rpc", self._client.submit(
            self._items, self._klass, self._tenant,
            key_type=self._key_type,
        ))

    def defer_collect(self, ticket, cb) -> None:
        """Out-of-order settlement hook: the service's host worker hands
        the COLLECTOR this batch only once the response (or expiry) has
        actually settled it, so collect() below never blocks and a
        consensus settle can never queue behind in-flight lower-class
        responses — the plane answers out of dispatch order by design
        (it schedules by class priority)."""
        _kind, pend = ticket
        pend.add_done_callback(cb)

    def collect(self, ticket) -> tuple[bool, list[bool]]:
        _kind, pend = ticket
        return self._client.collect(pend)


class RemoteProofVerifier:
    """The PROOF-mode seam over the wire.  Items are the proof query
    triples (models/proof_server.encode_query); submit() resolves each
    referenced digest against the LOCAL tree cache and ships the leaves
    + (tree, index) pairs as one ProofRequest — the plane proves against
    the exact bytes this node holds, so its answer is bit-identical to
    the local oracle by construction.  Queries that cannot ship (evicted
    digest, malformed item, index out of range) keep a local None row —
    the same typed miss every other route gives them.  Same host-worker
    routing / watchdog exemption rationale as RemoteBatchVerifier."""

    _entry = None
    _fallback = None
    inflight_where = "remote"

    def __init__(self, client: "RemotePlaneClient"):
        self._client = client
        self._klass = Klass.PROOF
        self._tenant = DEFAULT_TENANT
        self._items: list[tuple[bytes, bytes, bytes]] = []
        self._slots: list[int] = []
        self._rows: list = []

    def bind_request(self, klass: Klass, tenant: str) -> None:
        self._klass = klass
        self._tenant = tenant

    def add(self, pub: bytes, msg: bytes, sig: bytes) -> None:
        self._items.append((pub, msg, sig))

    def submit(self):
        from ..models import proof_server as PS

        trees: list[list[bytes]] = []
        tree_pos: dict[bytes, int] = {}
        queries: list[tuple[int, int]] = []
        slots: list[int] = []
        rows: list = [None] * len(self._items)
        for pos, item in enumerate(self._items):
            try:
                digest, idx = PS.decode_query(item)
            except (ValueError, TypeError):
                continue
            ti = tree_pos.get(digest)
            if ti is None:
                leaves = PS.tree_leaves(digest)
                if leaves is None:
                    tree_pos[digest] = -1
                    continue
                ti = tree_pos[digest] = len(trees)
                trees.append(list(leaves))
            elif ti < 0:
                continue
            if idx >= len(trees[ti]):
                continue
            queries.append((ti, idx))
            slots.append(pos)
        if not queries:
            # nothing provable: settle locally with the typed misses
            return ("sync", (False, rows))
        self._slots = slots
        self._rows = rows
        return ("rpc", self._client.submit_proof(
            trees, queries, self._klass, self._tenant
        ))

    def defer_collect(self, ticket, cb) -> None:
        kind, payload = ticket
        if kind == "sync":
            cb()
            return
        payload.add_done_callback(cb)

    def collect(self, ticket):
        kind, payload = ticket
        if kind == "sync":
            return payload
        _ok, server_rows = self._client.collect(payload)
        if len(server_rows) != len(self._slots):
            raise RemotePlaneError(
                f"plane answered {len(server_rows)} proof rows for "
                f"{len(self._slots)} queries"
            )
        rows = self._rows
        for slot, row in zip(self._slots, server_rows):
            rows[slot] = row
        _mhub().verify_proof_queries.inc(len(server_rows), route="remote")
        return bool(rows) and all(r is not None for r in rows), rows


class RemotePlaneClient:
    """One process's connection to a shared verifyd (module docstring)."""

    def __init__(
        self,
        addr: str,
        budget_s: float | None = None,
        connect_timeout_s: float | None = None,
        retry_max: int | None = None,
        breaker_fails: int | None = None,
        backoff_s: float | None = None,
        probe_period_s: float | None = None,
        probation_ok: int | None = None,
        artifact_dir: str | None = None,
    ):
        self.addr = addr
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        self.budget_s = (
            budget_s if budget_s is not None
            else max(1, envknobs.get_int(envknobs.VERIFYRPC_BUDGET_MS)) / 1e3
        )
        self.connect_timeout_s = (
            connect_timeout_s if connect_timeout_s is not None
            else max(1, envknobs.get_int(envknobs.VERIFYRPC_CONNECT_TIMEOUT_MS))
            / 1e3
        )
        self.retry_max = max(
            1, retry_max if retry_max is not None
            else envknobs.get_int(envknobs.VERIFYRPC_RETRY_MAX)
        )
        self.breaker_fails = max(
            1, breaker_fails if breaker_fails is not None
            else envknobs.get_int(envknobs.VERIFYRPC_BREAKER_FAILS)
        )
        self.backoff_s = (
            backoff_s if backoff_s is not None
            else max(1, envknobs.get_int(envknobs.VERIFYRPC_BACKOFF_MS)) / 1e3
        )
        self.probe_period_s = (
            probe_period_s if probe_period_s is not None
            else max(1, envknobs.get_int(envknobs.VERIFYRPC_PROBE_PERIOD_MS))
            / 1e3
        )
        self.probation_ok = max(
            1, probation_ok if probation_ok is not None
            else envknobs.get_int(envknobs.VERIFYRPC_PROBATION_OK)
        )
        self.artifact_dir = artifact_dir
        self.logger = get_logger("verifyrpc")
        self._mtx = threading.Lock()
        self._pending: dict[bytes, _Pending] = {}
        self._sock: socket.socket | None = None
        # the frame reader travels WITH the connection: it buffers
        # partial frames, so recreating it mid-stream would desync
        self._reader: wire.FrameReader | None = None
        self._send_mtx = threading.Lock()
        self._conn_gen = 0
        self._breaker = BREAKER_CLOSED
        self._consec_fails = 0
        self._probation_consec_ok = 0
        self._trips = 0
        self._restores = 0
        self._reconnects = 0
        self._resends = 0
        self._connected_once = False
        self._last_trip_reason: str | None = None
        self._last_artifact: str | None = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._io_loop, name="verifyrpc-io", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- surface

    def available(self) -> bool:
        """Whether batches should route remotely right now (breaker
        closed).  One str compare — safe on the dispatch path."""
        return self._breaker == BREAKER_CLOSED and not self._stop.is_set()

    @property
    def breaker(self) -> str:
        return self._breaker

    def submit(
        self, items, klass: Klass, tenant: str, key_type: str = "ed25519"
    ) -> _Pending:
        """Register + send one request; returns the pending handle for
        :meth:`collect`.  Runs on the service's host worker (never the
        scheduler).  Raises :class:`RemotePlaneError` when the breaker
        is open — the service then builds the host path instead."""
        items = list(items)
        # capture the submitter's span context NOW (the host worker runs
        # under the batch's context scope): it rides the wire so the
        # plane's server-side spans share this trace_id
        ctx = (
            tracing.current_context()
            if tracing.propagation_enabled() else None
        )
        pend = _Pending(
            rid=uuid.uuid4().bytes,
            digest=wire.batch_digest(items),
            items=items,
            klass=klass,
            tenant=tenant,
            deadline=time.monotonic() + self.budget_s,
            key_type=key_type,
            trace_ctx=ctx.to_traceparent() if ctx is not None else "",
        )
        tracing.instant(
            "verify.rpc.submit",
            {"class": klass.label, "tenant": tenant, "sigs": len(items)}
            if tracing.enabled() else None,
        )
        return self._register_and_send(pend)

    def submit_proof(
        self, trees, queries, klass: Klass, tenant: str
    ) -> _Pending:
        """Register + send one proof batch (leaf lists + (tree, index)
        query pairs) — the PROOF-mode twin of :meth:`submit`, under the
        same idempotency, budget, breaker, and resend contracts."""
        trees = [list(lv) for lv in trees]
        queries = list(queries)
        ctx = (
            tracing.current_context()
            if tracing.propagation_enabled() else None
        )
        pend = _Pending(
            rid=uuid.uuid4().bytes,
            digest=wire.proof_digest(trees, queries),
            items=queries,
            klass=klass,
            tenant=tenant,
            deadline=time.monotonic() + self.budget_s,
            key_type="proof",
            trace_ctx=ctx.to_traceparent() if ctx is not None else "",
            kind="proof",
            trees=trees,
        )
        tracing.instant(
            "verify.proof.rpc_submit",
            {"class": klass.label, "tenant": tenant,
             "queries": len(queries), "trees": len(trees)}
            if tracing.enabled() else None,
        )
        return self._register_and_send(pend)

    def _register_and_send(self, pend: _Pending) -> _Pending:
        with self._mtx:
            # breaker checked UNDER the lock the trip flips it under: a
            # submit racing a trip either registers before the trip's
            # pending sweep (and is failed by it) or sees OPEN here —
            # never a stranded pending the open-state loop won't expire
            if self._stop.is_set() or self._breaker == BREAKER_OPEN:
                raise RemotePlaneError(
                    f"remote verify plane unavailable "
                    f"(breaker {self._breaker})"
                )
            self._pending[pend.rid] = pend
        sent = self._try_send(pend)
        if not sent:
            # no live conn: the io thread connects and resends pending
            self._wake.set()
        return pend

    def collect(self, pend: _Pending) -> tuple[bool, list[bool]]:
        """Block for the response within the request's remaining budget.
        The io thread's expiry sweep settles (and trips the breaker on)
        any pending that breaches its deadline, so this normally returns
        promptly — the extra grace below is only a backstop against the
        io thread itself being gone."""
        if not pend.event.wait(max(1.0, pend.remaining() + 2.0)):
            with self._mtx:
                self._pending.pop(pend.rid, None)
            _mhub().verify_rpc_requests.inc(result="timeout")
            self._trip(
                f"request deadline breach (io thread unresponsive): "
                f"class={pend.klass.label} sigs={len(pend.items)}"
            )
            raise RemotePlaneError(
                f"remote verify deadline breached after {self.budget_s:g}s"
            )
        if pend.error is not None:
            raise pend.error
        return pend.response

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        with self._mtx:
            pending = list(self._pending.values())
            self._pending.clear()
        for p in pending:
            p.settle(error=RemotePlaneError("remote plane client closed"))
        self._drop_conn()
        self._thread.join(timeout=5.0)

    def stats(self) -> dict:
        with self._mtx:
            return {
                "addr": self.addr,
                "breaker": self._breaker,
                "trips": self._trips,
                "restores": self._restores,
                "consecutive_failures": self._consec_fails,
                "probation_consec_ok": self._probation_consec_ok,
                "probation_ok_needed": self.probation_ok,
                "pending": len(self._pending),
                "reconnects": self._reconnects,
                "resends": self._resends,
                "budget_ms": self.budget_s * 1e3,
                "last_trip_reason": self._last_trip_reason,
                "last_artifact": self._last_artifact,
            }

    # -------------------------------------------------------------- wire IO

    def _try_send(self, pend: _Pending) -> bool:
        """Send one request over the live conn, if any.  Serialized by
        _send_mtx (submitters and the io thread's resend path share the
        socket).  A send failure just reports False — the io thread owns
        failure accounting and reconnection."""
        with self._send_mtx:
            sock = self._sock
            if sock is None:
                return False
            gen = self._conn_gen
            pend.attempts += 1
            pend.sent_on_gen = gen
            budget_ms = max(1, int(pend.remaining() * 1e3))
            if pend.kind == "proof":
                msg = wire.PlaneMessage(
                    proof_request=wire.ProofRequest(
                        request_id=pend.rid,
                        digest=pend.digest,
                        tenant=pend.tenant,
                        klass=int(pend.klass),
                        budget_ms=budget_ms,
                        trees=[
                            wire.ProofTree(leaves=list(lv))
                            for lv in pend.trees
                        ],
                        queries=[
                            wire.ProofQuery(tree=t, index=i)
                            for (t, i) in pend.items
                        ],
                        attempt=pend.attempts,
                        trace_ctx=pend.trace_ctx,
                    )
                )
            else:
                msg = wire.PlaneMessage(
                    verify_request=wire.VerifyRequest(
                        request_id=pend.rid,
                        digest=pend.digest,
                        tenant=pend.tenant,
                        klass=int(pend.klass),
                        budget_ms=budget_ms,
                        items=[
                            wire.SigItem(pub=p, msg=m, sig=s)
                            for (p, m, s) in pend.items
                        ],
                        attempt=pend.attempts,
                        key_type=pend.key_type,
                        trace_ctx=pend.trace_ctx,
                    )
                )
            try:
                sock.sendall(wire.frame(msg))
                return True
            except OSError as e:
                # includes socket.timeout: the 0.25s recv-poll timeout
                # also bounds sendall, so a stalled plane with a full
                # TCP window can tear a frame mid-write.  A torn frame
                # desyncs the stream for every later request — the conn
                # is unusable either way, so drop it HERE (we hold
                # _send_mtx; close after releasing) and let the io
                # thread reconnect + idempotently resend.
                self._sock = None
                self._reader = None
                self.logger.info(f"verifyrpc send failed: {e!r}")
        from ..utils.netutil import close_socket

        close_socket(sock)
        self._wake.set()  # io thread notices the dead conn
        return False

    def _drop_conn(self) -> None:
        with self._send_mtx:
            sock, self._sock = self._sock, None
            self._reader = None
        if sock is not None:
            from ..utils.netutil import close_socket

            close_socket(sock)

    def _io_loop(self) -> None:
        """THE connection owner: connect (backoff), resend pending,
        drain responses; while the breaker is open, probation-probe."""
        backoff = self.backoff_s
        while not self._stop.is_set():
            if self._breaker == BREAKER_OPEN:
                self._probation_tick()
                continue
            self._expire_pending()
            if self._sock is None:
                with self._mtx:
                    idle = not self._pending
                if idle and self._connected_once:
                    # nothing to send and nothing owed: don't hold a
                    # conn open just to watch it (probes are the open
                    # state's job) — wait for work
                    self._wake.wait(0.25)
                    self._wake.clear()
                    continue
                if not self._connect():
                    # jittered exponential backoff between dial attempts
                    sleep = backoff * (0.5 + _rand())
                    self._stop.wait(min(sleep, 2.0))
                    backoff = min(backoff * 2, self.backoff_s * 40)
                    continue
                backoff = self.backoff_s
                self._resend_pending()
            self._recv_tick()

    def _connect(self) -> bool:
        try:
            sock = socket.create_connection(
                (self._host, self._port), self.connect_timeout_s
            )
            sock.settimeout(0.25)  # recv poll: stop/resend checks stay live
        except OSError as e:
            self._record_failure(f"connect failed: {e!r}")
            return False
        with self._send_mtx:
            self._sock = sock
            self._reader = wire.FrameReader(sock)
            self._conn_gen += 1
        if self._connected_once:
            with self._mtx:
                self._reconnects += 1
            _mhub().verify_rpc_reconnects.inc()
            self.logger.info(f"verifyrpc reconnected to {self.addr}")
        self._connected_once = True
        return True

    def _resend_pending(self) -> None:
        """Idempotent resend of everything in flight on a fresh conn —
        the server's dedup window makes repeats safe.  Requests out of
        retry budget or past deadline fail here (collect()'s breach path
        trips the breaker for the deadline case)."""
        with self._mtx:
            pending = sorted(
                self._pending.values(), key=lambda p: int(p.klass)
            )
        gen = self._conn_gen
        for p in pending:
            if p.event.is_set() or p.sent_on_gen == gen:
                continue
            if p.remaining() <= 0:
                continue  # collect() owns the breach verdict
            if p.attempts >= self.retry_max:
                with self._mtx:
                    self._pending.pop(p.rid, None)
                _mhub().verify_rpc_requests.inc(result="error")
                p.settle(error=RemotePlaneError(
                    f"retry budget exhausted ({p.attempts} attempts)"
                ))
                continue
            if p.attempts > 0:
                with self._mtx:
                    self._resends += 1
                _mhub().verify_rpc_resends.inc()
            if not self._try_send(p):
                return  # conn died again; next connect retries

    def _recv_tick(self) -> None:
        reader = self._reader
        if reader is None:
            return
        try:
            msg = reader.read()
        except socket.timeout:
            return
        except (OSError, ValueError) as e:
            self._conn_lost(f"recv failed: {e!r}")
            return
        if msg is None:
            self._conn_lost("connection closed by plane")
            return
        which = msg.which()
        if which == "verify_response":
            self._on_response(msg.verify_response)
        elif which == "proof_response":
            self._on_proof_response(msg.proof_response)
        elif which == "ping_response":
            self.logger.debug("verifyrpc: ping response")
        else:
            self.logger.warning(f"verifyrpc: unexpected message {which!r}")

    def _expire_pending(self) -> None:
        """Settle every pending past its deadline — THE breach signal.
        One breach trips the breaker (issue contract: K connection
        failures OR a deadline breach): an alive-but-unresponsive plane
        (SIGSTOP, a wedged scheduler) must not strand callers the way a
        cleanly-dead one cannot."""
        now = time.monotonic()
        with self._mtx:
            expired = [
                p for p in self._pending.values() if p.deadline <= now
            ]
            for p in expired:
                self._pending.pop(p.rid, None)
        if not expired:
            return
        m = _mhub()
        for _ in expired:
            m.verify_rpc_requests.inc(result="timeout")
        worst = expired[0]
        self._trip(
            f"request deadline breach: class={worst.klass.label} "
            f"sigs={len(worst.items)} budget={self.budget_s:g}s "
            f"attempts={worst.attempts} ({len(expired)} breached)"
        )
        err = RemotePlaneError(
            f"remote verify deadline breached after {self.budget_s:g}s"
        )
        for p in expired:
            p.settle(error=err)

    def _conn_lost(self, why: str) -> None:
        self._drop_conn()
        self._record_failure(why)

    def _on_response(self, resp: wire.VerifyResponse) -> None:
        with self._mtx:
            pend = self._pending.pop(resp.request_id, None)
            if pend is not None:
                self._consec_fails = 0  # the plane is answering
        if pend is None:
            return  # late answer for an already-settled request: discard
        m = _mhub()
        status = resp.status
        if status == wire.STATUS_OK:
            m.verify_rpc_requests.inc(
                result="deduped" if resp.deduped else "ok"
            )
            pend.settle(response=(
                bool(resp.all_ok), [bool(v) for v in resp.verdicts]
            ))
        elif status == wire.STATUS_BACKPRESSURE:
            # server-side admission control: surface the SAME exception
            # a local reject raises, tenant/scope included, so the
            # caller's fallback path is identical either way
            m.verify_rpc_requests.inc(result="backpressure")
            pend.settle(error=VerifyServiceBackpressure(
                pend.klass, 0, 0, tenant=pend.tenant,
                scope=resp.scope or "class",
            ))
        else:
            m.verify_rpc_requests.inc(result="error")
            pend.settle(error=RemotePlaneError(
                f"plane answered {wire.STATUS_NAMES.get(status, status)}: "
                f"{resp.error}"
            ))

    def _on_proof_response(self, resp: wire.ProofResponse) -> None:
        """The proof_response twin of _on_response: OK settles the
        pending with (ok, [Proof | None]) rows in wire-query order
        (ProofMsg total=0 = the typed miss sentinel); backpressure
        surfaces the SAME exception a local reject raises; everything
        else is a RemotePlaneError the service answers with a host
        re-proof — bit-identical bytes either way."""
        with self._mtx:
            pend = self._pending.pop(resp.request_id, None)
            if pend is not None:
                self._consec_fails = 0
        if pend is None:
            return
        m = _mhub()
        status = resp.status
        if status == wire.STATUS_OK:
            from ..crypto.merkle import Proof

            m.verify_rpc_requests.inc(
                result="deduped" if resp.deduped else "ok"
            )
            rows = [
                None if not pm.total else Proof(
                    total=int(pm.total),
                    index=int(pm.index or 0),
                    leaf_hash=pm.leaf_hash or b"",
                    aunts=list(pm.aunts or []),
                )
                for pm in (resp.proofs or [])
            ]
            ok = bool(rows) and all(r is not None for r in rows)
            pend.settle(response=(ok, rows))
        elif status == wire.STATUS_BACKPRESSURE:
            m.verify_rpc_requests.inc(result="backpressure")
            pend.settle(error=VerifyServiceBackpressure(
                pend.klass, 0, 0, tenant=pend.tenant,
                scope=resp.scope or "class",
            ))
        else:
            m.verify_rpc_requests.inc(result="error")
            pend.settle(error=RemotePlaneError(
                f"plane answered {wire.STATUS_NAMES.get(status, status)}: "
                f"{resp.error}"
            ))

    # ------------------------------------------------------------- breaker

    def _record_failure(self, why: str) -> None:
        with self._mtx:
            self._consec_fails += 1
            fails = self._consec_fails
        self.logger.info(
            f"verifyrpc failure [{fails}/{self.breaker_fails}]: {why}"
        )
        if fails >= self.breaker_fails:
            self._trip(f"{fails} consecutive connection failures ({why})")

    def _trip(self, reason: str) -> bool:
        with self._mtx:
            if self._breaker == BREAKER_OPEN:
                return False
            self._breaker = BREAKER_OPEN
            self._trips += 1
            self._probation_consec_ok = 0
            self._last_trip_reason = reason
            pending = list(self._pending.values())
            self._pending.clear()
        self._drop_conn()
        m = _mhub()
        m.verify_rpc_breaker_state.set(_BREAKER_CODE[BREAKER_OPEN])
        m.verify_rpc_breaker_transitions.inc(state="open")
        _flightrec().record(
            "verifyrpc_breaker", state="open", reason=reason,
            pending=len(pending),
        )
        tracing.instant(
            "verify.rpc_breaker",
            {"state": "open", "pending": len(pending)}
            if tracing.enabled() else None,
        )
        self.logger.error(
            f"remote verify plane breaker OPEN: {reason} "
            f"({len(pending)} pending request(s) -> host re-verify); "
            "probation probing toward restore"
        )
        # fail pending LAST: the service's collector immediately
        # host-re-verifies each batch, and those verdicts must not race
        # a half-torn-down client state
        err = RemotePlaneError(f"breaker tripped: {reason}")
        for p in pending:
            p.settle(error=err)
        self._last_artifact = self._capture_forensics(reason, len(pending))
        self._wake.set()
        return True

    def _capture_forensics(self, reason: str, n_pending: int) -> str | None:
        """ONE artifact per trip (debugdump.stall_report) — same rule as
        the PR-8 failover trip; must never raise."""
        from ..utils import debugdump

        try:
            path = debugdump.stall_report(
                f"remote verify plane breaker tripped: {reason}",
                [("verify rpc client", json.dumps(
                    self.stats(), indent=1, default=str
                )),
                 ("stranded remote requests", str(n_pending))],
                directory=self.artifact_dir,
            )
            _mhub().health_forensics.inc()
            self.logger.warning(f"breaker forensics written to {path}")
            return path
        except Exception as e:  # noqa: BLE001 — forensics must never hurt the node
            self.logger.warning(f"breaker forensics capture failed: {e!r}")
            return None

    def _probation_tick(self) -> None:
        """One probe round while open: ping the plane on a fresh
        short-lived connection; enough consecutive successes restore."""
        self._stop.wait(self.probe_period_s)
        if self._stop.is_set() or self._breaker != BREAKER_OPEN:
            return
        ok = plane_ping(
            self.addr, timeout_s=max(self.connect_timeout_s, 0.5)
        )
        with self._mtx:
            self._probation_consec_ok = (
                self._probation_consec_ok + 1 if ok else 0
            )
            consec = self._probation_consec_ok
        self.logger.info(
            f"verifyrpc probation probe: ok={ok} "
            f"[{consec}/{self.probation_ok}]"
        )
        if consec >= self.probation_ok:
            self._restore()

    def _restore(self) -> None:
        with self._mtx:
            if self._breaker != BREAKER_OPEN:
                return
            self._breaker = BREAKER_CLOSED
            self._restores += 1
            self._consec_fails = 0
            self._probation_consec_ok = 0
        m = _mhub()
        m.verify_rpc_breaker_state.set(_BREAKER_CODE[BREAKER_CLOSED])
        m.verify_rpc_breaker_transitions.inc(state="closed")
        _flightrec().record("verifyrpc_breaker", state="closed")
        tracing.instant(
            "verify.rpc_breaker",
            {"state": "closed"} if tracing.enabled() else None,
        )
        self.logger.warning(
            f"remote verify plane breaker CLOSED "
            f"({self.probation_ok} consecutive probes ok): batches route "
            "remotely again"
        )
        self._wake.set()


def _rand() -> float:
    # tiny indirection so tests can pin the backoff jitter
    import random

    return random.random()


# --------------------------------------------------- one-shot plane access

def _one_shot(addr: str, msg: wire.PlaneMessage, want: str, timeout_s: float):
    host, _, port = addr.rpartition(":")
    sock = socket.create_connection(
        (host or "127.0.0.1", int(port)), timeout_s
    )
    sock.settimeout(timeout_s)
    try:
        sock.sendall(wire.frame(msg))
        reader = wire.FrameReader(sock)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            resp = reader.read()
            if resp is None:
                return None
            if resp.which() == want:
                return getattr(resp, want)
        return None
    finally:
        from ..utils.netutil import close_socket

        close_socket(sock)


def plane_ping(addr: str, timeout_s: float = 2.0) -> bool:
    """Liveness: one ping round trip (the probation probe)."""
    try:
        return _one_shot(
            addr, wire.PlaneMessage(ping_request=wire.PingRequest()),
            "ping_response", timeout_s,
        ) is not None
    except (OSError, ValueError):
        return False


def plane_status(addr: str, timeout_s: float = 5.0) -> dict | None:
    """Readiness/diagnosis: the plane's stats() dict (server tallies +
    its service's scheduler snapshot), or None when unreachable."""
    try:
        resp = _one_shot(
            addr, wire.PlaneMessage(status_request=wire.StatusRequest()),
            "status_response", timeout_s,
        )
    except (OSError, ValueError):
        return None
    if resp is None:
        return None
    try:
        return json.loads(resp.json)
    except ValueError:
        return None


def plane_arm_fault(
    addr: str, name: str, value: float = 1.0,
    clear: bool = False, timeout_s: float = 5.0,
) -> bool:
    """Chaos harness: arm/clear a fault inside a live verifyd (gated on
    COMETBFT_TPU_FAULT_RPC in the plane's environment)."""
    try:
        resp = _one_shot(
            addr,
            wire.PlaneMessage(arm_fault_request=wire.ArmFaultRequest(
                name=name, value=float(value), clear=clear,
            )),
            "arm_fault_response", timeout_s,
        )
    except (OSError, ValueError):
        return False
    return bool(resp is not None and resp.ok)
