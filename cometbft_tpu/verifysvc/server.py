"""verifyd — the out-of-process verify plane's server half.

One :class:`VerifyServer` hosts one :class:`~.service.VerifyService`
behind the varint-delimited protobuf surface of ``verifysvc/wire.py``
(`scripts/verifyd.py` is the process entry point, equivalent to
``python -m cometbft_tpu.verifysvc.server``).  Remote submitters are
scheduled exactly like local ones: requests carry (tenant, class), so
the service's strict class priority, weighted-fair tenant interleave,
and per-(tenant, class) quotas are enforced **server-side** — a rogue
node flooding the shared plane is backpressured at the plane, and the
rejection (with the tenant/scope that bit) crosses the wire back to it.

Crash-tolerance contract (the client half is ``verifysvc/remote.py``):

  * **Deadline propagation** — requests carry their REMAINING budget in
    ms (never a wall-clock deadline: clock skew must not stretch or
    strangle a request).  The server derives its own absolute deadline
    at decode time; a request whose budget is already spent — or whose
    verification outlives it — answers ``STATUS_DEADLINE`` instead of
    parking the connection.
  * **Idempotent retry / dedup window** — every request carries
    (request_id UUID, batch digest).  The server remembers the pair →
    response for ``COMETBFT_TPU_VERIFYRPC_DEDUP_WINDOW_S``; a retried
    batch (the client resends after a connection death it cannot
    distinguish from a server death) is answered from the window, and a
    retry racing the ORIGINAL verification attaches to the in-flight
    ticket instead of re-submitting — the same batch is never verified
    twice into a different blame order.  Same id with a different
    digest is a protocol violation (``STATUS_BAD_REQUEST``).
  * **Liveness vs readiness** — ping answers whenever the socket is
    alive (liveness: don't reap the process); status reports the
    scheduler's own stats incl. ``running`` (readiness: route traffic).

Fault seams (utils/fail, armed via ``COMETBFT_TPU_FAULT_*`` env at
verifyd start or over the wire when ``COMETBFT_TPU_FAULT_RPC=1``):
``plane_crash`` / ``plane_stall`` fire on the Nth verify request —
SIGKILL/SIGSTOP with that exact batch in flight — and ``rpc_delay_ms``
/ ``rpc_drop_pct`` shape the response path at the socket.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from ..utils import envknobs, fail, tracing
from ..utils.log import get_logger
from ..utils.netutil import close_socket
from . import wire
from .service import (
    MODE_PROOF,
    Klass,
    VerifyService,
    VerifyServiceBackpressure,
    mode_for_key_type,
)

_READY_PREFIX = "VERIFYD READY addr="


class _DedupWindow:
    """(request_id -> digest, pending-event, response) with TTL + size
    bounds.  ``begin`` registers or joins; ``finish`` publishes."""

    def __init__(self, ttl_s: float, max_entries: int = 8192):
        self.ttl_s = max(1.0, ttl_s)
        self.max_entries = max_entries
        self._mtx = threading.Lock()
        self._entries: dict[bytes, dict] = {}

    def begin(self, rid: bytes, digest: bytes):
        """Returns ("new", entry) for a first-seen id (caller must
        finish() or abort()), ("dup", entry) for a retry (wait its event,
        read its response), or ("mismatch", None) when the id is reused
        with different content."""
        now = time.monotonic()
        with self._mtx:
            self._prune_locked(now)
            e = self._entries.get(rid)
            if e is not None:
                if e["digest"] != digest:
                    return "mismatch", None
                return "dup", e
            e = {
                "digest": digest,
                "event": threading.Event(),
                "response": None,
                "ts": now,
            }
            self._entries[rid] = e
            return "new", e

    def finish(self, rid: bytes, response) -> None:
        with self._mtx:
            e = self._entries.get(rid)
            if e is None:
                return
            e["response"] = response
            e["ts"] = time.monotonic()
        e["event"].set()

    def abort(self, rid: bytes) -> None:
        """Drop a pending entry whose verification never produced a
        cacheable answer (so a later retry gets a fresh run)."""
        with self._mtx:
            e = self._entries.pop(rid, None)
        if e is not None:
            e["event"].set()

    def _prune_locked(self, now: float) -> None:
        if len(self._entries) <= self.max_entries:
            stale = [
                rid for rid, e in self._entries.items()
                if e["response"] is not None and now - e["ts"] > self.ttl_s
            ]
        else:
            # over the size bound: shed oldest finished entries first
            finished = sorted(
                (
                    (e["ts"], rid) for rid, e in self._entries.items()
                    if e["response"] is not None
                ),
            )
            stale = [rid for _ts, rid in finished[: len(self._entries) // 2]]
        for rid in stale:
            del self._entries[rid]

    def __len__(self) -> int:
        with self._mtx:
            return len(self._entries)


class VerifyServer:
    """The verifyd listener: accept loop + per-connection reader
    threads; each verify request is handled on its own worker thread so
    one long verification never head-of-line-blocks a connection's
    later (possibly higher-class) requests — the service's scheduler,
    not socket order, decides priority."""

    def __init__(
        self,
        addr: str = "127.0.0.1:0",
        service: VerifyService | None = None,
        dedup_window_s: float | None = None,
        idle_timeout_s: float = 1.0,
        max_inflight_requests: int = 256,
    ):
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        # remote_addr pinned EMPTY for the default service: the plane IS
        # the remote end — inheriting COMETBFT_TPU_VERIFYRPC_ADDR from
        # the operator's environment would forward every batch back over
        # the wire (to itself, typically), each hop under a fresh
        # request_id so the dedup window never breaks the loop
        self.svc = (
            service if service is not None else VerifyService(remote_addr="")
        )
        self.dedup = _DedupWindow(
            dedup_window_s if dedup_window_s is not None
            else float(envknobs.get_int(envknobs.VERIFYRPC_DEDUP_WINDOW_S))
        )
        self.idle_timeout_s = idle_timeout_s
        # one worker THREAD per verify request (so the scheduler, not
        # socket order, decides priority) — but bounded: the signature
        # quota admits outstanding sigs, not request COUNT, so without
        # this cap a flood of tiny requests (or dup-retries parked in
        # the dedup window's wait) could exhaust plane threads before
        # admission control ever runs.  Over the cap answers
        # STATUS_BACKPRESSURE scope="server" immediately.
        self._req_sem = threading.BoundedSemaphore(
            max(1, max_inflight_requests)
        )
        self.logger = get_logger("verifyd")
        self._listener: socket.socket | None = None
        self._stopped = threading.Event()
        self._conns: list[socket.socket] = []
        self._conns_mtx = threading.Lock()
        self._stats_mtx = threading.Lock()
        self._requests = 0
        self._deduped = 0
        self._rejected = 0
        self._errors = 0
        self._started_unix = 0.0

    # ---------------------------------------------------------- lifecycle

    @property
    def addr(self) -> str:
        return f"{self._host}:{self._port}"

    def start(self) -> None:
        self._listener = socket.create_server((self._host, self._port))
        # accept with a poll timeout: stop() flips the event and the
        # loop exits within one tick — no blocking-accept teardown race
        self._listener.settimeout(0.5)
        self._port = self._listener.getsockname()[1]
        self._started_unix = time.time()
        threading.Thread(
            target=self._accept_loop, name="verifyd-accept", daemon=True
        ).start()
        self.logger.info(f"verifyd serving on {self.addr}")

    def stop(self) -> None:
        self._stopped.set()
        close_socket(self._listener)
        with self._conns_mtx:
            conns, self._conns = self._conns, []
        for c in conns:
            close_socket(c)
        self.svc.stop()

    # ------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(self.idle_timeout_s)
            with self._conns_mtx:
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn, peer),
                name=f"verifyd-conn-{peer[1]}", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket, peer) -> None:
        reader = wire.FrameReader(conn)
        wmtx = threading.Lock()  # response writes interleave across workers
        try:
            while not self._stopped.is_set():
                try:
                    msg = reader.read()
                except socket.timeout:
                    continue  # idle poll: re-check the stop flag
                if msg is None:
                    return  # clean EOF
                self._dispatch(msg, conn, wmtx)
        except (OSError, ValueError) as e:
            # conn death mid-frame or a desynced stream: drop the conn,
            # the client's reconnect/retry machinery owns recovery
            if not self._stopped.is_set():
                self.logger.info(f"verifyd conn {peer} dropped: {e!r}")
        finally:
            close_socket(conn)
            with self._conns_mtx:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    self.logger.debug(
                        f"verifyd conn {peer} already removed at teardown"
                    )

    def _dispatch(self, msg: wire.PlaneMessage, conn, wmtx) -> None:
        which = msg.which()
        if which == "verify_request":
            # own worker per request: the service's scheduler decides
            # order, not the socket — and a plane_stall/crash seam firing
            # in a worker can never desync this connection's reader
            req = msg.verify_request
            if not self._req_sem.acquire(blocking=False):
                with self._stats_mtx:
                    self._rejected += 1
                self._send(conn, wmtx, wire.PlaneMessage(
                    verify_response=wire.VerifyResponse(
                        request_id=req.request_id,
                        status=wire.STATUS_BACKPRESSURE,
                        error="plane at max in-flight requests",
                        scope="server",
                    )
                ))
                return
            threading.Thread(
                target=self._handle_verify_guarded, args=(req, conn, wmtx),
                name="verifyd-req", daemon=True,
            ).start()
        elif which == "proof_request":
            # same worker-per-request + inflight-cap shape as verify:
            # proof batches are scheduled by the service (PROOF class),
            # never by socket order
            req = msg.proof_request
            if not self._req_sem.acquire(blocking=False):
                with self._stats_mtx:
                    self._rejected += 1
                self._send(conn, wmtx, wire.PlaneMessage(
                    proof_response=wire.ProofResponse(
                        request_id=req.request_id,
                        status=wire.STATUS_BACKPRESSURE,
                        error="plane at max in-flight requests",
                        scope="server",
                    )
                ))
                return
            threading.Thread(
                target=self._handle_proof_guarded, args=(req, conn, wmtx),
                name="verifyd-proof", daemon=True,
            ).start()
        elif which == "ping_request":
            self._send(
                conn, wmtx,
                wire.PlaneMessage(ping_response=wire.PingResponse()),
            )
        elif which == "status_request":
            self._send(
                conn, wmtx,
                wire.PlaneMessage(
                    status_response=wire.StatusResponse(
                        json=json.dumps(self.stats(), default=str)
                    )
                ),
            )
        elif which == "arm_fault_request":
            self._handle_arm(msg.arm_fault_request, conn, wmtx)
        else:
            self.logger.warning(f"verifyd: unsupported message {which!r}")

    def _handle_arm(self, req: wire.ArmFaultRequest, conn, wmtx) -> None:
        resp = wire.ArmFaultResponse(ok=True)
        if not envknobs.get_bool(envknobs.FAULT_RPC):
            resp = wire.ArmFaultResponse(
                ok=False,
                error="fault injection disabled: set COMETBFT_TPU_FAULT_RPC=1",
            )
        else:
            try:
                if req.clear:
                    fail.clear(req.name) if req.name else fail.clear_all()
                else:
                    fail.arm(req.name, req.value if req.value else 1.0)
                self.logger.warning(
                    f"verifyd fault {'cleared' if req.clear else 'armed'} "
                    f"over the wire: {req.name or 'ALL'}={req.value}"
                )
            except ValueError as e:
                resp = wire.ArmFaultResponse(ok=False, error=str(e))
        self._send(conn, wmtx, wire.PlaneMessage(arm_fault_response=resp))

    def _handle_verify_guarded(self, req: wire.VerifyRequest, conn, wmtx) -> None:
        try:
            self._handle_verify(req, conn, wmtx)
        finally:
            self._req_sem.release()

    def _handle_verify(self, req: wire.VerifyRequest, conn, wmtx) -> None:
        deadline = time.monotonic() + max(0, req.budget_ms) / 1e3
        with self._stats_mtx:
            self._requests += 1
        # chaos seams: the Nth request crashes/stalls the plane with THIS
        # batch in flight — consume() counts down; the final shot fires
        for name, sig in (("plane_crash", signal.SIGKILL),
                          ("plane_stall", signal.SIGSTOP)):
            shots = fail.consume(name)
            if shots is not None and shots <= 1.0:
                self.logger.error(
                    f"verifyd: injected {name} firing (rid="
                    f"{req.request_id.hex()[:12]})"
                )
                os.kill(os.getpid(), sig)
        # adopt the client's span context (a CHILD of it: same trace_id,
        # fresh hop id) so this worker's spans — and the service spans
        # under the submit below — join the submitter's trace across the
        # process boundary; an absent/malformed context serves unlinked
        ctx = None
        if req.trace_ctx and tracing.propagation_enabled():
            parent = tracing.SpanContext.from_traceparent(req.trace_ctx)
            if parent is not None:
                ctx = parent.child()
        with tracing.context_scope(ctx), tracing.span(
            "verify.rpc.serve",
            {"sigs": len(req.items), "attempt": req.attempt,
             "key_type": req.key_type or "ed25519"}
            if tracing.enabled() else None,
        ):
            resp = self._verify_response(req, deadline)
        if resp is None:
            return
        # socket-level response shaping (delay / drop seams)
        d = fail.armed("rpc_delay_ms")
        if d:
            fail.jittered_sleep(d)
        pct = fail.armed("rpc_drop_pct")
        if pct is not None and fail.should_drop(pct):
            self.logger.warning(
                f"verifyd: injected response drop (rid="
                f"{req.request_id.hex()[:12]})"
            )
            return
        self._send(conn, wmtx, wire.PlaneMessage(verify_response=resp))

    def _verify_response(
        self, req: wire.VerifyRequest, deadline: float
    ) -> wire.VerifyResponse | None:
        rid = req.request_id
        if not rid or not req.digest:
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_BAD_REQUEST,
                error="missing request_id/digest",
            )
        items = [(it.pub, it.msg, it.sig) for it in req.items]
        if wire.batch_digest(items) != req.digest:
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_BAD_REQUEST,
                error="digest does not match items",
            )
        state, entry = self.dedup.begin(rid, req.digest)
        if state == "mismatch":
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_BAD_REQUEST,
                error="request_id reused with a different batch digest",
            )
        if state == "dup":
            # idempotent retry: never re-verify — attach to the original
            # (possibly still in flight) and answer its exact response
            with self._stats_mtx:
                self._deduped += 1
            if not entry["event"].wait(max(0.0, deadline - time.monotonic())):
                return wire.VerifyResponse(
                    request_id=rid, status=wire.STATUS_DEADLINE,
                    error="original verification still in flight",
                )
            cached = entry["response"]
            if cached is None:
                # the original aborted without a cacheable answer
                return wire.VerifyResponse(
                    request_id=rid, status=wire.STATUS_ERROR,
                    error="original verification aborted", deduped=True,
                )
            return wire.VerifyResponse(
                request_id=rid, status=cached.status, all_ok=cached.all_ok,
                verdicts=list(cached.verdicts), error=cached.error,
                scope=cached.scope, deduped=True,
            )
        # first sight: run it
        try:
            klass = Klass(req.klass)
        except ValueError:
            self.dedup.abort(rid)
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_BAD_REQUEST,
                error=f"unknown class {req.klass}",
            )
        mode = mode_for_key_type(req.key_type or "")
        if mode is None:
            # an unknown key type must never fall through to a default
            # verifier — the verdicts would be garbage with OK status
            self.dedup.abort(rid)
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_BAD_REQUEST,
                error=f"unknown key_type {req.key_type!r}",
            )
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            self.dedup.abort(rid)  # a retry with fresh budget may run
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_DEADLINE,
                error="budget exhausted on arrival",
            )
        try:
            ticket = self.svc.submit(
                items, klass, mode, tenant=req.tenant or None
            )
        except VerifyServiceBackpressure as e:
            with self._stats_mtx:
                self._rejected += 1
            resp = wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_BACKPRESSURE,
                error=str(e), scope=e.scope,
            )
            self.dedup.finish(rid, resp)  # a retry is equally rejected
            return resp
        try:
            all_ok, per = ticket.collect(remaining)
        except TimeoutError:
            # the ticket may still settle later; don't cache a verdict
            # that the service might yet produce — a fresh retry re-asks
            self.dedup.abort(rid)
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_DEADLINE,
                error="verification outlived the request budget",
            )
        except BaseException as e:  # noqa: BLE001 — answer the wire, keep serving
            with self._stats_mtx:
                self._errors += 1
            self.logger.error(f"verifyd: verification failed: {e!r}")
            self.dedup.abort(rid)
            return wire.VerifyResponse(
                request_id=rid, status=wire.STATUS_ERROR, error=repr(e),
            )
        resp = wire.VerifyResponse(
            request_id=rid, status=wire.STATUS_OK, all_ok=bool(all_ok),
            verdicts=[1 if v else 0 for v in per],
        )
        self.dedup.finish(rid, resp)
        return resp

    def _handle_proof_guarded(self, req: wire.ProofRequest, conn, wmtx) -> None:
        try:
            self._handle_proof(req, conn, wmtx)
        finally:
            self._req_sem.release()

    def _handle_proof(self, req: wire.ProofRequest, conn, wmtx) -> None:
        """The proof_request twin of _handle_verify: same budget,
        trace-adoption, and response-shaping seams around
        _proof_response."""
        deadline = time.monotonic() + max(0, req.budget_ms) / 1e3
        with self._stats_mtx:
            self._requests += 1
        ctx = None
        if req.trace_ctx and tracing.propagation_enabled():
            parent = tracing.SpanContext.from_traceparent(req.trace_ctx)
            if parent is not None:
                ctx = parent.child()
        with tracing.context_scope(ctx), tracing.span(
            "verify.proof.serve",
            {"queries": len(req.queries or []),
             "trees": len(req.trees or []), "attempt": req.attempt}
            if tracing.enabled() else None,
        ):
            resp = self._proof_response(req, deadline)
        if resp is None:
            return
        d = fail.armed("rpc_delay_ms")
        if d:
            fail.jittered_sleep(d)
        pct = fail.armed("rpc_drop_pct")
        if pct is not None and fail.should_drop(pct):
            self.logger.warning(
                f"verifyd: injected proof response drop (rid="
                f"{(req.request_id or b'').hex()[:12]})"
            )
            return
        self._send(conn, wmtx, wire.PlaneMessage(proof_response=resp))

    def _proof_response(
        self, req: wire.ProofRequest, deadline: float
    ) -> wire.ProofResponse:
        from ..models import proof_server as PS

        rid = req.request_id
        try:
            trees, queries = wire.validate_proof_request(req)
        except ValueError as e:
            return wire.ProofResponse(
                request_id=rid or b"", status=wire.STATUS_BAD_REQUEST,
                error=str(e),
            )
        state, entry = self.dedup.begin(rid, req.digest)
        if state == "mismatch":
            return wire.ProofResponse(
                request_id=rid, status=wire.STATUS_BAD_REQUEST,
                error="request_id reused with a different proof digest",
            )
        if state == "dup":
            with self._stats_mtx:
                self._deduped += 1
            if not entry["event"].wait(max(0.0, deadline - time.monotonic())):
                return wire.ProofResponse(
                    request_id=rid, status=wire.STATUS_DEADLINE,
                    error="original proof batch still in flight",
                )
            cached = entry["response"]
            if cached is None:
                return wire.ProofResponse(
                    request_id=rid, status=wire.STATUS_ERROR,
                    error="original proof batch aborted", deduped=True,
                )
            return wire.ProofResponse(
                request_id=rid, status=cached.status,
                proofs=list(cached.proofs or []), error=cached.error,
                scope=cached.scope, deduped=True,
            )
        try:
            klass = Klass(req.klass)
        except ValueError:
            self.dedup.abort(rid)
            return wire.ProofResponse(
                request_id=rid, status=wire.STATUS_BAD_REQUEST,
                error=f"unknown class {req.klass}",
            )
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            self.dedup.abort(rid)
            return wire.ProofResponse(
                request_id=rid, status=wire.STATUS_DEADLINE,
                error="budget exhausted on arrival",
            )
        digests = [PS.register_tree(lv) for lv in trees]
        items = [PS.encode_query(digests[t], i) for (t, i) in queries]
        try:
            ticket = self.svc.submit(
                items, klass, MODE_PROOF, tenant=req.tenant or None
            )
        except VerifyServiceBackpressure as e:
            with self._stats_mtx:
                self._rejected += 1
            resp = wire.ProofResponse(
                request_id=rid, status=wire.STATUS_BACKPRESSURE,
                error=str(e), scope=e.scope,
            )
            self.dedup.finish(rid, resp)
            return resp
        try:
            _all_ok, rows = ticket.collect(remaining)
        except TimeoutError:
            self.dedup.abort(rid)
            return wire.ProofResponse(
                request_id=rid, status=wire.STATUS_DEADLINE,
                error="proof generation outlived the request budget",
            )
        except BaseException as e:  # noqa: BLE001 — answer the wire, keep serving
            with self._stats_mtx:
                self._errors += 1
            self.logger.error(f"verifyd: proof batch failed: {e!r}")
            self.dedup.abort(rid)
            return wire.ProofResponse(
                request_id=rid, status=wire.STATUS_ERROR, error=repr(e),
            )
        resp = wire.ProofResponse(
            request_id=rid, status=wire.STATUS_OK,
            proofs=[
                wire.ProofMsg(total=0) if p is None else wire.ProofMsg(
                    total=p.total, index=p.index,
                    leaf_hash=p.leaf_hash, aunts=list(p.aunts),
                )
                for p in rows
            ],
        )
        self.dedup.finish(rid, resp)
        return resp

    def _send(self, conn, wmtx, msg: wire.PlaneMessage) -> None:
        try:
            with wmtx:
                conn.sendall(wire.frame(msg))
        except OSError as e:
            # the client died/reconnected: its retry path owns recovery
            self.logger.info(f"verifyd: response send failed: {e!r}")

    # -------------------------------------------------------------- status

    def stats(self) -> dict:
        with self._stats_mtx:
            server = {
                "addr": self.addr,
                "pid": os.getpid(),
                "started_unix": self._started_unix,
                "requests": self._requests,
                "deduped": self._deduped,
                "rejected": self._rejected,
                "errors": self._errors,
                "dedup_entries": len(self.dedup),
            }
        with self._conns_mtx:
            server["connections"] = len(self._conns)
        return {"server": server, "service": self.svc.stats(lock_timeout=0.5)}


# ----------------------------------------------------------- process entry

def spawn_verifyd(
    addr: str = "127.0.0.1:0",
    extra_env: dict[str, str] | None = None,
    log_path: str | None = None,
    ready_timeout_s: float = 30.0,
) -> tuple[subprocess.Popen, str]:
    """Spawn a verifyd subprocess and wait for its READY line; returns
    (proc, bound_addr).  Used by the chaos/soak harnesses and tests —
    production deploys run ``scripts/verifyd.py`` directly.  The child
    is forced onto CPU JAX and off the axon tunnel for the same reason
    e2e nodes are (a kill -9'd tunnel client wedges the relay for every
    later process)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("COMETBFT_TPU_DEVICE_BATCH_MIN", None)  # tests force 1; see runner
    # the spawning process is typically remote-bound to THIS plane; the
    # plane itself must verify locally, never forward (see __init__)
    env.pop("COMETBFT_TPU_VERIFYRPC_ADDR", None)
    env.update(extra_env or {})
    if log_path:
        os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
        log_f = open(log_path, "ab")
    else:
        log_f = subprocess.DEVNULL
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu.verifysvc.server",
             "--addr", addr],
            env=env,
            stdout=subprocess.PIPE,
            stderr=log_f,
            text=True,
        )
    finally:
        if log_f is not subprocess.DEVNULL:
            log_f.close()  # the child holds its own fd; don't leak ours
    deadline = time.monotonic() + ready_timeout_s
    # deadline-bounded raw reads (select + os.read on the pipe fd, never
    # readline): a child that wedges before printing READY must make
    # this raise at the deadline, not park the caller forever — the
    # same unbounded-blocking-read shape the socket-without-timeout
    # lint bans.  Raw fd reads bypass proc.stdout's buffer; that's fine,
    # nothing else consumes stdout after the READY line.
    import select

    fd = proc.stdout.fileno()
    buf = ""
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        readable, _, _ = select.select([fd], [], [], remaining)
        if not readable:
            break
        chunk = os.read(fd, 4096).decode("utf-8", "replace")
        if not chunk:
            break  # EOF: the child exited or closed stdout
        buf += chunk
        for line in buf.splitlines():
            if line.startswith(_READY_PREFIX):
                bound = line[len(_READY_PREFIX):].strip()
                # stop consuming stdout: nothing else is written there
                return proc, bound
    try:
        proc.kill()
    except OSError:
        pass
    raise RuntimeError(
        f"verifyd did not become ready within {ready_timeout_s}s "
        f"(stdout so far: {buf!r})"
    )


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="verifyd: the shared out-of-process verify plane"
    )
    p.add_argument("--addr", default="127.0.0.1:0",
                   help="host:port to listen on (port 0 = ephemeral; the "
                        "bound address is printed as 'VERIFYD READY addr=')")
    args = p.parse_args(argv)
    server = VerifyServer(args.addr)
    server.start()
    print(f"{_READY_PREFIX}{server.addr}", flush=True)
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    while not stop.is_set():
        stop.wait(0.5)
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
