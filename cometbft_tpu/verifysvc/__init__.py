"""Unified verify service: priority-scheduled device batching for every
signature-verification workload (see service.py for the design).

Clients:
  * consensus VerifyCommit + evidence checks — Klass.CONSENSUS
    (types/validation.py; evidence/verify.py runs on the proposal
    validation path, so it shares the consensus class)
  * blocksync verify-ahead/replay — Klass.BLOCKSYNC (blocksync/)
  * light client                  — Klass.BACKGROUND (light/)
  * mempool CheckTx               — Klass.MEMPOOL (checktx.py)

A new workload joins by calling ``global_service().submit(items, klass)``
or by constructing a :class:`ServiceBatchVerifier` — never by driving
models/verifier.py or models/comb_verifier.py directly (docs/
verify_service.md has the checklist).
"""

from .client import ServiceBatchVerifier, resolve_mode
from .service import (
    MODE_BLS,
    MODE_PLAIN,
    Klass,
    Ticket,
    VerifyService,
    VerifyServiceBackpressure,
    global_service,
    reset_global_service,
)

__all__ = [
    "Klass",
    "MODE_BLS",
    "MODE_PLAIN",
    "ServiceBatchVerifier",
    "Ticket",
    "VerifyService",
    "VerifyServiceBackpressure",
    "global_service",
    "reset_global_service",
    "resolve_mode",
]
