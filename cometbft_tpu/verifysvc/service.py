"""The unified verify service: one priority-scheduled seam in front of
the device verify pipeline.

Every signature-verification workload in the node — consensus
VerifyCommit, blocksync verify-ahead, the uncached fallback during comb
table warming, and mempool CheckTx — submits through this service
instead of driving the device verifiers (models/verifier.py,
models/comb_verifier.py) directly.  The service owns:

  * **Priority classes** (consensus > blocksync > mempool > background):
    a strict-priority scheduler dispatches ready consensus batches
    before anything else, so a flood of mempool CheckTx traffic can
    never delay a commit verification behind it.  An optional weighted
    mode (``COMETBFT_TPU_VERIFYSVC_WEIGHTS``) trades strictness for
    proportional interleave when starvation of low classes matters more
    than worst-case consensus latency.
  * **Adaptive batch formation**: a class's queue flushes when the
    pending signature count reaches the batch width
    (``COMETBFT_TPU_VERIFYSVC_BATCH_MAX``, reason=``full``) or when its
    oldest request has waited the class's flush deadline
    (``COMETBFT_TPU_VERIFYSVC_DEADLINE_<CLASS>_MS``, reason=
    ``deadline``), whichever comes first.  Consensus's deadline is 0 —
    its batches dispatch the moment the scheduler sees them — while
    mempool's small deadline is the coalescing window that merges per-tx
    CheckTx signature checks from concurrent senders into one device
    batch (the batch-width lever of arXiv:2302.00418; the
    tx-offload argument of arXiv:2112.02229).
  * **Bounded queues + backpressure**: each class's queue admits at most
    ``COMETBFT_TPU_VERIFYSVC_QUEUE_MAX`` signatures; a submit beyond
    that raises :class:`VerifyServiceBackpressure` (counted in
    ``verify_svc_rejected_total{class}``, flight-recorded) and the
    caller falls back to host verification — admission control, not an
    unbounded latency cliff.

Requests within one class that carry no validator-set binding coalesce
into shared batches; comb-bound requests (a whole commit against a
cached validator set) dispatch solo, because the comb program scatters
one row per validator.  Per-request blame order is preserved exactly:
each ticket's per-signature list follows its own add() order however
batches were merged or completed.

**Multi-tenant scheduling** (ROADMAP item 5: N independent chains
consolidated onto one shared verify plane): every request carries a
*tenant* id — ``COMETBFT_TPU_VERIFYSVC_TENANT`` names the tenant a
process submits under, defaulting to ``default`` so every single-chain
caller is untouched — and the scheduler keys its queues by
**(tenant, class)**:

  * classes still dispatch in strict global priority (one tenant's
    ready consensus batch outranks every tenant's mempool work);
  * WITHIN a class, ready tenants interleave weighted-fair
    (``COMETBFT_TPU_VERIFYSVC_TENANT_WEIGHTS``, default weight 1 each,
    rotating round-robin so no tenant owns the tie-break) — a rogue
    tenant's mempool flood cannot monopolize the class's dispatch slots;
  * each (tenant, class) is additionally bounded by
    ``COMETBFT_TPU_VERIFYSVC_TENANT_QUOTA`` OUTSTANDING signatures —
    queued plus dispatched-but-unsettled, released at ticket
    settlement, so a fast drain into the device/wire pipeline cannot
    launder a flood past admission (0 = the class-wide bound) — and
    backpressure lands on the flooding tenant:
    :class:`VerifyServiceBackpressure` carries ``tenant`` and ``scope``
    (which bound was hit) while other tenants keep admitting;
  * batches never mix tenants: coalescing happens inside one
    (tenant, class) queue, so per-tenant latency/flush/reject
    accounting stays exact (the ``verify_svc_tenant_*`` metrics, with
    the tenant label set bounded by utils/metrics.LabelGuard).

The sustained-load proof of these properties is the soak harness
(``scripts/soak.py`` driving e2e/soak.py): M in-process chains
(e2e/tenants.py) share one service for minutes-to-hours while faults
fire, with per-tenant SLOs asserting no starvation, no leak, no drift.

The scheduler thread only *dispatches* (the underlying submit() seam is
asynchronous — payload staging runs on the comb staging thread); a
separate collector thread drains results in dispatch order and resolves
tickets, so the scheduler is free to form the next batch while the
device runs the previous one.  Batches whose submit() does real inline
work — host-routed verifies below the device threshold, demoted comb
batches, and the uncached path's assembly/compile — go to a dedicated
host worker draining a CLASS-PRIORITY queue instead: that compute on
the scheduler thread would delay a consensus dispatch behind a mempool
batch, the inversion the class system exists to prevent, and the
priority queue bounds a queued consensus batch's extra wait to at most
one in-flight lower-class task.

**Degraded-mode failover** (ROADMAP item 5: BENCH r03-r05 lost three
perf rounds to a wedged device tunnel, and PR 7's health sentinel only
*detects* that state): the service runs in one of two backend modes,
``tpu`` or ``cpu_fallback``.  A dedicated failover watchdog thread —
never the scheduler, which must stay free to dispatch — trips the
service to CPU mode when an in-flight batch has been dispatched to (or
awaiting results from) the device longer than
``COMETBFT_TPU_FAILOVER_BATCH_DEADLINE_MS``, or when the health
sentinel (utils/healthmon) reports the accelerator ``wedged``.  A trip:

  * re-verifies every stranded in-flight batch on the host path, each
    request's per-signature blame in its OWN add() order (ticket
    resolution is first-wins, so the wedged device wait completing
    later — or never — cannot double-resolve or overwrite verdicts);
  * respawns the collector/host workers under a new generation (the old
    ones may be parked inside a wedged device wait forever; stale
    generations exit as soon as they unblock instead of double-draining);
  * routes every subsequent batch host-side — comb table binds are
    bypassed in ``_make_verifier`` here and in ``client.resolve_mode``
    (a table build is device work: it would hang with the tunnel);
  * emits a flight-recorder ``verifysvc_failover`` event, flips the
    ``verify_svc_backend_mode`` gauge, and writes ONE forensics
    artifact (utils/debugdump.stall_report) per trip.

While tripped, the watchdog runs a **probation loop**: the hang-proof
subprocess probe (utils/healthmon.probe_devices — it can never hang
this process, and it honors the ``wedge_device`` injected fault) every
``COMETBFT_TPU_FAILOVER_PROBE_PERIOD_MS``; after
``COMETBFT_TPU_FAILOVER_PROBATION_OK`` consecutive successes the
service restores TPU mode.  Dispatch/collect *errors* (as opposed to
hangs) don't flip the mode: the failed batch is re-verified on host
with identical verdicts and the service keeps serving — the
``fail_dispatch`` injected fault exercises exactly that path.

**Out-of-process verify plane** (``COMETBFT_TPU_VERIFYRPC_ADDR``):
when a remote plane is configured, the service routes every batch over
the wire to a shared verifyd (verifysvc/server.py) through
verifysvc/remote.py's crash-tolerant client instead of a local device
verifier.  The scheduler/collector/ticket plumbing is unchanged — a
RemoteBatchVerifier is just another BatchVerifier at the dispatch seam
— which is exactly how the PR-8 guarantees extend across the process
boundary: a plane death surfaces as a collect/submit error or deadline
breach, the remote client's circuit breaker trips to the in-process
HOST path (comb binds are bypassed — device-resident tables belong to
the plane), stranded batches host-re-verify with per-signature blame
in each request's own add() order, first-wins settlement discards any
late remote answer, and probation pings restore the remote path once
the plane returns.  Remote batches are tracked in flight as
``where="remote"`` and exempt from the LOCAL failover batch deadline:
the remote client owns its own deadline, and a slow plane must not be
conflated with a wedged local accelerator.
"""

from __future__ import annotations

import functools
import itertools
import queue
import threading
import time
from enum import IntEnum

from ..utils import envknobs, fail, healthmon, tracing
from ..utils.flightrec import recorder as _flightrec
from ..utils.log import get_logger
from ..utils.metrics import hub as _mhub

MODE_TPU = "tpu"
MODE_CPU_FALLBACK = "cpu_fallback"
_MODE_CODE = {MODE_TPU: 0, MODE_CPU_FALLBACK: 1}


class Klass(IntEnum):
    """Priority classes, highest first (lower value = dispatched first)."""

    CONSENSUS = 0
    BLOCKSYNC = 1
    MEMPOOL = 2
    BACKGROUND = 3
    # read-only proof serving (light-client fan-out): LOWEST priority by
    # construction — the scheduler is strict-priority across classes, so
    # however wide the proof backlog grows it can never delay a queued
    # CONSENSUS (or any signature-class) dispatch
    PROOF = 4

    @property
    def label(self) -> str:
        return self.name.lower()


_DEADLINE_KNOBS = {
    Klass.CONSENSUS: envknobs.VERIFYSVC_DEADLINE_CONSENSUS_MS,
    Klass.BLOCKSYNC: envknobs.VERIFYSVC_DEADLINE_BLOCKSYNC_MS,
    Klass.MEMPOOL: envknobs.VERIFYSVC_DEADLINE_MEMPOOL_MS,
    Klass.BACKGROUND: envknobs.VERIFYSVC_DEADLINE_BACKGROUND_MS,
    Klass.PROOF: envknobs.PROOF_DEADLINE_MS,
}

# request modes: how the dispatcher binds a batch to a device program.
# ("plain",)        -> uncached ed25519 kernel (power-of-two bucket
#                      shapes); coalescible with other plain requests of
#                      the class
# ("comb", entry)   -> comb-cached program bound to a valset cache entry
#                      (models/comb_verifier); dispatches solo — the
#                      scatter is one row per validator, so two commits
#                      against the same set cannot share a program call
# ("bls",)          -> BLS12-381 aggregate verifier (models/bls_verifier:
#                      device pubkey validation + G1 aggregation, host
#                      pairing); dispatches solo — a batch is an
#                      aggregate-commit claim, and mixing it with
#                      ed25519 rows would hand one verifier two key
#                      types.  Selected off the validator key type by
#                      crypto/batch.create_batch_verifier / client
#                      .resolve_mode.
# ("secp",)         -> batched secp256k1 ECDSA verifier
#                      (models/secp_verifier; Cosmos 33-byte and
#                      Ethereum 65-byte wire shapes in one lane);
#                      rows are independent, so secp requests COALESCE
#                      with other secp requests of the class exactly
#                      like plain ones — but never with a different
#                      mode, which would hand one verifier two key
#                      types.
# ("proof",)        -> batched Merkle proof GENERATION
#                      (models/proof_server): items are
#                      (tree_digest, index, b"") query triples, results
#                      are crypto/merkle.Proof rows.  Coalescible — each
#                      query's proof is independent, and coalescing is
#                      the whole point: a light-client swarm's queries
#                      merge into one one-hot-gather dispatch.
MODE_PLAIN = ("plain",)
MODE_BLS = ("bls",)
MODE_SECP = ("secp",)
MODE_PROOF = ("proof",)

# modes whose requests may merge into one batch (same mode only):
# per-row-independent verdicts with one shared data plane
_COALESCIBLE_MODES = frozenset({"plain", "secp", "proof"})

# the wire spelling of each mode's key type (verifysvc/wire.VerifyRequest
# .key_type); "" rides as ed25519 for back-compat with pre-BLS planes
_MODE_KEY_TYPE = {
    "plain": "ed25519",
    "comb": "ed25519",
    "bls": "bls12_381",
    "secp": "secp256k1",
    # proofs never ride a VerifyRequest — they have their own wire shape
    # (wire.ProofRequest).  The label exists for metrics/spans only, and
    # is deliberately ABSENT from _KEY_TYPE_MODE: a VerifyRequest
    # claiming key_type "proof" is a bad_request, not a proof query.
    "proof": "proof",
}
_KEY_TYPE_MODE = {
    "": MODE_PLAIN,
    "ed25519": MODE_PLAIN,
    "bls12_381": MODE_BLS,
    # all three secp wire formats share the MODE_SECP lane: the
    # verifier tells rows apart by pubkey length, like the host crypto
    # modules (20-byte "pubkey" = ecrecover sender address)
    "secp256k1": MODE_SECP,
    "secp256k1eth": MODE_SECP,
    "ecrecover": MODE_SECP,
}


def mode_key_type(mode) -> str:
    return _MODE_KEY_TYPE.get(mode[0], "ed25519")


def mode_for_key_type(key_type: str):
    """Wire key_type -> dispatch mode, or None for an unknown type (the
    server answers bad_request — never a silently-wrong verifier)."""
    return _KEY_TYPE_MODE.get(key_type)

# host-queue shutdown sentinel: sorts after every real class so queued
# work settles before the worker exits
_HOST_SENTINEL_PRIO = 1 << 30

# the tenant every single-chain caller lands on when none is claimed
DEFAULT_TENANT = "default"


def default_tenant() -> str:
    """The tenant id this process submits under — how a chain claims
    its slice of a shared verify plane (COMETBFT_TPU_VERIFYSVC_TENANT);
    empty/unset = ``default``."""
    t = envknobs.get_str(envknobs.VERIFYSVC_TENANT).strip()
    return t or DEFAULT_TENANT


def collect_timeout_s() -> float | None:
    """The client-side Ticket.collect() deadline
    (COMETBFT_TPU_VERIFYSVC_COLLECT_TIMEOUT_MS); None = wait forever."""
    ms = envknobs.get_int(envknobs.VERIFYSVC_COLLECT_TIMEOUT_MS)
    return None if ms <= 0 else ms / 1e3


def remote_plane_configured() -> bool:
    """Whether this process points at a shared out-of-process verify
    plane (COMETBFT_TPU_VERIFYRPC_ADDR).  Routing gates (crypto/batch,
    checktx, node startup) use this alongside device_capable(): a node
    with no local accelerator still consumes the remote plane."""
    return bool(envknobs.get_str(envknobs.VERIFYRPC_ADDR).strip())


class VerifyServiceBackpressure(Exception):
    """A signature bound was hit; the caller must fall back to host
    verification (or shed the request).  ``scope`` says which bound:
    ``tenant`` (this tenant's per-class quota on OUTSTANDING sigs —
    queued + in flight, released at settlement; other tenants are
    still admissible) or ``class`` (the class-wide queue bound)."""

    def __init__(
        self,
        klass: Klass,
        queued: int,
        limit: int,
        tenant: str = DEFAULT_TENANT,
        scope: str = "class",
    ):
        super().__init__(
            f"verify service backpressure: {scope} bound, class "
            f"{klass.label} tenant {tenant} has {queued} signatures "
            f"outstanding (limit {limit})"
        )
        self.klass = klass
        self.queued = queued
        self.limit = limit
        self.tenant = tenant
        self.scope = scope


class Ticket:
    """Handle for one submitted request; collect() blocks for
    (all_ok, per_signature) in the request's own add() order, or raises
    whatever the dispatch/collect path raised."""

    __slots__ = ("_ev", "_mtx", "_result", "_exc", "nsigs", "timings",
                 "_on_settle")

    def __init__(self, nsigs: int):
        self._ev = threading.Event()
        self._mtx = threading.Lock()
        self._result: tuple[bool, list[bool]] | None = None
        self._exc: BaseException | None = None
        self.nsigs = nsigs
        self.timings: dict[str, float] = {}
        # fired exactly once, on whichever resolution wins — the
        # service's outstanding-quota release hook (submit() sets it)
        self._on_settle = None

    def _settled(self) -> None:
        cb, self._on_settle = self._on_settle, None
        if cb is not None:
            cb()

    def _resolve(self, result, timings=None) -> bool:
        """First resolution wins: a failover host re-verify races the
        wedged device collect it replaced, and whichever settles a
        ticket first is authoritative — the loser's late answer is
        discarded, never overwritten onto an already-read result."""
        with self._mtx:
            if self._ev.is_set():
                return False
            self._result = result
            if timings:
                self.timings = dict(timings)
            self._ev.set()
        self._settled()
        return True

    def _fail(self, exc: BaseException) -> bool:
        with self._mtx:
            if self._ev.is_set():
                return False
            self._exc = exc
            self._ev.set()
        self._settled()
        return True

    def done(self) -> bool:
        return self._ev.is_set()

    def collect(self, timeout: float | None = None) -> tuple[bool, list[bool]]:
        if not self._ev.wait(timeout):
            raise TimeoutError("verify service ticket not resolved in time")
        if self._exc is not None:
            raise self._exc
        return self._result


class _Request:
    __slots__ = ("items", "klass", "mode", "ticket", "enq", "tenant", "ctx")

    def __init__(self, items, klass: Klass, mode, tenant: str = DEFAULT_TENANT):
        self.items = items
        self.klass = klass
        self.mode = mode
        self.tenant = tenant
        self.ticket = Ticket(len(items))
        self.enq = time.monotonic()
        # the submitter's span context: the scheduler/worker/collector
        # threads re-install it around their spans, so every hop of this
        # request — including the remote plane's, the context rides the
        # wire — shares the submitter's trace_id
        self.ctx = (
            tracing.current_context()
            if tracing.propagation_enabled() else None
        )


def _batch_ctx(batch: list["_Request"]):
    """The span context a coalesced batch's spans run under: the first
    member's (consensus batches are single-request; a coalesced mempool
    batch's members joined one dispatch, so one trace naming it is the
    honest attribution)."""
    for r in batch:
        if r.ctx is not None:
            return r.ctx
    return None


def _parse_weights(spec: str) -> dict[Klass, int]:
    """``"consensus=8,blocksync=4,mempool=2,background=1"`` -> weights.
    Forgiving like the rest of the knob layer: malformed entries are
    dropped, an empty result means strict priority."""
    out: dict[Klass, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            k = Klass[name.strip().upper()]
            w = int(val)
        except (KeyError, ValueError):
            continue
        if w >= 1:
            out[k] = w
    return out


def _parse_tenant_weights(spec: str) -> dict[str, int]:
    """``"chain-a=4,chain-b=1"`` -> per-tenant fair-share weights
    (unlisted tenants weigh 1).  Same forgiving parse as the class
    weights: malformed entries drop, empty = equal shares."""
    out: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        name = name.strip()
        try:
            w = int(val)
        except ValueError:
            continue
        if name and w >= 1:
            out[name] = w
    return out


def cpu_verifier_for_mode(mode):
    """The mode's pure-host data plane (CpuEd25519BatchVerifier for the
    ed25519 modes, CpuBlsBatchVerifier for MODE_BLS,
    CpuSecpBatchVerifier for MODE_SECP) — the ONE selection point every
    fallback path shares, so a new key type cannot be added to one
    fallback and missed in another."""
    if mode[0] == "bls":
        from ..models.bls_verifier import CpuBlsBatchVerifier

        return CpuBlsBatchVerifier()
    if mode[0] == "secp":
        from ..models.secp_verifier import CpuSecpBatchVerifier

        return CpuSecpBatchVerifier()
    if mode[0] == "proof":
        from ..models.proof_server import CpuProofProver

        return CpuProofProver()
    from ..models.verifier import CpuEd25519BatchVerifier

    return CpuEd25519BatchVerifier()


class _HostBatchVerifier:
    """The degraded-mode data plane: the exact BatchVerifier seam shape
    the device verifiers expose, wrapping the MODE's pure-host verifier
    (:func:`cpu_verifier_for_mode` — each the ONE source of its
    host-verdict semantics, bit-identical to its kernels) behind a
    sync-ticket submit().  ``_entry = None`` routes its submit() through
    the class-priority host worker (``_submit_is_offloaded``), so a
    mempool batch's host verification still cannot delay a queued
    consensus dispatch while the service is tripped."""

    _entry = None
    _fallback = None

    def __init__(self, mode=MODE_PLAIN):
        self._cpu = cpu_verifier_for_mode(mode)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        self._cpu.add(pub_key, msg, sig)

    def add_items_unchecked(self, items) -> None:
        """Re-verify seam: take the items as-is, bypassing add()'s
        shape validation.  The error paths re-verify batches whose
        dispatch ALREADY failed — possibly on exactly that validation —
        and a raise here would escape into the scheduler/host-worker
        loop; the cpu verifiers instead judge malformed rows False."""
        self._cpu._items = list(items)

    def submit(self):
        return ("sync", self._cpu.verify())

    def collect(self, ticket) -> tuple[bool, list[bool]]:
        return ticket[1]


def _host_verify_items(items, mode=MODE_PLAIN) -> tuple[bool, list[bool]]:
    """The one host-path verdict every fallback resolves to — delegates
    to the mode's cpu verifier so the semantics cannot drift from the
    cpu backend (the blame-order tests pin service results against
    exactly this)."""
    cpu = cpu_verifier_for_mode(mode)
    cpu._items = list(items)
    return cpu.verify()


class VerifyService:
    """Priority-scheduled batching front of the device verify pipeline.

    Construction reads the ``COMETBFT_TPU_VERIFYSVC_*`` knobs once;
    explicit constructor arguments override them (tests).  Threads start
    lazily on first submit and are daemons; :meth:`stop` tears them down
    (in-flight tickets are failed, not leaked).
    """

    def __init__(
        self,
        batch_max: int | None = None,
        queue_max: int | None = None,
        deadlines_ms: dict[Klass, float] | None = None,
        weights: dict[Klass, int] | None = None,
        tenant_quota: int | None = None,
        tenant_weights: dict[str, int] | None = None,
        failover: bool | None = None,
        batch_deadline_s: float | None = None,
        probation_ok: int | None = None,
        probe_fn=None,
        probe_period_s: float | None = None,
        probe_timeout_s: float | None = None,
        failover_tick_s: float = 0.25,
        artifact_dir: str | None = None,
        remote_addr: str | None = None,
        remote_opts: dict | None = None,
    ):
        self.batch_max = max(
            1, batch_max if batch_max is not None
            else envknobs.get_int(envknobs.VERIFYSVC_BATCH_MAX)
        )
        self.queue_max = max(
            1, queue_max if queue_max is not None
            else envknobs.get_int(envknobs.VERIFYSVC_QUEUE_MAX)
        )
        # PROOF gets its own (usually wider) queue bound: light-client
        # fan-out arrives thousands of queries at a time and must be
        # able to backlog without that backlog counting against — or
        # being counted against — the signature classes' bound.  0 =
        # inherit the class-wide bound.
        pq = envknobs.get_int(envknobs.PROOF_QUEUE_MAX)
        self._proof_queue_max = pq if pq and pq > 0 else self.queue_max
        if deadlines_ms is None:
            deadlines_ms = {
                k: max(0, envknobs.get_int(knob))
                for k, knob in _DEADLINE_KNOBS.items()
            }
        self._deadline_s = {
            k: float(deadlines_ms.get(k, 0)) / 1e3 for k in Klass
        }
        self._weights = (
            dict(weights) if weights is not None
            else _parse_weights(envknobs.get_str(envknobs.VERIFYSVC_WEIGHTS))
        )
        self._credits: dict[Klass, int] = {}
        # ---- (tenant, class) scheduling state.  Queues are keyed
        # class-first (strict global priority), then by tenant (the
        # weighted-fair interleave within the class).  Tenant sub-dicts
        # are created on first submit and REMOVED when drained, so an
        # unbounded tenant-id stream never grows the scheduler state.
        q = tenant_quota if tenant_quota is not None else envknobs.get_int(
            envknobs.VERIFYSVC_TENANT_QUOTA
        )
        self.tenant_quota = q if q and q > 0 else self.queue_max
        self._tenant_weights = (
            dict(tenant_weights) if tenant_weights is not None
            else _parse_tenant_weights(
                envknobs.get_str(envknobs.VERIFYSVC_TENANT_WEIGHTS)
            )
        )
        self._queues: dict[Klass, dict[str, list[_Request]]] = {
            k: {} for k in Klass
        }
        self._queued_sigs: dict[Klass, dict[str, int]] = {k: {} for k in Klass}
        self._class_sigs: dict[Klass, int] = {k: 0 for k in Klass}
        # per-(class, tenant) OUTSTANDING signatures — submitted and not
        # yet settled.  This, not queue depth, is what the tenant quota
        # admits against: the scheduler hands batches to the device's
        # (or the wire's) async pipeline almost instantly, so a queue
        # bound alone would let one tenant park unbounded work in
        # flight.  Released exactly once per request via the ticket's
        # first-wins settle hook.  Own lock, nested inside _cond on the
        # submit path; the release path takes only this lock, so a
        # ticket resolved under any other service lock cannot deadlock.
        self._outstanding_sigs: dict[Klass, dict[str, int]] = {
            k: {} for k in Klass
        }
        self._out_mtx = threading.Lock()
        # weighted round-robin position + credits per class; credits are
        # rebuilt from the READY tenant set at each replenish, so tenants
        # that drained and left the queue dict are pruned for free
        self._tenant_credits: dict[Klass, dict[str, int]] = {k: {} for k in Klass}
        self._last_tenant: dict[Klass, str | None] = {k: None for k in Klass}
        self._cond = threading.Condition()
        self._collectq: queue.Queue = queue.Queue()
        # class-priority queue for batches whose submit() runs real work
        # inline (host routes, uncached assembly, cold-shape compiles):
        # entries (klass_value, seq, (bv, batch)); lower tuples first so
        # a queued consensus batch always overtakes queued mempool work
        self._hostq: queue.PriorityQueue = queue.PriorityQueue()
        # thread-safe sequence (scheduler, collector, AND the failover
        # error path all enqueue): equal (prio, seq) tuples would make
        # PriorityQueue compare the unorderable payloads
        self._hostseq = itertools.count(1)
        # batches handed to the device/host but not yet settled, keyed by
        # id(batch): the health sentinel's forensics read their ages to
        # say HOW LONG a wedged dispatch has been in flight
        self._inflight: dict[int, dict] = {}
        self._inflight_mtx = threading.Lock()
        self._running = False
        self._threads: list[threading.Thread] = []
        self._start_once = threading.Lock()
        self.logger = get_logger("verifysvc")
        # service-local tallies mirrored to hub metrics; the RPC status
        # endpoint reads these without scraping /metrics
        self._dispatched: dict[str, int] = {k.label: 0 for k in Klass}
        self._rejected: dict[str, int] = {k.label: 0 for k in Klass}
        # per-tenant tallies for stats()/soak SLOs, keyed by the hub's
        # BOUNDED tenant label (LabelGuard) so a tenant-id flood can't
        # grow this dict without bound either
        self._tenant_tallies: dict[str, dict[str, int]] = {}
        self._tally_mtx = threading.Lock()

        # ---- degraded-mode failover (module docstring, "failover")
        self.failover_enabled = (
            envknobs.get_bool(envknobs.FAILOVER) if failover is None
            else failover
        )
        self.batch_deadline_s = (
            batch_deadline_s if batch_deadline_s is not None
            else max(1, envknobs.get_int(envknobs.FAILOVER_BATCH_DEADLINE_MS))
            / 1e3
        )
        self.probation_ok = max(
            1, probation_ok if probation_ok is not None
            else envknobs.get_int(envknobs.FAILOVER_PROBATION_OK)
        )
        self.probe_period_s = (
            probe_period_s if probe_period_s is not None
            else max(1, envknobs.get_int(envknobs.FAILOVER_PROBE_PERIOD_MS))
            / 1e3
        )
        self.probe_timeout_s = (
            probe_timeout_s if probe_timeout_s is not None
            else max(1, envknobs.get_int(envknobs.FAILOVER_PROBE_TIMEOUT_MS))
            / 1e3
        )
        self._probe_fn = (
            probe_fn if probe_fn is not None else healthmon.probe_devices
        )
        self.failover_tick_s = max(0.01, failover_tick_s)
        self.artifact_dir = artifact_dir
        # ---- out-of-process verify plane (module docstring, "remote").
        # The client (and its io thread) is created at _ensure_started,
        # so merely constructing a service never dials a plane.
        self.remote_addr = (
            remote_addr if remote_addr is not None
            else envknobs.get_str(envknobs.VERIFYRPC_ADDR).strip()
        ) or None
        self._remote_opts = dict(remote_opts or {})
        self._remote = None
        # mode state, guarded by _failover_mtx (never held across
        # blocking work); _gen tags worker threads so a trip can respawn
        # the collector/host workers while the wedged old generation is
        # still parked inside a device wait
        self._failover_mtx = threading.Lock()
        self._backend_mode = MODE_TPU
        self._gen = 0
        self._trips = 0
        self._restores = 0
        self._probation_consec_ok = 0
        self._next_probation_probe = 0.0
        self._last_restore_at: float | None = None
        self._last_trip_reason: str | None = None
        self._last_artifact: str | None = None
        self._stop_ev = threading.Event()

    # ------------------------------------------------------------ lifecycle

    def _ensure_started(self) -> None:
        if self._running:
            return
        with self._start_once:
            if self._running:
                return
            self._running = True
            # restart path (stop() then a later submit): a stale stop
            # signal would make every bounded wait in the failover loop
            # return immediately — a busy spin firing back-to-back
            # subprocess probes
            self._stop_ev.clear()
            if self.remote_addr and self._remote is None:
                from . import remote

                self._remote = remote.RemotePlaneClient(
                    self.remote_addr,
                    artifact_dir=self.artifact_dir,
                    **self._remote_opts,
                )
            self._threads = [
                threading.Thread(
                    target=self._sched_loop, name="verifysvc-sched",
                    daemon=True,
                ),
            ]
            if self.failover_enabled:
                self._threads.append(
                    threading.Thread(
                        target=self._failover_loop,
                        name="verifysvc-failover", daemon=True,
                    )
                )
            for t in self._threads:
                t.start()
            self._threads += self._spawn_workers(self._gen)

    def _spawn_workers(self, gen: int) -> list[threading.Thread]:
        """Start a collector + host worker tagged with ``gen``.  A
        failover trip bumps the generation and calls this again: the
        old workers may be parked forever inside a wedged device wait,
        and a stale generation exits (without retiring its heartbeat —
        the fresh worker owns the name now) as soon as it unblocks."""
        ts = [
            threading.Thread(
                target=self._collect_loop, args=(gen,),
                name="verifysvc-collect", daemon=True,
            ),
            threading.Thread(
                target=self._host_loop, args=(gen,),
                name="verifysvc-host", daemon=True,
            ),
        ]
        for t in ts:
            t.start()
        return ts

    def stop(self) -> None:
        """Tear down the scheduler/collector (tests).  Queued requests
        are failed with backpressure so no caller blocks forever."""
        with self._cond:
            if not self._running:
                return
            self._running = False
            stranded = [
                r
                for tenant_queues in self._queues.values()
                for q in tenant_queues.values()
                for r in q
            ]
            for k in Klass:
                self._queues[k] = {}
                self._queued_sigs[k] = {}
                self._class_sigs[k] = 0
            self._cond.notify_all()
        self._stop_ev.set()
        # close the remote client FIRST: its pending requests settle
        # with errors and their deferred-collect callbacks enqueue the
        # batches onto the collect queue, so the drain below fails those
        # tickets too — stop() must never leave a remote-in-flight
        # caller parked until its own collect timeout
        if self._remote is not None:
            self._remote.close()
            self._remote = None
        self._collectq.put(None)
        self._hostq.put((_HOST_SENTINEL_PRIO, 0, None))
        for r in stranded:
            r.ticket._fail(
                VerifyServiceBackpressure(r.klass, 0, self.queue_max)
            )
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        # a dispatch racing the sentinels can land its batch AFTER a
        # worker exited: fail those tickets too — stop() must never
        # leave a caller parked in collect() forever
        def _fail_batch(batch):
            for r in batch:
                r.ticket._fail(
                    VerifyServiceBackpressure(r.klass, 0, self.queue_max)
                )

        while True:
            try:
                item = self._collectq.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                _fail_batch(item[2])
        while True:
            try:
                _, _, payload = self._hostq.get_nowait()
            except queue.Empty:
                break
            if payload is not None:
                _fail_batch(payload[1])
        with self._inflight_mtx:
            self._inflight.clear()

    # ------------------------------------------------------------- submit

    def submit(
        self, items, klass: Klass, mode=MODE_PLAIN, tenant: str | None = None
    ) -> Ticket:
        """Enqueue one verification request (a list of
        (pubkey, msg, sig) triples, verified as a unit) under
        ``tenant`` (None = this process's default tenant) and return
        its ticket.  Raises :class:`VerifyServiceBackpressure` when the
        tenant's per-class quota or the class-wide queue bound is hit."""
        items = list(items)
        if tenant is None:
            tenant = default_tenant()
        if not items:
            t = Ticket(0)
            t._resolve((False, []))  # empty-batch contract of the verifiers
            return t
        self._ensure_started()
        n = len(items)
        m = _mhub()
        tlabel = m.tenant_labels.bound(tenant)
        with self._cond:
            if not self._running:
                # stop() won the race after _ensure_started: enqueueing
                # onto a dead scheduler would park the caller forever —
                # reject so they take their host fallback instead
                raise VerifyServiceBackpressure(
                    klass, 0, self.queue_max, tenant=tenant
                )
            class_q = self._class_sigs[klass]
            ten_q = self._queued_sigs[klass].get(tenant, 0)
            qmax = (
                self._proof_queue_max if klass is Klass.PROOF
                else self.queue_max
            )
            with self._out_mtx:
                ten_out = self._outstanding_sigs[klass].get(tenant, 0)
                if ten_out + n > self.tenant_quota < self.queue_max:
                    # the flooding tenant's OWN quota — on OUTSTANDING
                    # sigs (queued + dispatched-unsettled), so a fast
                    # drain into the device/wire pipeline can't launder
                    # a flood past admission: backpressure confined to
                    # the offender, the class stays admissible for
                    # others.  With no extra per-tenant bound configured
                    # (quota == queue_max) the class bound below owns
                    # the attribution: scope="tenant" must only ever
                    # point an operator at a quota knob that is
                    # actually the binding constraint.
                    queued, limit, scope = (
                        ten_out, self.tenant_quota, "tenant"
                    )
                elif class_q + n > qmax:
                    queued, limit, scope = class_q, qmax, "class"
                else:
                    queued = limit = 0
                    scope = None
                    self._outstanding_sigs[klass][tenant] = ten_out + n
            if scope is not None:
                self._rejected[klass.label] += 1
            else:
                req = _Request(items, klass, mode, tenant=tenant)
                req.ticket._on_settle = functools.partial(
                    self._release_outstanding, klass, tenant, n
                )
                self._queues[klass].setdefault(tenant, []).append(req)
                self._queued_sigs[klass][tenant] = ten_q + n
                self._class_sigs[klass] = class_q + n
                depth = class_q + n
                tdepth = ten_q + n
                self._cond.notify()
        if scope is not None:
            # admission control: count it, flight-record it, and push the
            # decision back to the caller (host fallback / shed)
            self._tally_tenant(tlabel, "rejected")
            m.verify_svc_rejected.inc(**{"class": klass.label})
            m.verify_svc_tenant_rejected.inc(
                **{"tenant": tlabel, "class": klass.label, "scope": scope}
            )
            _flightrec().record(
                "verifysvc_backpressure",
                klass=klass.label, tenant=tenant, scope=scope,
                queued=queued, sigs=n, limit=limit,
            )
            tracing.instant(
                "verify.sched.reject",
                {"class": klass.label, "tenant": tenant, "scope": scope,
                 "queued": queued, "sigs": n}
                if tracing.enabled() else None,
            )
            raise VerifyServiceBackpressure(
                klass, queued, limit, tenant=tenant, scope=scope
            )
        m.verify_svc_queue_depth.set(depth, **{"class": klass.label})
        m.verify_svc_tenant_queue_depth.set(
            tdepth, **{"tenant": tlabel, "class": klass.label}
        )
        return req.ticket

    def _release_outstanding(self, klass: Klass, tenant: str, n: int) -> None:
        """Return ``n`` signatures of ``tenant``'s quota — the ticket's
        settle hook, fired exactly once per admitted request no matter
        which path (collect, host re-verify, failure, stop) wins."""
        with self._out_mtx:
            d = self._outstanding_sigs[klass]
            left = d.get(tenant, 0) - n
            if left > 0:
                d[tenant] = left
            else:
                d.pop(tenant, None)

    def _tally_tenant(self, tlabel: str, key: str, n: int = 1) -> None:
        """Bump a per-tenant tally (keyed by the BOUNDED label).  Its
        own small lock: the reject path holds the scheduler cond, the
        dispatch path holds nothing — order is always cond -> tally."""
        with self._tally_mtx:
            t = self._tenant_tallies.get(tlabel)
            if t is None:
                t = self._tenant_tallies[tlabel] = {
                    "dispatched_batches": 0, "dispatched_sigs": 0,
                    "rejected": 0,
                }
            t[key] = t.get(key, 0) + n

    def verify(
        self, items, klass: Klass, mode=MODE_PLAIN, tenant: str | None = None
    ) -> tuple[bool, list[bool]]:
        """submit() + collect() in one call (synchronous callers)."""
        return self.submit(items, klass, mode, tenant=tenant).collect()

    # ---------------------------------------------------------- scheduler

    def _tenant_ready_locked(self, klass: Klass, tenant: str, now: float) -> bool:
        q = self._queues[klass].get(tenant)
        if not q:
            return False
        if self._queued_sigs[klass].get(tenant, 0) >= self.batch_max:
            return True
        return (now - q[0].enq) >= self._deadline_s[klass]

    def _ready_locked(self, klass: Klass, now: float) -> bool:
        """A class is ready when ANY of its tenants is ready (width or
        deadline) — strict class priority is decided first, the tenant
        interleave second."""
        return any(
            self._tenant_ready_locked(klass, t, now)
            for t in self._queues[klass]
        )

    def _next_deadline_locked(self, now: float) -> float | None:
        """Seconds until the earliest not-yet-ready (class, tenant)
        queue flushes, or None when every queue is empty."""
        best = None
        for k in Klass:
            for q in self._queues[k].values():
                if not q:
                    continue
                remain = self._deadline_s[k] - (now - q[0].enq)
                if best is None or remain < best:
                    best = remain
        return best

    def _pick_class_locked(self, now: float) -> Klass | None:
        ready = [k for k in Klass if self._ready_locked(k, now)]
        if not ready:
            return None
        if not self._weights:
            return ready[0]  # strict priority: Klass order
        # weighted interleave: spend per-class credits in priority order,
        # replenish when every ready class is out
        for k in ready:
            if self._credits.get(k, 0) > 0:
                self._credits[k] -= 1
                return k
        for k in Klass:
            self._credits[k] = self._weights.get(k, 1)
        self._credits[ready[0]] -= 1
        return ready[0]

    def _pick_tenant_locked(self, klass: Klass, now: float) -> str:
        """Weighted-fair interleave of the class's READY tenants: spend
        per-tenant credits in rotating round-robin order (starting after
        the last dispatched tenant, so no tenant owns the tie-break);
        when every ready tenant is out of credits, replenish each to its
        configured weight.  A tenant with weight w gets w dispatch slots
        per round — a flooding tenant's surplus queue depth buys it
        nothing beyond its share."""
        ready = sorted(
            t for t in self._queues[klass]
            if self._tenant_ready_locked(klass, t, now)
        )
        if len(ready) == 1:
            self._last_tenant[klass] = ready[0]
            return ready[0]
        last = self._last_tenant[klass]
        if last in ready:
            i = ready.index(last)
            order = ready[i + 1 :] + ready[: i + 1]
        else:
            order = ready
        creds = self._tenant_credits[klass]
        for t in order:
            if creds.get(t, 0) > 0:
                creds[t] -= 1
                self._last_tenant[klass] = t
                return t
        # replenish — rebuilt from the ready set, which prunes tenants
        # that drained and left the queue dict since the last round
        self._tenant_credits[klass] = creds = {
            t: self._tenant_weights.get(t, 1) for t in ready
        }
        t = order[0]
        creds[t] -= 1
        self._last_tenant[klass] = t
        return t

    def _form_batch_locked(
        self, klass: Klass, tenant: str
    ) -> tuple[list[_Request], str]:
        """Pop the head batch of a ready (class, tenant) queue.  Only
        coalescible modes (plain ed25519, secp) merge — and only with
        the SAME mode, up to the batch width: a coalesced batch has
        exactly one verifier, and one verifier serves one key type.
        Comb- and bls-bound requests go solo (each binds its own device
        program / aggregate claim).  Batches never mix tenants —
        per-tenant latency and blame accounting stay exact."""
        q = self._queues[klass][tenant]
        # the flush reason is what made the queue ready, decided before
        # popping: a width-triggered flush whose head dispatches solo
        # (comb) must not read as a deadline expiry on the dashboards
        was_full = self._queued_sigs[klass].get(tenant, 0) >= self.batch_max
        head = q.pop(0)
        batch = [head]
        total = len(head.items)
        kind = head.mode[0]
        if kind in _COALESCIBLE_MODES:
            while q and q[0].mode[0] == kind and total < self.batch_max:
                nxt = q.pop(0)
                batch.append(nxt)
                total += len(nxt.items)
        remaining = self._queued_sigs[klass].get(tenant, 0) - total
        if q:
            self._queued_sigs[klass][tenant] = remaining
        else:
            # drained: drop the tenant's entries so scheduler state stays
            # bounded however many tenant ids ever appeared
            del self._queues[klass][tenant]
            self._queued_sigs[klass].pop(tenant, None)
        self._class_sigs[klass] -= total
        reason = "full" if (was_full or total >= self.batch_max) else "deadline"
        return batch, reason

    def _track_inflight(self, batch: list[_Request], where: str) -> None:
        now = time.monotonic()
        with self._inflight_mtx:
            self._inflight[id(batch)] = {
                "class": batch[0].klass.label,
                "tenant": batch[0].tenant,
                "sigs": sum(len(r.items) for r in batch),
                "requests": len(batch),
                "where": where,
                "since": now,
                # when the batch ENTERED the device-bound phase — the
                # clock the failover deadline runs on.  A host-tracked
                # batch starts it only at the host->device relabel:
                # host-worker time (a cold XLA compile is legitimate
                # minutes-long work) must never count toward the trip
                "device_since": now if where == "device" else None,
                # the requests themselves, so a failover trip can
                # re-verify stranded work on host (never serialized:
                # stats() copies the display fields only)
                "batch": batch,
            }

    def _relabel_inflight(self, batch: list[_Request], where: str) -> None:
        with self._inflight_mtx:
            rec = self._inflight.get(id(batch))
            if rec is not None:
                if where == "remote":
                    # remote batches never start the LOCAL failover
                    # deadline clock (device_since stays None): the
                    # remote client owns its own deadline + breaker,
                    # and a slow plane is not a wedged local device
                    rec["remote"] = True
                rec["where"] = where
                if (
                    where in ("device", "collect")
                    and not rec.get("remote")
                    and rec.get("device_since") is None
                ):
                    rec["device_since"] = time.monotonic()

    def _untrack_inflight(self, batch: list[_Request]) -> None:
        with self._inflight_mtx:
            self._inflight.pop(id(batch), None)

    def _sched_loop(self) -> None:
        m = _mhub()
        while True:
            healthmon.beat("verifysvc-sched")
            with self._cond:
                if not self._running:
                    healthmon.retire("verifysvc-sched")
                    return
                now = time.monotonic()
                klass = self._pick_class_locked(now)
                if klass is None:
                    remain = self._next_deadline_locked(now)
                    # bounded wait (never a bare wait(): new submissions
                    # notify, deadlines cap the sleep, and an idle tick
                    # keeps shutdown prompt)
                    self._cond.wait(
                        0.5 if remain is None else max(0.0, min(remain, 0.5))
                    )
                    continue
                tenant = self._pick_tenant_locked(klass, now)
                batch, reason = self._form_batch_locked(klass, tenant)
                depth = self._class_sigs[klass]
                tdepth = self._queued_sigs[klass].get(tenant, 0)
            m.verify_svc_queue_depth.set(depth, **{"class": klass.label})
            m.verify_svc_tenant_queue_depth.set(
                tdepth,
                **{"tenant": m.tenant_labels.bound(tenant),
                   "class": klass.label},
            )
            self._dispatch(klass, batch, reason)

    def _make_verifier(self, mode):
        """Bind a batch to a data-plane verifier.  The ONLY constructor
        seam — tests monkeypatch this to observe dispatch order without
        touching a real kernel.  With a remote plane configured, every
        batch routes over the wire while the breaker is closed and to
        the in-process HOST path while it is open (never a local device:
        a node consuming a shared plane may not even have one, and the
        host path is the bit-identical verdict source either way).  In
        CPU fallback mode EVERY batch — comb-bound or not — gets the
        host verifier: a comb entry is device-resident state, and
        touching it while the tunnel is wedged is exactly the hang the
        trip escaped."""
        rem = self._remote  # one read: stop() nulls it concurrently
        if mode[0] == "proof":
            # proofs have their own wire shape and their own device
            # prover; every degraded arm lands on _HostBatchVerifier
            # over CpuProofProver -> proofs_from_byte_slices, the
            # bit-identity oracle
            if rem is not None:
                if rem.available():
                    from .remote import RemoteProofVerifier

                    return RemoteProofVerifier(rem)
                return _HostBatchVerifier(mode)
            if self._backend_mode == MODE_CPU_FALLBACK:
                return _HostBatchVerifier(mode)
            from ..models.proof_server import TpuProofProver

            return TpuProofProver()
        if rem is not None:
            if rem.available():
                from .remote import RemoteBatchVerifier

                return RemoteBatchVerifier(rem, key_type=mode_key_type(mode))
            return _HostBatchVerifier(mode)
        if self._backend_mode == MODE_CPU_FALLBACK:
            return _HostBatchVerifier(mode)
        if mode[0] == "bls":
            from ..models.bls_verifier import BlsAggregateVerifier

            return BlsAggregateVerifier()
        if mode[0] == "secp":
            from ..models.secp_verifier import TpuSecpBatchVerifier

            return TpuSecpBatchVerifier()
        if mode[0] == "comb":
            from ..models.comb_verifier import CombBatchVerifier

            return CombBatchVerifier(mode[1])
        from ..models.verifier import TpuEd25519BatchVerifier

        return TpuEd25519BatchVerifier()

    @staticmethod
    def _submit_is_offloaded(bv, nsigs: int) -> bool:
        """Whether bv.submit() must run on the host worker instead of
        the scheduler thread.  Only the comb-cached staging path is
        genuinely cheap at submit time (the slab fill + H2D + dispatch
        run on the comb staging thread): everything else does real work
        inline — sub-threshold batches verify on host, demoted comb
        batches resolve their fallback synchronously, and the uncached
        device path runs host assembly plus, at a new bucket shape, the
        XLA compile.  Any of those on the scheduler thread would delay
        a consensus dispatch behind lower-class work."""
        if getattr(bv, "_entry", None) is None:  # plain/uncached path
            return True
        if getattr(bv, "_fallback", None) is not None:  # demoted comb
            return True
        from ..models.verifier import _device_batch_min

        return nsigs < _device_batch_min()  # comb submit host-routes

    def _dispatch(self, klass: Klass, batch: list[_Request], reason: str) -> None:
        m = _mhub()
        nsigs = sum(len(r.items) for r in batch)
        tlabel = m.tenant_labels.bound(batch[0].tenant)
        now = time.monotonic()
        for r in batch:
            m.verify_svc_queue_wait.observe(
                now - r.enq, **{"class": klass.label}
            )
        m.verify_svc_flush.inc(**{"class": klass.label, "reason": reason})
        m.verify_svc_tenant_dispatched.inc(
            **{"tenant": tlabel, "class": klass.label}
        )
        self._dispatched[klass.label] += 1
        self._tally_tenant(tlabel, "dispatched_batches")
        self._tally_tenant(tlabel, "dispatched_sigs", nsigs)
        labels = (
            {"class": klass.label, "tenant": batch[0].tenant,
             "reason": reason, "sigs": nsigs, "requests": len(batch)}
            if tracing.enabled() else None
        )
        bv = None
        with tracing.context_scope(_batch_ctx(batch)), \
                tracing.span("verify.sched.dispatch", labels):
            try:
                if fail.armed("fail_dispatch") is not None:
                    raise fail.InjectedFault("injected fault: fail_dispatch")
                bv = self._make_verifier(batch[0].mode)
                bind = getattr(bv, "bind_request", None)
                if bind is not None:
                    # remote verifiers carry (tenant, class) on the wire
                    # — the plane schedules remote submitters server-side
                    bind(klass, batch[0].tenant)
                for r in batch:
                    for pub, msg, sig in r.items:
                        bv.add(pub, msg, sig)
                if self._submit_is_offloaded(bv, nsigs):
                    # real submit-time work: hand it to the host worker
                    # (class-priority queue) so the scheduler stays free
                    # to dispatch the next, possibly higher-class, batch
                    self._track_inflight(batch, "host")
                    self._hostq.put(
                        (int(klass), next(self._hostseq), (bv, batch))
                    )
                    return
                ticket = bv.submit()  # comb staging seam: cheap dispatch
            except BaseException as e:  # noqa: BLE001 — settle the tickets, keep scheduling
                self.logger.error(
                    f"dispatch failed (class={klass.label}, sigs={nsigs}): {e!r}"
                )
                self._fail_or_reverify(
                    batch, e, cause="dispatch_error", bv=bv
                )
                return
        self._track_inflight(batch, "device")
        self._collectq.put((bv, ticket, batch))

    def _host_loop(self, gen: int = 0) -> None:
        """Drain submit-time work in class-priority order: queued
        consensus batches overtake queued lower-class ones (the worker
        can't preempt an in-flight verify/compile, so the worst-case
        consensus delay is ONE lower-class task, not a whole backlog).
        ``gen`` retires this worker after a failover trip respawned a
        fresh one (a stale worker processes at most the item it already
        held — harmless, settlement is first-wins — then exits without
        retiring the heartbeat the fresh worker now owns)."""
        while True:
            if gen != self._gen:
                return
            if not self._running:
                healthmon.retire("verifysvc-host")
                return
            healthmon.beat("verifysvc-host")
            try:
                _, _, payload = self._hostq.get(timeout=0.5)
            except queue.Empty:
                continue
            if payload is None:
                healthmon.retire("verifysvc-host")
                return
            bv, batch = payload
            if all(r.ticket.done() for r in batch):
                # a failover trip already host-re-verified this batch
                # while it sat queued: submitting its stale device-bound
                # verifier now could park THIS worker in the same wedge
                self._untrack_inflight(batch)
                continue
            if (
                self._backend_mode == MODE_CPU_FALLBACK
                and not isinstance(bv, _HostBatchVerifier)
            ):
                # pending batch whose payload was bound to a DEVICE
                # verifier pre-trip (raced the mode flip): its submit()
                # would dispatch to the wedged tunnel — rebuild it on
                # the host path instead (unchecked: a malformed row must
                # judge False, not raise out of this worker loop)
                hbv = _HostBatchVerifier(batch[0].mode)
                hbv.add_items_unchecked(
                    [it for r in batch for it in r.items]
                )
                bv = hbv
            klass = batch[0].klass
            labels = (
                {"class": klass.label, "requests": len(batch)}
                if tracing.enabled() else None
            )
            with tracing.context_scope(_batch_ctx(batch)), \
                    tracing.span("verify.sched.hostwork", labels):
                try:
                    ticket = bv.submit()  # the inline work happens here
                except BaseException as e:  # noqa: BLE001 — settle the tickets, keep serving
                    self.logger.error(
                        f"host-route verify failed (class={klass.label}): {e!r}"
                    )
                    self._untrack_inflight(batch)
                    self._fail_or_reverify(
                        batch, e, cause="submit_error", bv=bv
                    )
                    continue
            if ticket[0] == "sync":
                self._settle(bv, ticket, batch)  # resolved already
            else:
                # device/remote ticket (uncached path): the collector
                # owns the blocking result wait, freeing this worker
                # immediately.  Relabel the in-flight record (same
                # entry, age keeps accruing) so a wedge during the
                # collect blames the device wait, not the finished host
                # work — remote batches keep their own label and stay
                # off the local failover clock
                self._relabel_inflight(
                    batch, getattr(bv, "inflight_where", "device")
                )
                defer = getattr(bv, "defer_collect", None)
                if defer is not None:
                    # remote batches reach the collector only once their
                    # response/expiry has SETTLED them: the plane answers
                    # out of dispatch order (it schedules by class), and
                    # a FIFO blocking collect would park a consensus
                    # settle behind every in-flight mempool response
                    defer(
                        ticket,
                        lambda bv=bv, t=ticket, b=batch:
                        self._collectq.put((bv, t, b)),
                    )
                else:
                    self._collectq.put((bv, ticket, batch))

    # ---------------------------------------------------------- collector

    def _collect_loop(self, gen: int = 0) -> None:
        while True:
            if gen != self._gen:
                return  # superseded by a failover trip's fresh worker
            if not self._running:
                healthmon.retire("verifysvc-collect")
                return
            healthmon.beat("verifysvc-collect")
            try:
                item = self._collectq.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:
                healthmon.retire("verifysvc-collect")
                return
            self._settle(*item)

    def _settle(self, bv, ticket, batch: list[_Request]) -> None:
        """Resolve a dispatched batch's tickets from its verifier
        ticket, splitting the result vector back per request.  The batch
        stays in the in-flight table until it resolves either way — the
        blocking collect() below is exactly the wait whose age the
        failover watchdog and the health forensics read when a device
        wedges mid-batch."""
        if all(r.ticket.done() for r in batch):
            # a failover trip already host-re-verified this batch while
            # it sat queued behind a wedged collect: touching the device
            # ticket now would park THIS worker in the same wedge
            self._untrack_inflight(batch)
            return
        self._relabel_inflight(batch, "collect")
        try:
            self._settle_inner(bv, ticket, batch)
        finally:
            self._untrack_inflight(batch)

    def _settle_inner(self, bv, ticket, batch: list[_Request]) -> None:
        labels = (
            {"class": batch[0].klass.label,
             "requests": len(batch)}
            if tracing.enabled() else None
        )
        t_collect = time.monotonic()
        with tracing.context_scope(_batch_ctx(batch)), \
                tracing.span("verify.sched.collect", labels):
            try:
                if not (isinstance(ticket, tuple) and ticket and ticket[0] == "sync"):
                    # injected-fault seams, in the same place a real
                    # device wedge/stall bites: the blocking DEVICE
                    # result wait.  Sync tickets are host-verified
                    # results — a wedged device never blocks them, so
                    # neither do the faults (post-trip CPU-mode batches
                    # must keep settling while the wedge is armed)
                    slow = fail.armed("slow_collect")
                    if slow is not None:
                        time.sleep(slow)
                    fail.wedge_wait("wedge_device")
                _, res = bv.collect(ticket)
            except BaseException as e:  # noqa: BLE001 — settle the tickets, keep draining
                self.logger.error(
                    f"collect failed (class={batch[0].klass.label}): {e!r}"
                )
                self._fail_or_reverify(
                    batch, e, cause="collect_error", bv=bv
                )
                return
        total = sum(len(r.items) for r in batch)
        if batch[0].klass == Klass.CONSENSUS:
            # height-timeline verify attribution: tickets don't carry
            # heights, so the batch lands on the ledger's current one
            from ..utils.heightline import registry as _hl_registry

            _hl_registry().note_verify(total, time.monotonic() - t_collect)
        if len(res) != total:
            err = RuntimeError(
                f"verifier returned {len(res)} results for {total} "
                "submitted signatures"
            )
            for r in batch:
                r.ticket._fail(err)
            return
        timings = getattr(bv, "last_timings", None)
        off = 0
        for r in batch:
            part = list(res[off : off + len(r.items)])
            off += len(r.items)
            # per-request verdict: the whole-batch all_ok is useless
            # once requests are coalesced — recompute from the slice
            # (matches the verifiers' own all(res) and bool(res))
            r.ticket._resolve((all(part) and bool(part), part), timings)

    # ----------------------------------------------------------- failover

    @property
    def backend_mode(self) -> str:
        """``tpu`` | ``cpu_fallback`` (atomic str read; clients check
        this before binding comb tables)."""
        return self._backend_mode

    def _fail_or_reverify(
        self, batch: list[_Request], exc, cause: str, bv=None
    ) -> None:
        """A dispatch/submit/collect ERROR (not a hang): with failover
        enabled the batch re-verifies on host — identical verdicts, no
        mode change, the service keeps serving — instead of failing the
        callers' tickets and pushing every one of them onto their own
        inline fallback.  The re-verification is requeued onto the
        class-priority host worker, NEVER run on the caller: a big
        lower-class batch erroring at dispatch must not park the
        scheduler (or the collector's FIFO) behind seconds of
        sequential host verifies, and the single worker bounds
        concurrency while keeping consensus re-verifies ahead of
        mempool ones.  If the HOST path itself errored (``bv`` already
        a :class:`_HostBatchVerifier`) the tickets fail — requeueing
        would loop."""
        if isinstance(exc, VerifyServiceBackpressure):
            # a REMOTE plane's server-side admission control said no:
            # the same contract as a local reject — the tickets fail
            # with the backpressure (tenant/scope intact) and the
            # CALLER owns the host fallback; re-verifying here would
            # defeat the plane's admission control by doing the work
            # locally on its behalf
            for r in batch:
                r.ticket._fail(exc)
            return
        if not self.failover_enabled or isinstance(bv, _HostBatchVerifier):
            for r in batch:
                r.ticket._fail(exc)
            return
        _mhub().verify_svc_host_reverify.inc(cause=cause)
        # unchecked fill: the dispatch may have failed on add()'s own
        # shape validation (e.g. a remote batch whose items don't match
        # its key_type) — re-raising here would escape into the
        # scheduler/worker loop and wedge the plane; the cpu verifiers
        # judge malformed rows False instead
        hbv = _HostBatchVerifier(batch[0].mode)
        hbv.add_items_unchecked([it for r in batch for it in r.items])
        # (re-)track as host work; on the collect_error path the outer
        # _settle finally pops this entry while the requeue is pending —
        # a brief stats gap, settlement itself is unaffected
        self._track_inflight(batch, "host")
        self._hostq.put(
            (int(batch[0].klass), next(self._hostseq), (hbv, batch))
        )

    def _reverify_batches(self, batches: list[list[_Request]]) -> None:
        """Host-verify every request of every batch, per-signature blame
        in each request's OWN add() order, resolving tickets first-wins
        (a wedged device wait completing later is discarded)."""
        for batch in batches:
            for r in batch:
                if r.ticket.done():
                    continue
                with tracing.context_scope(r.ctx), tracing.span(
                    "verify.failover.reverify",
                    {"class": r.klass.label, "sigs": len(r.items)}
                    if tracing.enabled() else None,
                ):
                    r.ticket._resolve(_host_verify_items(r.items, r.mode))

    def _failover_loop(self) -> None:
        """The failover watchdog: a dedicated thread — NEVER the
        scheduler — so a wedged scheduler/collector can't take the trip
        decision down with it, and the probation probe (a subprocess
        with a hard deadline) has somewhere safe to block."""
        while self._running:
            if self._backend_mode == MODE_TPU:
                healthmon.beat("verifysvc-failover")
                reason = self._trip_reason()
                if reason is not None:
                    self._trip_to_cpu(reason)
                else:
                    self._stop_ev.wait(self.failover_tick_s)
                continue
            # ---- CPU mode: sweep stranded work every tick, probe
            # toward restoration every probe period
            self._stop_ev.wait(self.failover_tick_s)
            if not self._running:
                return
            healthmon.beat("verifysvc-failover")
            if self._backend_mode != MODE_CPU_FALLBACK:
                continue
            self._sweep_stranded()
            now = time.monotonic()
            if now < self._next_probation_probe:
                continue
            self._next_probation_probe = now + self.probe_period_s
            try:
                res = self._probe_fn(self.probe_timeout_s)
                ok = bool(res.ok)
                detail = res.detail
            except BaseException as e:  # noqa: BLE001 — a probe bug is a failed probe
                ok, detail = False, f"probe raised {type(e).__name__}: {e}"
            with self._failover_mtx:
                self._probation_consec_ok = (
                    self._probation_consec_ok + 1 if ok else 0
                )
                consec = self._probation_consec_ok
            self.logger.info(
                f"failover probation probe: ok={ok} ({detail}) "
                f"[{consec}/{self.probation_ok}]"
            )
            if consec >= self.probation_ok:
                self._restore_tpu()

    def _sweep_stranded(self) -> None:
        """Close the trip/dispatch race: the scheduler reads the mode
        (tpu) in _make_verifier BEFORE tracking the batch, so a batch
        bound to a device verifier concurrently with the trip can miss
        the stranded-batch snapshot and park the fresh collector in the
        same wedge.  In CPU mode, any tracked batch overdue on the
        device deadline is host-re-verified — first-wins settlement
        makes repeats no-ops, and its callers unblock no matter how the
        race interleaved."""
        now = time.monotonic()
        with self._inflight_mtx:
            overdue = [
                rec["batch"] for rec in self._inflight.values()
                if rec.get("device_since") is not None
                and now - rec["device_since"] > self.batch_deadline_s
            ]
        overdue = [
            b for b in overdue if not all(r.ticket.done() for r in b)
        ]
        if not overdue:
            return
        _mhub().verify_svc_host_reverify.inc(len(overdue), cause="wedge")
        self.logger.warning(
            f"cpu-fallback sweep: {len(overdue)} batch(es) stranded past "
            "the device deadline after the trip; re-verifying on host"
        )
        # untrack BEFORE the off-thread re-verify: the parked worker's
        # own finally may never run (that is the wedge), a stale
        # ever-aging entry would re-trip the service the moment
        # probation restores, and the next tick must not re-select the
        # work this spawn is already doing.  Off-thread like the trip's
        # _recover: the watchdog must go straight back to watching (and
        # to probation probes), not serialize behind a big host verify.
        for b in overdue:
            self._untrack_inflight(b)
        threading.Thread(
            target=self._reverify_batches, args=(overdue,),
            name="verifysvc-reverify", daemon=True,
        ).start()

    def _trip_reason(self) -> str | None:
        """Why the service should trip NOW, or None.  Two signals:
        an in-flight batch stuck dispatched-to/awaiting the device past
        the batch deadline (``where`` device/collect; ``host`` is exempt
        — a cold-bucket XLA compile on the host worker is legitimate
        minutes-long work), or the health sentinel judging the
        accelerator wedged."""
        now = time.monotonic()
        with self._inflight_mtx:
            worst = max(
                (
                    now - rec["device_since"]
                    for rec in self._inflight.values()
                    if rec.get("device_since") is not None
                ),
                default=0.0,
            )
        if worst > self.batch_deadline_s:
            return (
                f"in-flight batch {worst:.1f}s past the "
                f"{self.batch_deadline_s:g}s device deadline"
            )
        mon = healthmon.monitor()
        if mon is not None and mon.state == healthmon.STATE_WEDGED:
            # ignore a wedged verdict the sentinel formed BEFORE our
            # probation restored: the sentinel probes far less often
            # (60s default vs probation's 15s), and its stale state
            # would re-trip a just-restored service every watchdog tick
            # until its next probe — duplicate artifacts and to_cpu
            # events for one incident.  Once it re-probes and still
            # says wedged, the trip is legitimate.
            probe_at = getattr(mon, "last_probe_at", None)
            if (
                self._last_restore_at is None
                or probe_at is None
                or probe_at > self._last_restore_at
            ):
                return "health sentinel reports accelerator wedged"
        return None

    def trip_to_cpu(self, reason: str) -> bool:
        """Public trip entry (bench degraded rounds; operators via
        tests).  Returns False when already tripped."""
        return self._trip_to_cpu(reason)

    def _trip_to_cpu(self, reason: str) -> bool:
        with self._failover_mtx:
            if self._backend_mode == MODE_CPU_FALLBACK:
                return False
            self._backend_mode = MODE_CPU_FALLBACK
            self._trips += 1
            self._probation_consec_ok = 0
            self._last_trip_reason = reason
            self._next_probation_probe = time.monotonic() + self.probe_period_s
            self._gen += 1
            gen = self._gen
        with self._inflight_mtx:
            stranded = [rec["batch"] for rec in self._inflight.values()]
        stranded_sigs = sum(
            len(r.items) for batch in stranded for r in batch
        )
        m = _mhub()
        m.verify_svc_backend_mode.set(_MODE_CODE[MODE_CPU_FALLBACK])
        m.verify_svc_failover.inc(direction="to_cpu")
        m.verify_svc_host_reverify.inc(len(stranded), cause="wedge")
        _flightrec().record(
            "verifysvc_failover",
            direction="to_cpu",
            reason=reason,
            stranded_batches=len(stranded),
            stranded_sigs=stranded_sigs,
        )
        tracing.instant(
            "verify.failover",
            {"direction": "to_cpu", "stranded": len(stranded)}
            if tracing.enabled() else None,
        )
        self.logger.error(
            f"verify service TRIPPED to CPU fallback: {reason} "
            f"({len(stranded)} in-flight batches / {stranded_sigs} sigs "
            "re-verifying on host)"
        )
        # a pre-trip stats snapshot (in-flight ages still visible) for
        # the forensics artifact, taken before re-verification resolves
        # and untracks the stranded entries
        snapshot = self.stats(lock_timeout=0.5)
        # fresh workers: the old generation may be parked inside the
        # wedged wait forever (that is the failure being survived)
        workers = self._spawn_workers(gen)
        self._threads = [
            t for t in self._threads
            if t.name not in ("verifysvc-collect", "verifysvc-host")
        ] + workers
        # re-verify stranded work off-thread: the watchdog must go
        # straight back to watching, and forensics IO must not delay
        # the re-verification that restores consensus liveness
        def _recover():
            # untrack FIRST: the stranded batches are already past the
            # device deadline, and leaving them in the table would make
            # the watchdog's very next sweep re-select them — double
            # counting and re-verifying work this thread is about to do
            # (the forensics snapshot above already preserved them)
            for batch in stranded:
                self._untrack_inflight(batch)
            self._reverify_batches(stranded)
            path = self._capture_failover_forensics(reason, snapshot)
            with self._failover_mtx:
                self._last_artifact = path

        threading.Thread(
            target=_recover, name="verifysvc-reverify", daemon=True
        ).start()
        return True

    def _restore_tpu(self) -> None:
        with self._failover_mtx:
            if self._backend_mode != MODE_CPU_FALLBACK:
                return
            self._backend_mode = MODE_TPU
            self._restores += 1
            self._probation_consec_ok = 0
            self._last_restore_at = time.monotonic()
        m = _mhub()
        m.verify_svc_backend_mode.set(_MODE_CODE[MODE_TPU])
        m.verify_svc_failover.inc(direction="to_tpu")
        _flightrec().record("verifysvc_failover", direction="to_tpu")
        tracing.instant(
            "verify.failover",
            {"direction": "to_tpu"} if tracing.enabled() else None,
        )
        self.logger.warning(
            "verify service restored to TPU mode "
            f"({self.probation_ok} consecutive probation probes ok)"
        )

    def _capture_failover_forensics(self, reason: str, snapshot: dict) -> str | None:
        """ONE diagnosis artifact per trip (debugdump.stall_report:
        verifysvc stats with the stranded in-flight ages, flight
        recorder, all-thread stacks).  Must never raise — it runs while
        the node is already degraded."""
        import json as _json

        from ..utils import debugdump

        try:
            sections = [
                ("verify service (at trip)",
                 _json.dumps(snapshot, indent=1, default=str)),
            ]
            if tracing.enabled():
                events = tracing.chrome_trace_events()[-256:]
                sections.append(
                    ("trace ring (newest 256)",
                     _json.dumps(events, default=str))
                )
            path = debugdump.stall_report(
                f"verify-service failover to cpu_fallback: {reason}",
                sections,
                directory=self.artifact_dir,
            )
            _mhub().health_forensics.inc()
            self.logger.warning(f"failover forensics written to {path}")
            return path
        except Exception as e:  # noqa: BLE001 — forensics must never hurt the node
            self.logger.warning(f"failover forensics capture failed: {e!r}")
            return None

    # ------------------------------------------------------------- status

    def stats(self, lock_timeout: float | None = None) -> dict:
        """Snapshot for the /verify_svc_status RPC, bench reporting, and
        the health sentinel's stall forensics.  ``lock_timeout`` bounds
        the wait for the scheduler lock (the sentinel passes a small
        value: a diagnosis of a wedged node must not block on the wedge
        it is diagnosing); on timeout the queue section reads
        ``lock_busy`` and the lock-free tallies still report."""
        now = time.monotonic()
        with self._inflight_mtx:
            in_flight = [
                {
                    "class": rec["class"],
                    "tenant": rec.get("tenant", DEFAULT_TENANT),
                    "sigs": rec["sigs"],
                    "requests": rec["requests"],
                    "where": rec["where"],
                    "age_s": round(now - rec["since"], 3),
                    # the failover deadline's clock (None while still in
                    # host-worker submit: compiles don't count)
                    "device_age_s": (
                        round(now - rec["device_since"], 3)
                        if rec.get("device_since") is not None
                        else None
                    ),
                }
                for rec in self._inflight.values()
            ]
        if lock_timeout is None:
            acquired = self._cond.acquire()
        else:
            acquired = self._cond.acquire(timeout=lock_timeout)
        if acquired:
            try:
                queued = {
                    k.label: {
                        "requests": sum(
                            len(q) for q in self._queues[k].values()
                        ),
                        "sigs": self._class_sigs[k],
                        "by_tenant": {
                            t: n for t, n in self._queued_sigs[k].items()
                        },
                    }
                    for k in Klass
                }
                dispatched = dict(self._dispatched)
                rejected = dict(self._rejected)
            finally:
                self._cond.release()
        else:
            queued = {"lock_busy": True}
            dispatched = dict(self._dispatched)
            rejected = dict(self._rejected)
        with self._tally_mtx:
            tenants = {t: dict(v) for t, v in self._tenant_tallies.items()}
        rem = self._remote  # one read: stop() nulls it concurrently
        remote = rem.stats() if rem is not None else None
        with self._failover_mtx:
            failover = {
                "enabled": self.failover_enabled,
                "backend_mode": self._backend_mode,
                "trips": self._trips,
                "restores": self._restores,
                "probation_consec_ok": self._probation_consec_ok,
                "probation_ok_needed": self.probation_ok,
                "batch_deadline_ms": self.batch_deadline_s * 1e3,
                "last_trip_reason": self._last_trip_reason,
                "last_artifact": self._last_artifact,
            }
        return {
            "in_flight": in_flight,
            "running": self._running,
            "backend_mode": failover["backend_mode"],
            "failover": failover,
            "remote": remote,
            "batch_max": self.batch_max,
            "queue_max": self.queue_max,
            "tenant_quota": self.tenant_quota,
            "tenant_weights": dict(self._tenant_weights),
            "deadline_ms": {
                k.label: self._deadline_s[k] * 1e3 for k in Klass
            },
            "weights": {k.label: w for k, w in self._weights.items()},
            "queued": queued,
            "dispatched_batches": dispatched,
            "rejected": rejected,
            "tenants": tenants,
        }


# ---- client-side collect-stall forensics (the bounded Ticket.collect
# contract): rate-limit the heavyweight artifact so a storm of timed-out
# callers produces ONE report per window, not one per caller
_STALL_MTX = threading.Lock()
_LAST_STALL_REPORT = 0.0
_STALL_REPORT_MIN_INTERVAL_S = 60.0


def _reset_stall_gate() -> None:
    """Tests only: re-arm the stall-report rate limiter."""
    global _LAST_STALL_REPORT
    with _STALL_MTX:
        _LAST_STALL_REPORT = 0.0


def report_collect_stall(
    klass: Klass,
    tenant: str,
    nsigs: int,
    waited_s: float,
    service: "VerifyService | None" = None,
    artifact_dir: str | None = None,
) -> str | None:
    """A client's bounded Ticket.collect() expired: the scheduler is
    alive enough to accept submits but did not resolve this ticket in
    time.  Count it, flight-record it, and (rate-limited) write a stall
    forensics artifact naming the stuck class/tenant with the service's
    own view of its queues and in-flight ages — the caller then degrades
    to an inline host verification instead of parking forever.  Returns
    the artifact path, or None when rate-limited/failed."""
    m = _mhub()
    m.verify_svc_collect_timeout.inc(**{"class": klass.label})
    _flightrec().record(
        "verifysvc_collect_stall",
        klass=klass.label, tenant=tenant, sigs=nsigs,
        waited_s=round(waited_s, 3),
    )
    tracing.instant(
        "verify.collect_stall",
        {"class": klass.label, "tenant": tenant, "sigs": nsigs}
        if tracing.enabled() else None,
    )
    global _LAST_STALL_REPORT
    now = time.monotonic()
    with _STALL_MTX:
        if now - _LAST_STALL_REPORT < _STALL_REPORT_MIN_INTERVAL_S:
            return None
        _LAST_STALL_REPORT = now
    import json as _json

    from ..utils import debugdump

    svc = service if service is not None else _GLOBAL
    sections = []
    if svc is not None:
        # bounded lock wait: the stats of a stuck scheduler must not
        # park the very diagnosis of its stall
        sections.append(
            ("verify service (at stall)",
             _json.dumps(svc.stats(lock_timeout=0.5), indent=1, default=str))
        )
    try:
        path = debugdump.stall_report(
            f"verify-service collect() deadline expired: class="
            f"{klass.label} tenant={tenant} sigs={nsigs} after "
            f"{waited_s:.1f}s (caller degrading to inline host verify)",
            sections,
            directory=artifact_dir,
        )
        m.health_forensics.inc()
        get_logger("verifysvc").error(
            f"collect stall forensics written to {path}"
        )
        return path
    except Exception as e:  # noqa: BLE001 — forensics must never hurt the caller
        get_logger("verifysvc").warning(
            f"collect stall forensics capture failed: {e!r}"
        )
        return None


_GLOBAL: VerifyService | None = None
_GLOBAL_MTX = threading.Lock()


def global_service() -> VerifyService:
    """The process-wide service every production consumer shares — one
    scheduler means one priority order across subsystems."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_MTX:
            if _GLOBAL is None:
                _GLOBAL = VerifyService()
    return _GLOBAL


def reset_global_service() -> None:
    """Stop and drop the global service (tests re-reading knobs)."""
    global _GLOBAL
    with _GLOBAL_MTX:
        svc, _GLOBAL = _GLOBAL, None
    if svc is not None:
        svc.stop()
