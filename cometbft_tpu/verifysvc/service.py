"""The unified verify service: one priority-scheduled seam in front of
the device verify pipeline.

Every signature-verification workload in the node — consensus
VerifyCommit, blocksync verify-ahead, the uncached fallback during comb
table warming, and mempool CheckTx — submits through this service
instead of driving the device verifiers (models/verifier.py,
models/comb_verifier.py) directly.  The service owns:

  * **Priority classes** (consensus > blocksync > mempool > background):
    a strict-priority scheduler dispatches ready consensus batches
    before anything else, so a flood of mempool CheckTx traffic can
    never delay a commit verification behind it.  An optional weighted
    mode (``COMETBFT_TPU_VERIFYSVC_WEIGHTS``) trades strictness for
    proportional interleave when starvation of low classes matters more
    than worst-case consensus latency.
  * **Adaptive batch formation**: a class's queue flushes when the
    pending signature count reaches the batch width
    (``COMETBFT_TPU_VERIFYSVC_BATCH_MAX``, reason=``full``) or when its
    oldest request has waited the class's flush deadline
    (``COMETBFT_TPU_VERIFYSVC_DEADLINE_<CLASS>_MS``, reason=
    ``deadline``), whichever comes first.  Consensus's deadline is 0 —
    its batches dispatch the moment the scheduler sees them — while
    mempool's small deadline is the coalescing window that merges per-tx
    CheckTx signature checks from concurrent senders into one device
    batch (the batch-width lever of arXiv:2302.00418; the
    tx-offload argument of arXiv:2112.02229).
  * **Bounded queues + backpressure**: each class's queue admits at most
    ``COMETBFT_TPU_VERIFYSVC_QUEUE_MAX`` signatures; a submit beyond
    that raises :class:`VerifyServiceBackpressure` (counted in
    ``verify_svc_rejected_total{class}``, flight-recorded) and the
    caller falls back to host verification — admission control, not an
    unbounded latency cliff.

Requests within one class that carry no validator-set binding coalesce
into shared batches; comb-bound requests (a whole commit against a
cached validator set) dispatch solo, because the comb program scatters
one row per validator.  Per-request blame order is preserved exactly:
each ticket's per-signature list follows its own add() order however
batches were merged or completed.

The scheduler thread only *dispatches* (the underlying submit() seam is
asynchronous — payload staging runs on the comb staging thread); a
separate collector thread drains results in dispatch order and resolves
tickets, so the scheduler is free to form the next batch while the
device runs the previous one.  Batches whose submit() does real inline
work — host-routed verifies below the device threshold, demoted comb
batches, and the uncached path's assembly/compile — go to a dedicated
host worker draining a CLASS-PRIORITY queue instead: that compute on
the scheduler thread would delay a consensus dispatch behind a mempool
batch, the inversion the class system exists to prevent, and the
priority queue bounds a queued consensus batch's extra wait to at most
one in-flight lower-class task.
"""

from __future__ import annotations

import queue
import threading
import time
from enum import IntEnum

from ..utils import envknobs, healthmon, tracing
from ..utils.flightrec import recorder as _flightrec
from ..utils.log import get_logger
from ..utils.metrics import hub as _mhub


class Klass(IntEnum):
    """Priority classes, highest first (lower value = dispatched first)."""

    CONSENSUS = 0
    BLOCKSYNC = 1
    MEMPOOL = 2
    BACKGROUND = 3

    @property
    def label(self) -> str:
        return self.name.lower()


_DEADLINE_KNOBS = {
    Klass.CONSENSUS: envknobs.VERIFYSVC_DEADLINE_CONSENSUS_MS,
    Klass.BLOCKSYNC: envknobs.VERIFYSVC_DEADLINE_BLOCKSYNC_MS,
    Klass.MEMPOOL: envknobs.VERIFYSVC_DEADLINE_MEMPOOL_MS,
    Klass.BACKGROUND: envknobs.VERIFYSVC_DEADLINE_BACKGROUND_MS,
}

# request modes: how the dispatcher binds a batch to a device program.
# ("plain",)        -> uncached kernel (power-of-two bucket shapes);
#                      coalescible with other plain requests of the class
# ("comb", entry)   -> comb-cached program bound to a valset cache entry
#                      (models/comb_verifier); dispatches solo — the
#                      scatter is one row per validator, so two commits
#                      against the same set cannot share a program call
MODE_PLAIN = ("plain",)

# host-queue shutdown sentinel: sorts after every real class so queued
# work settles before the worker exits
_HOST_SENTINEL_PRIO = 1 << 30


class VerifyServiceBackpressure(Exception):
    """A class's queue is at its signature bound; the caller must fall
    back to host verification (or shed the request)."""

    def __init__(self, klass: Klass, queued: int, limit: int):
        super().__init__(
            f"verify service backpressure: class {klass.label} has "
            f"{queued} signatures queued (limit {limit})"
        )
        self.klass = klass
        self.queued = queued
        self.limit = limit


class Ticket:
    """Handle for one submitted request; collect() blocks for
    (all_ok, per_signature) in the request's own add() order, or raises
    whatever the dispatch/collect path raised."""

    __slots__ = ("_ev", "_result", "_exc", "nsigs", "timings")

    def __init__(self, nsigs: int):
        self._ev = threading.Event()
        self._result: tuple[bool, list[bool]] | None = None
        self._exc: BaseException | None = None
        self.nsigs = nsigs
        self.timings: dict[str, float] = {}

    def _resolve(self, result, timings=None) -> None:
        self._result = result
        if timings:
            self.timings = dict(timings)
        self._ev.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def collect(self, timeout: float | None = None) -> tuple[bool, list[bool]]:
        if not self._ev.wait(timeout):
            raise TimeoutError("verify service ticket not resolved in time")
        if self._exc is not None:
            raise self._exc
        return self._result


class _Request:
    __slots__ = ("items", "klass", "mode", "ticket", "enq")

    def __init__(self, items, klass: Klass, mode):
        self.items = items
        self.klass = klass
        self.mode = mode
        self.ticket = Ticket(len(items))
        self.enq = time.monotonic()


def _parse_weights(spec: str) -> dict[Klass, int]:
    """``"consensus=8,blocksync=4,mempool=2,background=1"`` -> weights.
    Forgiving like the rest of the knob layer: malformed entries are
    dropped, an empty result means strict priority."""
    out: dict[Klass, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            k = Klass[name.strip().upper()]
            w = int(val)
        except (KeyError, ValueError):
            continue
        if w >= 1:
            out[k] = w
    return out


class VerifyService:
    """Priority-scheduled batching front of the device verify pipeline.

    Construction reads the ``COMETBFT_TPU_VERIFYSVC_*`` knobs once;
    explicit constructor arguments override them (tests).  Threads start
    lazily on first submit and are daemons; :meth:`stop` tears them down
    (in-flight tickets are failed, not leaked).
    """

    def __init__(
        self,
        batch_max: int | None = None,
        queue_max: int | None = None,
        deadlines_ms: dict[Klass, float] | None = None,
        weights: dict[Klass, int] | None = None,
    ):
        self.batch_max = max(
            1, batch_max if batch_max is not None
            else envknobs.get_int(envknobs.VERIFYSVC_BATCH_MAX)
        )
        self.queue_max = max(
            1, queue_max if queue_max is not None
            else envknobs.get_int(envknobs.VERIFYSVC_QUEUE_MAX)
        )
        if deadlines_ms is None:
            deadlines_ms = {
                k: max(0, envknobs.get_int(knob))
                for k, knob in _DEADLINE_KNOBS.items()
            }
        self._deadline_s = {
            k: float(deadlines_ms.get(k, 0)) / 1e3 for k in Klass
        }
        self._weights = (
            dict(weights) if weights is not None
            else _parse_weights(envknobs.get_str(envknobs.VERIFYSVC_WEIGHTS))
        )
        self._credits: dict[Klass, int] = {}
        self._queues: dict[Klass, list[_Request]] = {k: [] for k in Klass}
        self._queued_sigs: dict[Klass, int] = {k: 0 for k in Klass}
        self._cond = threading.Condition()
        self._collectq: queue.Queue = queue.Queue()
        # class-priority queue for batches whose submit() runs real work
        # inline (host routes, uncached assembly, cold-shape compiles):
        # entries (klass_value, seq, (bv, batch)); lower tuples first so
        # a queued consensus batch always overtakes queued mempool work
        self._hostq: queue.PriorityQueue = queue.PriorityQueue()
        self._hostseq = 0
        # batches handed to the device/host but not yet settled, keyed by
        # id(batch): the health sentinel's forensics read their ages to
        # say HOW LONG a wedged dispatch has been in flight
        self._inflight: dict[int, dict] = {}
        self._inflight_mtx = threading.Lock()
        self._running = False
        self._threads: list[threading.Thread] = []
        self._start_once = threading.Lock()
        self.logger = get_logger("verifysvc")
        # service-local tallies mirrored to hub metrics; the RPC status
        # endpoint reads these without scraping /metrics
        self._dispatched: dict[str, int] = {k.label: 0 for k in Klass}
        self._rejected: dict[str, int] = {k.label: 0 for k in Klass}

    # ------------------------------------------------------------ lifecycle

    def _ensure_started(self) -> None:
        if self._running:
            return
        with self._start_once:
            if self._running:
                return
            self._running = True
            self._threads = [
                threading.Thread(
                    target=self._sched_loop, name="verifysvc-sched",
                    daemon=True,
                ),
                threading.Thread(
                    target=self._collect_loop, name="verifysvc-collect",
                    daemon=True,
                ),
                threading.Thread(
                    target=self._host_loop, name="verifysvc-host",
                    daemon=True,
                ),
            ]
            for t in self._threads:
                t.start()

    def stop(self) -> None:
        """Tear down the scheduler/collector (tests).  Queued requests
        are failed with backpressure so no caller blocks forever."""
        with self._cond:
            if not self._running:
                return
            self._running = False
            stranded = [r for q in self._queues.values() for r in q]
            for k in Klass:
                self._queues[k] = []
                self._queued_sigs[k] = 0
            self._cond.notify_all()
        self._collectq.put(None)
        self._hostq.put((_HOST_SENTINEL_PRIO, 0, None))
        for r in stranded:
            r.ticket._fail(
                VerifyServiceBackpressure(r.klass, 0, self.queue_max)
            )
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        # a dispatch racing the sentinels can land its batch AFTER a
        # worker exited: fail those tickets too — stop() must never
        # leave a caller parked in collect() forever
        def _fail_batch(batch):
            for r in batch:
                r.ticket._fail(
                    VerifyServiceBackpressure(r.klass, 0, self.queue_max)
                )

        while True:
            try:
                item = self._collectq.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                _fail_batch(item[2])
        while True:
            try:
                _, _, payload = self._hostq.get_nowait()
            except queue.Empty:
                break
            if payload is not None:
                _fail_batch(payload[1])
        with self._inflight_mtx:
            self._inflight.clear()

    # ------------------------------------------------------------- submit

    def submit(self, items, klass: Klass, mode=MODE_PLAIN) -> Ticket:
        """Enqueue one verification request (a list of
        (pubkey, msg, sig) triples, verified as a unit) and return its
        ticket.  Raises :class:`VerifyServiceBackpressure` when the
        class's queue is at its signature bound."""
        items = list(items)
        if not items:
            t = Ticket(0)
            t._resolve((False, []))  # empty-batch contract of the verifiers
            return t
        self._ensure_started()
        n = len(items)
        m = _mhub()
        with self._cond:
            if not self._running:
                # stop() won the race after _ensure_started: enqueueing
                # onto a dead scheduler would park the caller forever —
                # reject so they take their host fallback instead
                raise VerifyServiceBackpressure(klass, 0, self.queue_max)
            queued = self._queued_sigs[klass]
            if queued + n > self.queue_max:
                self._rejected[klass.label] += 1
                rejected = self._rejected[klass.label]
            else:
                req = _Request(items, klass, mode)
                self._queues[klass].append(req)
                self._queued_sigs[klass] = queued + n
                depth = queued + n
                self._cond.notify()
                rejected = None
        if rejected is not None:
            # admission control: count it, flight-record it, and push the
            # decision back to the caller (host fallback / shed)
            m.verify_svc_rejected.inc(**{"class": klass.label})
            _flightrec().record(
                "verifysvc_backpressure",
                klass=klass.label, queued=queued, sigs=n, limit=self.queue_max,
            )
            tracing.instant(
                "verify.sched.reject",
                {"class": klass.label, "queued": queued, "sigs": n}
                if tracing.enabled() else None,
            )
            raise VerifyServiceBackpressure(klass, queued, self.queue_max)
        m.verify_svc_queue_depth.set(depth, **{"class": klass.label})
        return req.ticket

    def verify(self, items, klass: Klass, mode=MODE_PLAIN) -> tuple[bool, list[bool]]:
        """submit() + collect() in one call (synchronous callers)."""
        return self.submit(items, klass, mode).collect()

    # ---------------------------------------------------------- scheduler

    def _ready_locked(self, klass: Klass, now: float) -> bool:
        q = self._queues[klass]
        if not q:
            return False
        if self._queued_sigs[klass] >= self.batch_max:
            return True
        return (now - q[0].enq) >= self._deadline_s[klass]

    def _next_deadline_locked(self, now: float) -> float | None:
        """Seconds until the earliest not-yet-ready class flushes, or
        None when every queue is empty."""
        best = None
        for k in Klass:
            q = self._queues[k]
            if not q:
                continue
            remain = self._deadline_s[k] - (now - q[0].enq)
            if best is None or remain < best:
                best = remain
        return best

    def _pick_class_locked(self, now: float) -> Klass | None:
        ready = [k for k in Klass if self._ready_locked(k, now)]
        if not ready:
            return None
        if not self._weights:
            return ready[0]  # strict priority: Klass order
        # weighted interleave: spend per-class credits in priority order,
        # replenish when every ready class is out
        for k in ready:
            if self._credits.get(k, 0) > 0:
                self._credits[k] -= 1
                return k
        for k in Klass:
            self._credits[k] = self._weights.get(k, 1)
        self._credits[ready[0]] -= 1
        return ready[0]

    def _form_batch_locked(self, klass: Klass) -> tuple[list[_Request], str]:
        """Pop the head batch of a ready class.  Comb-bound requests go
        solo; plain requests coalesce up to the batch width."""
        q = self._queues[klass]
        # the flush reason is what made the CLASS ready, decided before
        # popping: a width-triggered flush whose head dispatches solo
        # (comb) must not read as a deadline expiry on the dashboards
        was_full = self._queued_sigs[klass] >= self.batch_max
        head = q.pop(0)
        batch = [head]
        total = len(head.items)
        if head.mode[0] != "comb":
            while q and q[0].mode[0] != "comb" and total < self.batch_max:
                nxt = q.pop(0)
                batch.append(nxt)
                total += len(nxt.items)
        self._queued_sigs[klass] -= total
        reason = "full" if (was_full or total >= self.batch_max) else "deadline"
        return batch, reason

    def _track_inflight(self, batch: list[_Request], where: str) -> None:
        with self._inflight_mtx:
            self._inflight[id(batch)] = {
                "class": batch[0].klass.label,
                "sigs": sum(len(r.items) for r in batch),
                "requests": len(batch),
                "where": where,
                "since": time.monotonic(),
            }

    def _untrack_inflight(self, batch: list[_Request]) -> None:
        with self._inflight_mtx:
            self._inflight.pop(id(batch), None)

    def _sched_loop(self) -> None:
        m = _mhub()
        while True:
            healthmon.beat("verifysvc-sched")
            with self._cond:
                if not self._running:
                    healthmon.retire("verifysvc-sched")
                    return
                now = time.monotonic()
                klass = self._pick_class_locked(now)
                if klass is None:
                    remain = self._next_deadline_locked(now)
                    # bounded wait (never a bare wait(): new submissions
                    # notify, deadlines cap the sleep, and an idle tick
                    # keeps shutdown prompt)
                    self._cond.wait(
                        0.5 if remain is None else max(0.0, min(remain, 0.5))
                    )
                    continue
                batch, reason = self._form_batch_locked(klass)
                depth = self._queued_sigs[klass]
            m.verify_svc_queue_depth.set(depth, **{"class": klass.label})
            self._dispatch(klass, batch, reason)

    def _make_verifier(self, mode):
        """Bind a batch to a device verifier.  The ONLY constructor seam
        for the data plane — tests monkeypatch this to observe dispatch
        order without touching a real kernel."""
        if mode[0] == "comb":
            from ..models.comb_verifier import CombBatchVerifier

            return CombBatchVerifier(mode[1])
        from ..models.verifier import TpuEd25519BatchVerifier

        return TpuEd25519BatchVerifier()

    @staticmethod
    def _submit_is_offloaded(bv, nsigs: int) -> bool:
        """Whether bv.submit() must run on the host worker instead of
        the scheduler thread.  Only the comb-cached staging path is
        genuinely cheap at submit time (the slab fill + H2D + dispatch
        run on the comb staging thread): everything else does real work
        inline — sub-threshold batches verify on host, demoted comb
        batches resolve their fallback synchronously, and the uncached
        device path runs host assembly plus, at a new bucket shape, the
        XLA compile.  Any of those on the scheduler thread would delay
        a consensus dispatch behind lower-class work."""
        if getattr(bv, "_entry", None) is None:  # plain/uncached path
            return True
        if getattr(bv, "_fallback", None) is not None:  # demoted comb
            return True
        from ..models.verifier import _device_batch_min

        return nsigs < _device_batch_min()  # comb submit host-routes

    def _dispatch(self, klass: Klass, batch: list[_Request], reason: str) -> None:
        m = _mhub()
        nsigs = sum(len(r.items) for r in batch)
        now = time.monotonic()
        for r in batch:
            m.verify_svc_queue_wait.observe(
                now - r.enq, **{"class": klass.label}
            )
        m.verify_svc_flush.inc(**{"class": klass.label, "reason": reason})
        self._dispatched[klass.label] += 1
        labels = (
            {"class": klass.label, "reason": reason,
             "sigs": nsigs, "requests": len(batch)}
            if tracing.enabled() else None
        )
        with tracing.span("verify.sched.dispatch", labels):
            try:
                bv = self._make_verifier(batch[0].mode)
                for r in batch:
                    for pub, msg, sig in r.items:
                        bv.add(pub, msg, sig)
                if self._submit_is_offloaded(bv, nsigs):
                    # real submit-time work: hand it to the host worker
                    # (class-priority queue) so the scheduler stays free
                    # to dispatch the next, possibly higher-class, batch
                    self._track_inflight(batch, "host")
                    self._hostseq += 1
                    self._hostq.put(
                        (int(klass), self._hostseq, (bv, batch))
                    )
                    return
                ticket = bv.submit()  # comb staging seam: cheap dispatch
            except BaseException as e:  # noqa: BLE001 — fail the tickets, keep scheduling
                self.logger.error(
                    f"dispatch failed (class={klass.label}, sigs={nsigs}): {e!r}"
                )
                for r in batch:
                    r.ticket._fail(e)
                return
        self._track_inflight(batch, "device")
        self._collectq.put((bv, ticket, batch))

    def _host_loop(self) -> None:
        """Drain submit-time work in class-priority order: queued
        consensus batches overtake queued lower-class ones (the worker
        can't preempt an in-flight verify/compile, so the worst-case
        consensus delay is ONE lower-class task, not a whole backlog)."""
        while True:
            healthmon.beat("verifysvc-host")
            try:
                _, _, payload = self._hostq.get(timeout=0.5)
            except queue.Empty:
                continue
            if payload is None:
                healthmon.retire("verifysvc-host")
                return
            bv, batch = payload
            klass = batch[0].klass
            labels = (
                {"class": klass.label, "requests": len(batch)}
                if tracing.enabled() else None
            )
            with tracing.span("verify.sched.hostwork", labels):
                try:
                    ticket = bv.submit()  # the inline work happens here
                except BaseException as e:  # noqa: BLE001 — fail the tickets, keep serving
                    self.logger.error(
                        f"host-route verify failed (class={klass.label}): {e!r}"
                    )
                    self._untrack_inflight(batch)
                    for r in batch:
                        r.ticket._fail(e)
                    continue
            if ticket[0] == "sync":
                self._settle(bv, ticket, batch)  # resolved already
            else:
                # device ticket (uncached path): the collector owns the
                # blocking result wait, freeing this worker immediately.
                # Relabel the in-flight record (same entry, age keeps
                # accruing) so a wedge during the collect blames the
                # device wait, not the finished host work
                with self._inflight_mtx:
                    rec = self._inflight.get(id(batch))
                    if rec is not None:
                        rec["where"] = "device"
                self._collectq.put((bv, ticket, batch))

    # ---------------------------------------------------------- collector

    def _collect_loop(self) -> None:
        while True:
            healthmon.beat("verifysvc-collect")
            try:
                item = self._collectq.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:
                healthmon.retire("verifysvc-collect")
                return
            self._settle(*item)

    def _settle(self, bv, ticket, batch: list[_Request]) -> None:
        """Resolve a dispatched batch's tickets from its verifier
        ticket, splitting the result vector back per request.  The batch
        stays in the in-flight table until it resolves either way — the
        blocking collect() below is exactly the wait whose age the
        health forensics need to report when a device wedges mid-batch."""
        try:
            self._settle_inner(bv, ticket, batch)
        finally:
            self._untrack_inflight(batch)

    def _settle_inner(self, bv, ticket, batch: list[_Request]) -> None:
        labels = (
            {"class": batch[0].klass.label,
             "requests": len(batch)}
            if tracing.enabled() else None
        )
        with tracing.span("verify.sched.collect", labels):
            try:
                _, res = bv.collect(ticket)
            except BaseException as e:  # noqa: BLE001 — fail the tickets, keep draining
                self.logger.error(
                    f"collect failed (class={batch[0].klass.label}): {e!r}"
                )
                for r in batch:
                    r.ticket._fail(e)
                return
        total = sum(len(r.items) for r in batch)
        if len(res) != total:
            err = RuntimeError(
                f"verifier returned {len(res)} results for {total} "
                "submitted signatures"
            )
            for r in batch:
                r.ticket._fail(err)
            return
        timings = getattr(bv, "last_timings", None)
        off = 0
        for r in batch:
            part = list(res[off : off + len(r.items)])
            off += len(r.items)
            # per-request verdict: the whole-batch all_ok is useless
            # once requests are coalesced — recompute from the slice
            # (matches the verifiers' own all(res) and bool(res))
            r.ticket._resolve((all(part) and bool(part), part), timings)

    # ------------------------------------------------------------- status

    def stats(self, lock_timeout: float | None = None) -> dict:
        """Snapshot for the /verify_svc_status RPC, bench reporting, and
        the health sentinel's stall forensics.  ``lock_timeout`` bounds
        the wait for the scheduler lock (the sentinel passes a small
        value: a diagnosis of a wedged node must not block on the wedge
        it is diagnosing); on timeout the queue section reads
        ``lock_busy`` and the lock-free tallies still report."""
        now = time.monotonic()
        with self._inflight_mtx:
            in_flight = [
                {
                    "class": rec["class"],
                    "sigs": rec["sigs"],
                    "requests": rec["requests"],
                    "where": rec["where"],
                    "age_s": round(now - rec["since"], 3),
                }
                for rec in self._inflight.values()
            ]
        if lock_timeout is None:
            acquired = self._cond.acquire()
        else:
            acquired = self._cond.acquire(timeout=lock_timeout)
        if acquired:
            try:
                queued = {
                    k.label: {
                        "requests": len(self._queues[k]),
                        "sigs": self._queued_sigs[k],
                    }
                    for k in Klass
                }
                dispatched = dict(self._dispatched)
                rejected = dict(self._rejected)
            finally:
                self._cond.release()
        else:
            queued = {"lock_busy": True}
            dispatched = dict(self._dispatched)
            rejected = dict(self._rejected)
        return {
            "in_flight": in_flight,
            "running": self._running,
            "batch_max": self.batch_max,
            "queue_max": self.queue_max,
            "deadline_ms": {
                k.label: self._deadline_s[k] * 1e3 for k in Klass
            },
            "weights": {k.label: w for k, w in self._weights.items()},
            "queued": queued,
            "dispatched_batches": dispatched,
            "rejected": rejected,
        }


_GLOBAL: VerifyService | None = None
_GLOBAL_MTX = threading.Lock()


def global_service() -> VerifyService:
    """The process-wide service every production consumer shares — one
    scheduler means one priority order across subsystems."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_MTX:
            if _GLOBAL is None:
                _GLOBAL = VerifyService()
    return _GLOBAL


def reset_global_service() -> None:
    """Stop and drop the global service (tests re-reading knobs)."""
    global _GLOBAL
    with _GLOBAL_MTX:
        svc, _GLOBAL = _GLOBAL, None
    if svc is not None:
        svc.stop()
