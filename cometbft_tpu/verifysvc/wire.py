"""Wire format of the out-of-process verify plane (verifyd).

Varint-length-prefixed protobuf over a plain TCP stream — the exact
framing the remote signer already speaks (privval/signer.py,
libs/protoio semantics via wire/proto.py) — carrying a small oneof
envelope (:class:`PlaneMessage`).  The protocol is deliberately tiny:

  * :class:`VerifyRequest` — one batch of (pub, msg, sig) triples
    verified as a unit.  Carries the **tenant** and **class** (the
    server's VerifyService schedules remote submitters exactly like
    local ones — quotas and weighted-fair interleave are enforced
    server-side), an **idempotency key** (``request_id`` UUID +
    ``digest`` over the canonical item encoding: a retried batch is
    recognizable and is never verified into a different blame order),
    and the **remaining deadline budget in ms** — budget, not a wall
    -clock deadline, crosses the wire, so client/server clock skew can
    never extend or strangle a request; every resend re-derives the
    remaining budget from the client's own monotonic clock.
  * :class:`VerifyResponse` — per-signature verdicts in the request's
    own add() order, or a typed non-OK status (backpressure with the
    tenant/scope that was hit, deadline expiry, error).  ``deduped``
    marks a response served from the server's idempotency window.
  * Ping/Status — liveness (the socket answers) vs readiness (the
    status payload says the scheduler is running); the breaker's
    probation probe uses ping.
  * ArmFault — chaos-only (gated on COMETBFT_TPU_FAULT_RPC in the
    verifyd process): lets a harness arm ``plane_crash``/``plane_stall``
    /``rpc_delay_ms``/``rpc_drop_pct`` in a live plane over the wire,
    so "kill -9 with this exact batch in flight" is deterministic.

Verdicts ride as a packed repeated varint (0/1) — ``bool`` fields can't
repeat in this codec, and packed ints are the compact proto3 idiom.
"""

from __future__ import annotations

import hashlib
import struct

from ..wire.proto import Field, Message, encode_varint

# VerifyResponse.status values
STATUS_OK = 0
STATUS_BACKPRESSURE = 1
STATUS_DEADLINE = 2
STATUS_ERROR = 3
STATUS_BAD_REQUEST = 4

STATUS_NAMES = {
    STATUS_OK: "ok",
    STATUS_BACKPRESSURE: "backpressure",
    STATUS_DEADLINE: "deadline",
    STATUS_ERROR: "error",
    STATUS_BAD_REQUEST: "bad_request",
}


class SigItem(Message):
    FIELDS = [
        Field(1, "pub", "bytes"),
        Field(2, "msg", "bytes"),
        Field(3, "sig", "bytes"),
    ]


class VerifyRequest(Message):
    FIELDS = [
        Field(1, "request_id", "bytes"),  # idempotency key half 1: UUID
        Field(2, "digest", "bytes"),      # idempotency key half 2: batch digest
        Field(3, "tenant", "string"),
        Field(4, "klass", "varint"),      # service.Klass value
        Field(5, "budget_ms", "varint"),  # REMAINING deadline budget
        Field(6, "items", "message", SigItem, repeated=True),
        Field(7, "attempt", "varint"),    # 1 = first send, >1 = idempotent resend
        # validator key type of the batch ("" = ed25519 for back-compat):
        # the server routes it to the matching verifier lane
        # (service.mode_for_key_type — ed25519 -> MODE_PLAIN,
        # bls12_381 -> MODE_BLS, secp256k1/secp256k1eth -> MODE_SECP);
        # an unknown value is bad_request
        Field(8, "key_type", "string"),
        # optional W3C traceparent ("00-<trace_id>-<span_id>-01",
        # utils/tracing.SpanContext): the client's span context, so the
        # plane's server-side spans join the submitter's trace across
        # the process boundary.  "" (the proto3 default) encodes to
        # NOTHING — a request without a context is byte-identical to
        # the pre-context wire, and an old decoder skips the field;
        # malformed values parse to "no context", never an error
        Field(9, "trace_ctx", "string"),
    ]


class VerifyResponse(Message):
    FIELDS = [
        Field(1, "request_id", "bytes"),
        Field(2, "status", "varint"),
        Field(3, "all_ok", "bool"),
        Field(4, "verdicts", "varint", repeated=True, packed=True),
        Field(5, "error", "string"),
        Field(6, "deduped", "bool"),
        Field(7, "scope", "string"),  # backpressure: which bound (tenant|class)
    ]


class PingRequest(Message):
    FIELDS = []


class PingResponse(Message):
    FIELDS = []


class StatusRequest(Message):
    FIELDS = []


class StatusResponse(Message):
    # JSON payload: forgiving for a diagnosis surface — the schema is the
    # server's stats() dict, which evolves with the service
    FIELDS = [Field(1, "json", "string")]


class ArmFaultRequest(Message):
    FIELDS = [
        Field(1, "name", "string"),
        Field(2, "value", "double"),
        Field(3, "clear", "bool"),  # clear instead of arm ("" clears all)
    ]


class ArmFaultResponse(Message):
    FIELDS = [
        Field(1, "ok", "bool"),
        Field(2, "error", "string"),
    ]


class ProofTree(Message):
    """One tree shipped by leaves; queries reference it by list position."""

    FIELDS = [Field(1, "leaves", "bytes", repeated=True)]


class ProofQuery(Message):
    FIELDS = [
        Field(1, "tree", "varint"),   # index into ProofRequest.trees
        Field(2, "index", "varint"),  # leaf index within that tree
    ]


class ProofMsg(Message):
    """One crypto/merkle.Proof on the wire.  ``total = 0`` marks a MISSING
    row (unknown tree / index out of range): a real proof always has
    total >= 1, so the sentinel cannot collide with a valid proof."""

    FIELDS = [
        Field(1, "total", "varint"),
        Field(2, "index", "varint"),
        Field(3, "leaf_hash", "bytes"),
        Field(4, "aunts", "bytes", repeated=True),
    ]


class ProofRequest(Message):
    """One batch of Merkle proof queries — the PROOF class's own wire
    shape (a VerifyRequest claiming key_type "proof" is a bad_request).
    Same idempotency key, budget, tenant/class, and trace-context
    contracts as VerifyRequest; ``digest`` is proof_digest() over the
    canonical tree+query encoding."""

    FIELDS = [
        Field(1, "request_id", "bytes"),
        Field(2, "digest", "bytes"),
        Field(3, "tenant", "string"),
        Field(4, "klass", "varint"),
        Field(5, "budget_ms", "varint"),
        Field(6, "trees", "message", ProofTree, repeated=True),
        Field(7, "queries", "message", ProofQuery, repeated=True),
        Field(8, "attempt", "varint"),
        Field(9, "trace_ctx", "string"),
    ]


class ProofResponse(Message):
    FIELDS = [
        Field(1, "request_id", "bytes"),
        Field(2, "status", "varint"),
        Field(3, "proofs", "message", ProofMsg, repeated=True),
        Field(4, "error", "string"),
        Field(5, "deduped", "bool"),
        Field(6, "scope", "string"),
    ]


class PlaneMessage(Message):
    """The oneof envelope on the verifyd socket."""

    FIELDS = [
        Field(1, "verify_request", "message", VerifyRequest),
        Field(2, "verify_response", "message", VerifyResponse),
        Field(3, "ping_request", "message", PingRequest),
        Field(4, "ping_response", "message", PingResponse),
        Field(5, "status_request", "message", StatusRequest),
        Field(6, "status_response", "message", StatusResponse),
        Field(7, "arm_fault_request", "message", ArmFaultRequest),
        Field(8, "arm_fault_response", "message", ArmFaultResponse),
        Field(9, "proof_request", "message", ProofRequest),
        Field(10, "proof_response", "message", ProofResponse),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None


def frame(msg: PlaneMessage) -> bytes:
    """Varint-length-prefixed encoding, ready for sendall()."""
    raw = msg.encode()
    return encode_varint(len(raw)) + raw


def batch_digest(items) -> bytes:
    """Canonical digest over a batch's (pub, msg, sig) triples — the
    content half of the idempotency key.  Length-prefixed fields so two
    different batches can never collide by boundary shifting."""
    h = hashlib.sha256()
    for pub, msg, sig in items:
        h.update(struct.pack("<I", len(pub)))
        h.update(pub)
        h.update(struct.pack("<I", len(msg)))
        h.update(msg)
        h.update(struct.pack("<I", len(sig)))
        h.update(sig)
    return h.digest()


def proof_digest(trees, queries) -> bytes:
    """Canonical digest over a proof request's trees + queries — the
    content half of its idempotency key.  Same length-prefixing rule as
    batch_digest; the tree/query section boundary is a length prefix
    too, so no boundary shifting between sections either."""
    h = hashlib.sha256()
    h.update(struct.pack("<I", len(trees)))
    for leaves in trees:
        h.update(struct.pack("<I", len(leaves)))
        for leaf in leaves:
            h.update(struct.pack("<I", len(leaf)))
            h.update(leaf)
    h.update(struct.pack("<I", len(queries)))
    for tree, index in queries:
        h.update(struct.pack("<II", tree, index))
    return h.digest()


def validate_proof_request(req: ProofRequest) -> tuple[list, list]:
    """Structural validation of a decoded ProofRequest — the ONE gate
    between wire bytes and the proof data plane (taint source
    ``verifysvc-proof-request``).  Returns (trees, queries) as plain
    Python lists; every malformed shape raises ValueError, which the
    server answers as bad_request (the decode gauntlet pins that no
    other exception type can escape this surface)."""
    if not req.request_id:
        raise ValueError("proof request missing request_id")
    if len(req.digest or b"") != 32:
        raise ValueError("proof request digest must be 32 bytes")
    trees = []
    for t in req.trees or []:
        leaves = list(t.leaves or [])
        if not leaves:
            raise ValueError("proof request tree has no leaves")
        trees.append(leaves)
    queries = []
    for q in req.queries or []:
        tree = int(q.tree or 0)
        index = int(q.index or 0)
        if tree < 0 or tree >= len(trees):
            raise ValueError(f"proof query references unknown tree {tree}")
        if index < 0 or index >= len(trees[tree]):
            raise ValueError(
                f"proof query index {index} out of range for tree {tree}"
            )
        queries.append((tree, index))
    if not queries:
        raise ValueError("proof request has no queries")
    if proof_digest(trees, queries) != req.digest:
        raise ValueError("proof request digest mismatch")
    return trees, queries


class FrameReader:
    """Incremental varint-delimited PlaneMessage reader over a socket.

    recv() must be called with the socket's timeout already configured
    (the socket-without-timeout contract lives with the socket's owner);
    returns None on clean EOF, raises socket.timeout/OSError upward.
    A frame larger than ``max_frame`` desyncs nothing — it raises, and
    the owner drops the connection (the privval stream-desync rule).
    """

    def __init__(self, sock, max_frame: int = 64 << 20):
        self._sock = sock
        self._buf = bytearray()
        self._max = max_frame

    def read(self) -> PlaneMessage | None:
        while True:
            msg = self._try_decode()
            if msg is not None:
                return msg
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                return None
            self._buf += chunk

    def _try_decode(self) -> PlaneMessage | None:
        buf = self._buf
        # decode the varint prefix by hand so a partial prefix just waits
        n = 0
        shift = 0
        pos = 0
        while True:
            if pos >= len(buf):
                return None
            b = buf[pos]
            pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                raise ValueError("verify-plane frame: varint overflow")
        if n > self._max:
            raise ValueError(f"verify-plane frame too large ({n} bytes)")
        if len(buf) - pos < n:
            return None
        payload = bytes(buf[pos : pos + n])
        del buf[: pos + n]
        return PlaneMessage.decode(payload)
