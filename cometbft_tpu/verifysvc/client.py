"""Client-side adapters for the verify service.

:class:`ServiceBatchVerifier` implements the BatchVerifier contract
(crypto/crypto.go:47-55) — add() accumulates, verify()/submit()/collect()
resolve — but routes the batch through the process-global
:class:`~cometbft_tpu.verifysvc.service.VerifyService` instead of driving
a device verifier directly.  crypto/batch.create_batch_verifier returns
one of these whenever the device backend is selectable, so every legacy
call site (types/validation, blocksync, light, evidence) became a verify
-service client without changing its own shape.

Backpressure handling lives here, on the caller's side of the seam: a
rejected submit degrades to an inline host verification
(`verify.svc_fallback` span) — correct results, no device batching, and
the rejection is already counted/flight-recorded by the service.
"""

from __future__ import annotations

import time

from ..utils import tracing
from .service import (
    MODE_BLS,
    MODE_PLAIN,
    MODE_SECP,
    Klass,
    VerifyService,
    VerifyServiceBackpressure,
    collect_timeout_s,
    default_tenant,
    global_service,
    report_collect_stall,
)


def resolve_mode(pubkeys: list[bytes] | None, key_type: str = "ed25519"):
    """Bind a request to its device program up front, in the CALLER's
    thread — exactly where the comb-table ensure()/ensure_async() cost
    landed before the service existed (a 10k-validator table build must
    never run on, and block, the shared scheduler thread).

    Mirrors the pre-service routing of crypto/batch.create_batch_verifier:
    BLS validator sets take the aggregate lane (MODE_BLS — no comb
    tables; the BLS plane owns its own pubkey-validation cache), secp
    sets (both the Cosmos and Ethereum wire formats) the batched ECDSA
    lane (MODE_SECP — the Shamir G table is a process-resident
    device_put constant, nothing to bind per set), large known ed25519
    sets use the comb-cached program (background build while warming ->
    uncached), everything else the uncached kernel."""
    if key_type == "bls12_381":
        return MODE_BLS
    if key_type in ("secp256k1", "secp256k1eth", "ecrecover"):
        return MODE_SECP
    if pubkeys is None:
        return MODE_PLAIN
    from .service import _GLOBAL, remote_plane_configured

    if remote_plane_configured():
        # a remote-bound process must not build a local table it will
        # never use — checked against the ENV, not just the installed
        # service's binding: a service constructed before the knob was
        # set would otherwise kick a background table build (minutes of
        # compile) for a plane that owns its own device-resident tables
        return MODE_PLAIN
    if _GLOBAL is not None:
        if _GLOBAL.backend_mode != "tpu" or _GLOBAL.remote_addr:
            # degraded mode: comb table binds are bypassed entirely — an
            # ensure()/ensure_async() is DEVICE work (table build + H2D),
            # exactly the hang the failover trip escaped.  Same with a
            # remote plane configured: device-resident tables belong to
            # the PLANE's process, not this one.  Peek the module
            # global, never global_service(): resolving a mode must not
            # construct and install a fresh scheduler.
            return MODE_PLAIN
    from ..crypto import batch as crypto_batch

    if len(pubkeys) < crypto_batch.comb_min():
        return MODE_PLAIN
    from ..models.comb_verifier import global_cache

    if len(pubkeys) >= crypto_batch.comb_async_min():
        entry = global_cache().ensure_async(list(pubkeys))
        if entry is None:
            return MODE_PLAIN  # tables still warming: uncached kernel
        return ("comb", entry)
    return ("comb", global_cache().ensure(list(pubkeys)))


class ServiceBatchVerifier:
    """BatchVerifier bound to a priority class of the verify service.

    Exposes the same async submit()/collect() seam as the device
    verifiers it replaced, so pipelined callers (blocksync verify-ahead,
    types/validation.submit_verify_commit_light) work unchanged."""

    def __init__(
        self,
        klass: Klass = Klass.CONSENSUS,
        mode=MODE_PLAIN,
        service: VerifyService | None = None,
        tenant: str | None = None,
    ):
        self._klass = klass
        self._mode = mode
        self._svc = service
        self._tenant = tenant if tenant is not None else default_tenant()
        self._items: list[tuple[bytes, bytes, bytes]] = []
        self.last_timings: dict[str, float] = {}
        # this batch's span context, minted at submit(): the service
        # request inherits it (and carries it to a remote plane), and
        # the host-fallback / collect-stall paths re-install it so a
        # degraded batch's spans still share one trace_id
        self._ctx = None

    def __len__(self) -> int:
        return len(self._items)

    @property
    def klass(self) -> Klass:
        return self._klass

    @property
    def tenant(self) -> str:
        return self._tenant

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        if self._mode[0] == "bls":
            # 48-byte compressed G1 pubkey, 96-byte compressed G2 sig
            if len(pub_key) != 48 or len(sig) != 96:
                raise ValueError("malformed bls12-381 pubkey or signature")
            self._items.append((pub_key, msg, sig))
            return
        if self._mode[0] == "secp":
            # 33-byte compressed (cosmos, 64-byte r||s), 65-byte
            # uncompressed (eth, 65-byte R||S||V), or 20-byte sender
            # address (ecrecover, 65-byte R||S||V) wire shapes
            if len(pub_key) not in (20, 33, 65) or len(sig) not in (64, 65):
                raise ValueError("malformed secp256k1 pubkey or signature")
            self._items.append((pub_key, msg, sig))
            return
        if len(pub_key) != 32 or len(sig) != 64:
            raise ValueError("malformed ed25519 pubkey or signature")
        if len(msg) >= 1 << 24:
            # the comb payload's mlen field is 3 bytes (models/
            # comb_verifier); raise at add() time like CombBatchVerifier
            # did, not as a deferred dispatch failure
            raise ValueError("message too large for batch verification")
        self._items.append((pub_key, msg, sig))

    def _service(self) -> VerifyService:
        if self._svc is None:
            self._svc = global_service()
        return self._svc

    def _host_fallback(self, span_name: str) -> tuple[bool, list[bool]]:
        """Inline host verification of OUR retained items — correct
        verdicts in our own add() order, shared by the backpressure and
        collect-stall paths.  Mode-aware: a BLS batch degrades to the
        pure-host BLS verifier (bit-identical verdict procedure), never
        the ed25519 one."""
        from .service import cpu_verifier_for_mode

        cpu = cpu_verifier_for_mode(self._mode)
        cpu._items = list(self._items)
        with tracing.context_scope(self._ctx), tracing.span(
            span_name,
            {"class": self._klass.label, "sigs": len(cpu._items)}
            if tracing.enabled() else None,
        ):
            return cpu.verify()

    def submit(self):
        """Enqueue with the service and return an opaque ticket for
        collect().  On backpressure the batch is verified inline on the
        host — the caller-side fallback of the admission-control loop."""
        if not self._items:
            return ("sync", (False, []))
        if tracing.propagation_enabled() and self._ctx is None:
            # root of this batch's trace — unless the caller already
            # installed one (e.g. an RPC-served verify), which we join
            self._ctx = tracing.current_context() or tracing.new_context()
        try:
            with tracing.context_scope(self._ctx):
                return ("svc", self._service().submit(
                    list(self._items), self._klass, self._mode,
                    tenant=self._tenant,
                ))
        except VerifyServiceBackpressure:
            return ("sync", self._host_fallback("verify.svc_fallback"))

    def collect(self, ticket) -> tuple[bool, list[bool]]:
        kind, payload = ticket
        if kind == "sync":
            return payload
        # bounded wait: a live-but-stuck scheduler (accepted the submit,
        # never resolved the ticket) must not park a consensus or
        # blocksync caller forever.  On expiry: stall forensics, then the
        # host fallback — first-wins ticket settlement discards the
        # service's late answer if it ever comes.
        timeout = collect_timeout_s()
        t0 = time.monotonic()
        try:
            result = payload.collect(timeout)
        except VerifyServiceBackpressure:
            # a REMOTE plane's server-side quota rejected the batch
            # after local admission (the reject rides the response and
            # fails the ticket): same contract as a local reject —
            # verify inline on host; the service never does it for us
            return self._host_fallback("verify.svc_fallback")
        except TimeoutError:
            report_collect_stall(
                self._klass, self._tenant, len(self._items),
                time.monotonic() - t0, service=self._svc,
            )
            return self._host_fallback("verify.collect_stall_fallback")
        if payload.timings:
            self.last_timings.update(payload.timings)
        return result

    def verify(self) -> tuple[bool, list[bool]]:
        return self.collect(self.submit())
