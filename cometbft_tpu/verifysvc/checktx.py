"""Mempool CheckTx signature gate — the verify service's fourth client.

At production scale per-tx signature checks on mempool ingest dwarf
commit verification (ROADMAP item 4; the FPGA verification-engine study
arXiv:2112.02229 makes the same point for permissioned chains), and
before this module they never touched the accelerator: the reference
delegates tx signature checking entirely to the application.

This module defines a minimal *signed-tx envelope* the node itself can
verify before the tx ever reaches the app's CheckTx:

    ``MAGIC(8) | pubkey(32) | signature(64) | payload``

with the ed25519 signature over ``SIGN_DOMAIN + payload`` (domain
separation: a tx signature can never be replayed as a vote signature or
vice versa).  Transactions that don't start with the magic are passed
through untouched — the gate is opt-in per tx, so apps with their own
signature schemes lose nothing.

Each CheckTx caller submits its single (pubkey, msg, sig) to the verify
service's MEMPOOL class; the class's flush deadline is the coalescing
window that merges checks from concurrent senders (p2p gossip threads,
RPC broadcast handlers) into one device batch.  When the device backend
isn't selectable, or the service pushes back, the check runs on the host
(``crypto/ed25519.verify_signature``) — bit-identical semantics either
way (both ends are ZIP-215; tests/test_comb_tree.py pins kernel == host).
"""

from __future__ import annotations

from ..crypto import ed25519 as host_ed25519
from .service import (
    Klass,
    VerifyService,
    VerifyServiceBackpressure,
    collect_timeout_s,
    global_service,
    report_collect_stall,
)

MAGIC = b"\xd0sigtx1\x00"
SIGN_DOMAIN = b"cometbft-tpu/sigtx/v1|"
_HEADER_LEN = len(MAGIC) + 32 + 64


def make_signed_tx(priv_key, payload: bytes) -> bytes:
    """Wrap payload in the signed envelope (tests, loadgen, bench)."""
    sig = priv_key.sign(SIGN_DOMAIN + payload)
    return MAGIC + priv_key.pub_key().data + sig + payload


def parse_signed_tx(tx: bytes) -> tuple[bytes, bytes, bytes] | None:
    """(pubkey, signature, payload) when tx carries the envelope, else
    None (an unsigned tx — not an error)."""
    if len(tx) < _HEADER_LEN or not tx.startswith(MAGIC):
        return None
    off = len(MAGIC)
    return tx[off : off + 32], tx[off + 32 : off + 96], tx[_HEADER_LEN:]


def verify_tx_signature(
    tx: bytes,
    service: VerifyService | None = None,
    tenant: str | None = None,
) -> bool | None:
    """Verify a tx's envelope signature through the verify service.

    Returns None for unsigned txs (no envelope), True/False for signed
    ones.  Device-batched through the MEMPOOL class — under ``tenant``
    (None = this process's default tenant) — when the accelerator
    backend is selectable; host verification otherwise, on backpressure,
    and on a collect-deadline stall — the caller never needs to know
    which path ran."""
    parsed = parse_signed_tx(tx)
    if parsed is None:
        return None
    pub, sig, payload = parsed
    msg = SIGN_DOMAIN + payload
    svc = service
    if svc is None:
        from ..crypto import batch as crypto_batch

        from .service import remote_plane_configured

        if crypto_batch.device_capable() or remote_plane_configured():
            # a node with no local accelerator still batches through a
            # configured shared remote plane
            svc = global_service()
    if svc is not None:
        import time as _time

        t0 = _time.monotonic()
        try:
            _, per = svc.submit(
                [(pub, msg, sig)], Klass.MEMPOOL, tenant=tenant
            ).collect(collect_timeout_s())
            return bool(per and per[0])
        except VerifyServiceBackpressure:
            pass  # admission control said no: fall through to the host
        except TimeoutError:
            # live-but-stuck scheduler: leave forensics, take the host
            # path (first-wins settlement discards the late answer)
            from .service import default_tenant

            report_collect_stall(
                Klass.MEMPOOL,
                tenant if tenant is not None else default_tenant(),
                1, _time.monotonic() - t0, service=svc,
            )
        except ValueError:
            return False  # malformed pubkey/sig lengths can't be valid
    return host_ed25519.verify_signature(pub, msg, sig)
