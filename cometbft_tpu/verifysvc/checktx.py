"""Mempool CheckTx signature gate — the verify service's fourth client.

At production scale per-tx signature checks on mempool ingest dwarf
commit verification (ROADMAP item 4; the FPGA verification-engine study
arXiv:2112.02229 makes the same point for permissioned chains), and
before this module they never touched the accelerator: the reference
delegates tx signature checking entirely to the application.

This module defines a minimal *signed-tx envelope* the node itself can
verify before the tx ever reaches the app's CheckTx.  Two wire
versions:

    v1 (legacy): ``MAGIC_V1(8) | pubkey(32) | signature(64) | payload``
                 — always ed25519; every pre-key-type envelope on disk
                 or in flight keeps parsing and verifying unchanged.
    v2:          ``MAGIC_V2(8) | key_type(1) | pubkey | signature | payload``
                 — the key-type byte selects the signature scheme and
                 fixes the pubkey/signature widths:

                     0x00  ed25519        pub 32   sig 64
                     0x01  secp256k1      pub 33   sig 64  (r||s, SHA-256)
                     0x02  secp256k1eth   pub 65   sig 65  (R||S||V, Keccak)
                     0x03  ecrecover      pub 20   sig 65  (R||S||V, Keccak;
                           the "pubkey" is the 20-byte sender ADDRESS —
                           the verifier recovers the signer and compares
                           the derived address, the real Ethereum tx
                           shape where no pubkey rides the wire)

In both versions the signature is over ``SIGN_DOMAIN + payload``
(domain separation: a tx signature can never be replayed as a vote
signature or vice versa).  Transactions that don't carry a well-formed
envelope are passed through untouched — the gate is opt-in per tx, so
apps with their own signature schemes lose nothing.

Each CheckTx caller submits its single (pubkey, msg, sig) to the verify
service's MEMPOOL class under the key type's dispatch mode (ed25519 ->
MODE_PLAIN, secp types -> MODE_SECP — the key-type routing seam of
verifysvc/service.mode_for_key_type); the class's flush deadline is the
coalescing window that merges checks from concurrent senders into one
device batch per mode.  When the device backend isn't selectable, or
the service pushes back, the check runs on the host through the SAME
per-mode cpu verifier every fallback path shares
(``service.cpu_verifier_for_mode``) — bit-identical semantics either
way.
"""

from __future__ import annotations

from ..utils import tracing
from .service import (
    Klass,
    VerifyService,
    VerifyServiceBackpressure,
    _host_verify_items,
    collect_timeout_s,
    global_service,
    mode_for_key_type,
    report_collect_stall,
)

MAGIC = b"\xd0sigtx1\x00"  # v1: implicit ed25519 (the pre-key-type wire)
MAGIC_V2 = b"\xd0sigtx2\x00"  # v2: explicit key-type byte
SIGN_DOMAIN = b"cometbft-tpu/sigtx/v1|"
_HEADER_LEN = len(MAGIC) + 32 + 64

# key-type byte -> (key type name, pubkey width, signature width)
KEY_TYPE_BYTES: dict[str, int] = {
    "ed25519": 0,
    "secp256k1": 1,
    "secp256k1eth": 2,
    "ecrecover": 3,
}
_KT_SHAPES: dict[int, tuple[str, int, int]] = {
    0: ("ed25519", 32, 64),
    1: ("secp256k1", 33, 64),
    2: ("secp256k1eth", 65, 65),
    3: ("ecrecover", 20, 65),
}


def make_signed_tx(priv_key, payload: bytes) -> bytes:
    """Wrap payload in the signed envelope (tests, loadgen, bench).

    ed25519 keys keep emitting the v1 wire — every deployed parser
    (and a pre-key-type shared verify plane's host fallback)
    understands it; secp keys emit v2 with their key-type byte."""
    sig = priv_key.sign(SIGN_DOMAIN + payload)
    kt = getattr(priv_key, "type", "ed25519")
    if kt == "ed25519":
        return MAGIC + priv_key.pub_key().data + sig + payload
    ktb = KEY_TYPE_BYTES[kt]
    return MAGIC_V2 + bytes([ktb]) + priv_key.pub_key().data + sig + payload


def parse_signed_tx(tx: bytes) -> tuple[str, bytes, bytes, bytes] | None:
    """(key_type, pubkey, signature, payload) when tx carries a
    well-formed envelope, else None (an unsigned tx — not an error;
    malformed envelopes pass through unsigned exactly like the v1
    parser always treated short v1 headers)."""
    if tx.startswith(MAGIC):
        if len(tx) < _HEADER_LEN:
            return None
        off = len(MAGIC)
        return (
            "ed25519",
            tx[off : off + 32],
            tx[off + 32 : off + 96],
            tx[_HEADER_LEN:],
        )
    if tx.startswith(MAGIC_V2):
        off = len(MAGIC_V2)
        if len(tx) < off + 1:
            return None
        shape = _KT_SHAPES.get(tx[off])
        if shape is None:
            return None  # unknown key type: not our envelope
        kt, npub, nsig = shape
        off += 1
        if len(tx) < off + npub + nsig:
            return None
        return (
            kt,
            tx[off : off + npub],
            tx[off + npub : off + npub + nsig],
            tx[off + npub + nsig :],
        )
    return None


def _host_verify(mode, pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Inline host verdict through the ONE shared fallback procedure
    (service._host_verify_items -> cpu_verifier_for_mode): a malformed
    row judges False here, never raises."""
    _, per = _host_verify_items([(pub, msg, sig)], mode)
    return bool(per and per[0])


def verify_tx_signature(
    tx: bytes,
    service: VerifyService | None = None,
    tenant: str | None = None,
) -> bool | None:
    """Verify a tx's envelope signature through the verify service.

    Returns None for unsigned txs (no envelope), True/False for signed
    ones.  Device-batched through the MEMPOOL class — under ``tenant``
    (None = this process's default tenant), in the key type's dispatch
    mode — when the accelerator backend is selectable; host
    verification otherwise, on backpressure, and on a collect-deadline
    stall — the caller never needs to know which path ran."""
    parsed = parse_signed_tx(tx)
    if parsed is None:
        return None
    key_type, pub, sig, payload = parsed
    msg = SIGN_DOMAIN + payload
    # the ONE key-type routing seam (service._KEY_TYPE_MODE); every
    # key type parse_signed_tx can emit has a mode there
    mode = mode_for_key_type(key_type)
    svc = service
    if svc is None:
        from ..crypto import batch as crypto_batch

        from .service import remote_plane_configured

        if crypto_batch.device_capable() or remote_plane_configured():
            # a node with no local accelerator still batches through a
            # configured shared remote plane
            svc = global_service()
    # one span context per signed tx: the service request inherits it
    # (riding the wire to a remote plane), and the host fallback below
    # re-installs it, so a degraded check still traces as one trace_id
    ctx = (
        (tracing.current_context() or tracing.new_context())
        if tracing.propagation_enabled() else None
    )
    if svc is not None:
        import time as _time

        t0 = _time.monotonic()
        try:
            with tracing.context_scope(ctx):
                _, per = svc.submit(
                    [(pub, msg, sig)], Klass.MEMPOOL, mode, tenant=tenant
                ).collect(collect_timeout_s())
            return bool(per and per[0])
        except VerifyServiceBackpressure:
            pass  # admission control said no: fall through to the host
        except TimeoutError:
            # live-but-stuck scheduler: leave forensics, take the host
            # path (first-wins settlement discards the late answer)
            from .service import default_tenant

            report_collect_stall(
                Klass.MEMPOOL,
                tenant if tenant is not None else default_tenant(),
                1, _time.monotonic() - t0, service=svc,
            )
        except ValueError:
            return False  # malformed pubkey/sig lengths can't be valid
    with tracing.context_scope(ctx):
        return _host_verify(mode, pub, msg, sig)
