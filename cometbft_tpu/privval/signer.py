"""Remote signer: validator keys in a separate process/HSM
(reference: privval/signer_listener_endpoint.go, signer_client.go,
signer_server.go, retry_signer_client.go).

Topology matches the reference: the NODE listens on
priv_validator_laddr; the SIGNER dials in and serves signing requests
over varint-delimited protobuf.  SignerClient implements the
PrivValidator surface (get_pub_key / sign_vote / sign_proposal) against
the connected signer; the HRS double-sign protection lives with the key
holder (the signer's FilePV), exactly like the reference.
"""

from __future__ import annotations

import socket
import threading
import time

from ..p2p.conn.secret_connection import (
    SecretConnectionError,
    make_secret_connection,
)
from ..types.proposal import Proposal
from ..types.vote import Vote
from ..utils.log import get_logger
from ..wire import privval_pb as pb
from ..wire.proto import encode_varint


class RemoteSignerError(Exception):
    pass


class SignerTransportError(RemoteSignerError):
    """Connection-level failure: retryable.  Signer-side rejections
    (double-sign refusals, chain-id mismatches) stay plain
    RemoteSignerError and are permanent — the reference's retry client
    only retries transport errors (retry_signer_client.go)."""


def decode_varint_stream(conn) -> int | None:
    """Read one varint length prefix off a conn (protoio reader)."""
    shift, out = 0, 0
    while True:
        b = conn.read(1)
        if not b:
            return None
        out |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            return out
        shift += 7
        if shift > 63:
            raise RemoteSignerError("varint overflow")


#: Privval frames are single sign requests/responses; the reference
#: bounds them via protoio's maxMsgSize.  The prefix sizes the read
#: loop's recv() calls, so it must be checked before any allocation —
#: even on this authenticated link, the peer's bytes are not ours.
MAX_PRIVVAL_MSG_SIZE = 1024 * 1024


def _send_msg(conn, msg: pb.PrivvalMessage) -> None:
    raw = msg.encode()
    conn.write(encode_varint(len(raw)) + raw)


def _recv_msg(conn) -> pb.PrivvalMessage | None:
    n = decode_varint_stream(conn)
    if n is None:
        return None
    if n > MAX_PRIVVAL_MSG_SIZE:
        raise RemoteSignerError(f"privval frame {n} exceeds max")
    buf = b""
    while len(buf) < n:
        chunk = conn.read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return pb.PrivvalMessage.decode(buf)


class _PlainConn:
    """socket -> read/write duplex (unix-socket style deployments where
    filesystem permissions are the auth boundary)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock

    def write(self, data: bytes):
        self._sock.sendall(data)
        return len(data)

    def read(self, n: int) -> bytes:
        return self._sock.recv(n)

    def close(self) -> None:
        self._sock.close()


class SignerListenerEndpoint:
    """Node side: accept the signer's inbound connection and do locked
    request/response over it (signer_listener_endpoint.go).

    With identity_key set, every inbound connection runs the STS
    handshake (SecretConnection, like the reference's tcp:// listeners)
    and, when authorized_keys is given, the signer's identity pubkey must
    be in it — an unauthorized dialer cannot displace the real signer."""

    def __init__(
        self,
        addr: str,
        timeout: float = 5.0,
        ping_period: float = 10.0,
        identity_key=None,
        authorized_keys: list[bytes] | None = None,
    ):
        host, _, port = addr.rpartition(":")
        self._listener = socket.create_server((host or "127.0.0.1", int(port)))
        self.listen_addr = (
            f"{self._listener.getsockname()[0]}:{self._listener.getsockname()[1]}"
        )
        self.timeout = timeout
        self.ping_period = ping_period
        self.identity_key = identity_key
        self.authorized_keys = authorized_keys
        self.logger = get_logger("privval-listener")
        if identity_key is None:
            self.logger.error(
                "privval listener running UNENCRYPTED: use identity_key "
                "(SecretConnection) for anything beyond localhost tests"
            )
        self._mtx = threading.Lock()
        self._conn = None
        self._conn_ready = threading.Event()
        self._stopped = False
        threading.Thread(
            target=self._accept_routine, daemon=True, name="privval-accept"
        ).start()
        threading.Thread(
            target=self._ping_routine, daemon=True, name="privval-ping"
        ).start()

    def _accept_routine(self) -> None:
        while not self._stopped:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.settimeout(self.timeout)
            try:
                conn = self._secure(sock)
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"signer handshake rejected: {e}")
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._mtx:
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                self._conn = conn
            self._conn_ready.set()
            self.logger.info("remote signer connected")

    def _secure(self, sock: socket.socket):
        if self.identity_key is None:
            return _PlainConn(sock)
        conn = make_secret_connection(sock, self.identity_key)
        if self.authorized_keys is not None and (
            conn.remote_pub.data not in self.authorized_keys
        ):
            conn.close()
            raise RemoteSignerError(
                f"signer identity {conn.remote_pub.data.hex()[:16]} not in "
                "the authorized key list"
            )
        return conn

    def _ping_routine(self) -> None:
        while not self._stopped:
            time.sleep(self.ping_period)
            try:
                self.request(pb.PrivvalMessage(ping_request=pb.PingRequest()))
            except SignerTransportError:
                pass

    def wait_for_signer(self, timeout: float = 30.0) -> bool:
        return self._conn_ready.wait(timeout)

    def request(self, msg: pb.PrivvalMessage) -> pb.PrivvalMessage:
        with self._mtx:
            conn = self._conn
            if conn is None:
                raise SignerTransportError("no signer connected")
            try:
                _send_msg(conn, msg)
                resp = _recv_msg(conn)
            except (OSError, SecretConnectionError) as e:
                # SecretConnectionError surfaces when the peer closes
                # mid-frame (e.g. teardown racing the ping routine)
                self._drop(conn)
                raise SignerTransportError(f"signer connection failed: {e}") from e
            except (RemoteSignerError, ValueError) as e:
                # parse failure mid-stream (varint overflow or proto
                # decode error): the framing is desynced — drop the conn
                # and classify as TRANSPORT failure (retryable: the
                # signer redials, and the ping loop must survive it)
                self._drop(conn)
                raise SignerTransportError(
                    f"signer stream desynced: {e}"
                ) from e
            if resp is None:
                self._drop(conn)
                raise SignerTransportError("signer connection closed")
            return resp

    def _drop(self, conn) -> None:
        try:
            conn.close()
        except OSError:
            pass
        if self._conn is conn:
            self._conn = None
            self._conn_ready.clear()

    def close(self) -> None:
        self._stopped = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._mtx:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


class SignerClient:
    """PrivValidator over a SignerListenerEndpoint (signer_client.go)."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self.endpoint = endpoint
        self.chain_id = chain_id
        self._pub_key = None

    # PrivValidator surface -------------------------------------------------

    def get_pub_key(self):
        if self._pub_key is None:
            resp = self.endpoint.request(
                pb.PrivvalMessage(
                    pub_key_request=pb.PubKeyRequest(chain_id=self.chain_id)
                )
            )
            r = resp.pub_key_response
            if r is None:
                raise RemoteSignerError(f"unexpected response {resp.which()}")
            if r.error is not None:
                raise RemoteSignerError(r.error.description)
            from ..crypto import encoding as keyenc

            self._pub_key = keyenc.pubkey_from_type_and_bytes(
                r.pub_key_type or "ed25519", r.pub_key_bytes
            )
        return self._pub_key

    # `key` facade so ConsensusState's address lookups keep working
    @property
    def key(self):
        class _K:
            priv_key = None

            def __init__(k, pub):
                k.pub = pub

        pub = self.get_pub_key()

        class _PK:
            def pub_key(self):
                return pub

        k = _K(pub)
        k.priv_key = _PK()
        return k

    def sign_vote(self, chain_id: str, vote: Vote, sign_extension: bool = False) -> None:
        resp = self.endpoint.request(
            pb.PrivvalMessage(
                sign_vote_request=pb.SignVoteRequest(
                    vote=vote.to_proto(),
                    chain_id=chain_id,
                    skip_extension_signing=not sign_extension,
                )
            )
        )
        r = resp.signed_vote_response
        if r is None:
            raise RemoteSignerError(f"unexpected response {resp.which()}")
        if r.error is not None:
            raise RemoteSignerError(r.error.description)
        signed = Vote.from_proto(r.vote)
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp
        vote.extension_signature = signed.extension_signature

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        resp = self.endpoint.request(
            pb.PrivvalMessage(
                sign_proposal_request=pb.SignProposalRequest(
                    proposal=proposal.to_proto(), chain_id=chain_id
                )
            )
        )
        r = resp.signed_proposal_response
        if r is None:
            raise RemoteSignerError(f"unexpected response {resp.which()}")
        if r.error is not None:
            raise RemoteSignerError(r.error.description)
        signed = Proposal.from_proto(r.proposal)
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp


class RetrySignerClient:
    """Retrying facade (retry_signer_client.go)."""

    def __init__(self, client: SignerClient, retries: int = 5, delay: float = 0.2):
        self.client = client
        self.retries = retries
        self.delay = delay

    def _retry(self, fn, *args, **kwargs):
        last = None
        for _ in range(self.retries):
            try:
                return fn(*args, **kwargs)
            except SignerTransportError as e:
                last = e
                time.sleep(self.delay)
            # signer-side rejections (double-sign protection etc.) are
            # permanent: surface immediately
        raise last

    def get_pub_key(self):
        return self._retry(self.client.get_pub_key)

    @property
    def key(self):
        return self.client.key

    def sign_vote(self, chain_id, vote, sign_extension=False):
        return self._retry(self.client.sign_vote, chain_id, vote, sign_extension)

    def sign_proposal(self, chain_id, proposal):
        return self._retry(self.client.sign_proposal, chain_id, proposal)


class SignerServer:
    """Signer side: dial the node and serve its requests against a local
    FilePV (signer_server.go + signer_dialer_endpoint.go)."""

    def __init__(self, addr: str, chain_id: str, priv_validator, identity_key=None):
        self.addr = addr
        self.chain_id = chain_id
        self.pv = priv_validator
        # identity for the SecretConnection handshake; defaults to the
        # validator key itself (operators can use a dedicated conn key)
        self.identity_key = identity_key
        self.logger = get_logger("signer-server")
        self._stopped = False
        self._thread: threading.Thread | None = None
        self._active = None  # the live conn, closed by stop()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="privval-serve"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped = True
        active = self._active
        if active is not None:
            try:
                active.close()
            except OSError:
                pass

    def _run(self) -> None:
        while not self._stopped:
            try:
                host, _, port = self.addr.rpartition(":")
                sock = socket.create_connection((host or "127.0.0.1", int(port)), 5.0)
            except OSError:
                time.sleep(0.5)
                continue
            self.logger.info(f"connected to node at {self.addr}")
            try:
                if self.identity_key is not None:
                    from ..p2p.conn.secret_connection import (
                        make_secret_connection,
                    )

                    conn = make_secret_connection(sock, self.identity_key)
                else:
                    conn = _PlainConn(sock)
                self._active = conn
                self._serve(conn)
            except Exception as e:  # noqa: BLE001 - never kill the dial loop
                self.logger.error(f"signer connection lost: {e}")
            finally:
                self._active = None
                try:
                    sock.close()
                except OSError:
                    pass

    def _serve(self, conn) -> None:
        conn._sock.settimeout(None)
        while not self._stopped:
            req = _recv_msg(conn)
            if req is None:
                return
            _send_msg(conn, self._handle(req))

    def _handle(self, req: pb.PrivvalMessage) -> pb.PrivvalMessage:
        """signer_requestHandler.go DefaultValidationRequestHandler."""
        which = req.which()
        if which == "ping_request":
            return pb.PrivvalMessage(ping_response=pb.PingResponse())
        if which == "pub_key_request":
            if req.pub_key_request.chain_id != self.chain_id:
                return pb.PrivvalMessage(
                    pub_key_response=pb.PubKeyResponse(
                        error=pb.RemoteSignerError(
                            code=1, description="chain id mismatch"
                        )
                    )
                )
            pub = self.pv.key.priv_key.pub_key()
            return pb.PrivvalMessage(
                pub_key_response=pb.PubKeyResponse(
                    pub_key_bytes=pub.bytes(), pub_key_type=pub.type
                )
            )
        if which == "sign_vote_request":
            r = req.sign_vote_request
            try:
                vote = Vote.from_proto(r.vote)
                self.pv.sign_vote(
                    r.chain_id, vote, sign_extension=not r.skip_extension_signing
                )
                return pb.PrivvalMessage(
                    signed_vote_response=pb.SignedVoteResponse(vote=vote.to_proto())
                )
            except Exception as e:  # noqa: BLE001
                return pb.PrivvalMessage(
                    signed_vote_response=pb.SignedVoteResponse(
                        error=pb.RemoteSignerError(code=2, description=str(e))
                    )
                )
        if which == "sign_proposal_request":
            r = req.sign_proposal_request
            try:
                proposal = Proposal.from_proto(r.proposal)
                self.pv.sign_proposal(r.chain_id, proposal)
                return pb.PrivvalMessage(
                    signed_proposal_response=pb.SignedProposalResponse(
                        proposal=proposal.to_proto()
                    )
                )
            except Exception as e:  # noqa: BLE001
                return pb.PrivvalMessage(
                    signed_proposal_response=pb.SignedProposalResponse(
                        error=pb.RemoteSignerError(code=3, description=str(e))
                    )
                )
        return pb.PrivvalMessage(
            pub_key_response=pb.PubKeyResponse(
                error=pb.RemoteSignerError(
                    code=4, description=f"unsupported request {which}"
                )
            )
        )
