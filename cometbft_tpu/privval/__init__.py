"""Validator signing with double-sign protection (reference: privval/)."""

from .file_pv import (
    FilePV,
    FilePVKey,
    FilePVLastSignState,
    DoubleSignError,
    STEP_PROPOSE,
    STEP_PREVOTE,
    STEP_PRECOMMIT,
)

__all__ = [
    "FilePV",
    "FilePVKey",
    "FilePVLastSignState",
    "DoubleSignError",
    "STEP_PROPOSE",
    "STEP_PREVOTE",
    "STEP_PRECOMMIT",
]
