"""Validator signing with double-sign protection (reference: privval/)."""

from .signer import (
    RemoteSignerError,
    RetrySignerClient,
    SignerClient,
    SignerListenerEndpoint,
    SignerServer,
)
from .file_pv import (
    FilePV,
    FilePVKey,
    FilePVLastSignState,
    DoubleSignError,
    STEP_PROPOSE,
    STEP_PREVOTE,
    STEP_PRECOMMIT,
)

__all__ = [
    "SignerListenerEndpoint",
    "SignerClient",
    "RetrySignerClient",
    "SignerServer",
    "RemoteSignerError",
    "FilePV",
    "FilePVKey",
    "FilePVLastSignState",
    "DoubleSignError",
    "STEP_PROPOSE",
    "STEP_PREVOTE",
    "STEP_PRECOMMIT",
]
