"""FilePV: file-backed validator key with last-sign-state (HRS)
double-sign protection (reference: privval/file.go).

The last-sign state persists (height, round, step, sign-bytes, signature)
after every signature; CheckHRS (file.go:100) refuses any HRS regression,
and a crash-between-sign-and-WAL at the same HRS regenerates the identical
signature (or reuses it when the new request differs only by timestamp,
file.go:374-386).
"""

from __future__ import annotations

import base64
import json
import os
import tempfile

from ..crypto import ed25519
from ..wire.canonical import (
    CanonicalProposal,
    CanonicalVote,
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    Timestamp,
)
from ..wire.proto import decode_delimited

STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_TYPE_TO_STEP = {PREVOTE_TYPE: STEP_PREVOTE, PRECOMMIT_TYPE: STEP_PRECOMMIT}


class DoubleSignError(Exception):
    pass


def _atomic_write(path: str, data: str) -> None:
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".pv-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _generate_priv_key(key_type: str, seed: bytes | None = None):
    """Validator key of any supported type (privval/file.go
    GenFilePVWithKeyType; key types per crypto/encoding).  Consensus
    signs/verifies through the PrivKey/PubKey interface, so everything
    downstream is type-agnostic — but only ed25519 rides the TPU batch
    path (crypto/batch.supports_batch_verifier); the rest verify through
    the sequential fallback in types/validation.py."""
    if key_type == "ed25519":
        return ed25519.PrivKey.from_seed(seed) if seed else ed25519.PrivKey.generate()
    if key_type == "secp256k1":
        from ..crypto import secp256k1

        return (
            secp256k1.PrivKey.from_seed(seed) if seed else secp256k1.PrivKey.generate()
        )
    if key_type == "secp256k1eth":
        from ..crypto import secp256k1eth

        return (
            secp256k1eth.PrivKey.from_seed(seed)
            if seed
            else secp256k1eth.PrivKey.generate()
        )
    if key_type == "bls12_381":
        from ..crypto import bls12381

        return (
            bls12381.PrivKey.from_secret(seed)
            if seed
            else bls12381.PrivKey.generate()
        )
    raise ValueError(f"unsupported validator key type {key_type!r}")


class FilePVKey:
    """privval_key.json: address + pubkey + privkey (file.go FilePVKey)."""

    def __init__(self, priv_key: ed25519.PrivKey, file_path: str = ""):
        self.priv_key = priv_key
        self.pub_key = priv_key.pub_key()
        self.address = self.pub_key.address()
        self.file_path = file_path

    def save(self) -> None:
        if not self.file_path:
            return
        from ..utils import amino_json

        _atomic_write(
            self.file_path,
            amino_json.marshal(
                {
                    "address": self.address.hex().upper(),
                    "pub_key": self.pub_key,
                    "priv_key": self.priv_key,
                },
                indent=2,
            ),
        )

    @classmethod
    def load(cls, file_path: str) -> "FilePVKey":
        from ..utils import amino_json

        with open(file_path) as f:
            d = amino_json.unmarshal(f.read())
        return cls(d["priv_key"], file_path)


class FilePVLastSignState:
    """privval_state.json (file.go:75)."""

    def __init__(self, file_path: str = ""):
        self.height = 0
        self.round = 0
        self.step = 0
        self.signature = b""
        self.sign_bytes = b""
        self.file_path = file_path

    def check_hrs(self, height: int, round: int, step: int) -> bool:
        """True -> same HRS seen before and sign-bytes exist (caller may
        reuse/regenerate); raises on regression (file.go:100)."""
        if self.height > height:
            raise DoubleSignError(
                f"height regression: got {height}, last {self.height}"
            )
        if self.height != height:
            return False
        if self.round > round:
            raise DoubleSignError(
                f"round regression at height {height}: got {round}, last {self.round}"
            )
        if self.round != round:
            return False
        if self.step > step:
            raise DoubleSignError(
                f"step regression at {height}/{round}: got {step}, last {self.step}"
            )
        if self.step < step:
            return False
        if not self.sign_bytes:
            raise DoubleSignError("no sign-bytes despite matching HRS")
        if not self.signature:
            raise DoubleSignError("signature missing despite sign-bytes present")
        return True

    def save(self) -> None:
        if not self.file_path:
            return
        _atomic_write(
            self.file_path,
            json.dumps(
                {
                    "height": str(self.height),
                    "round": self.round,
                    "step": self.step,
                    "signature": base64.b64encode(self.signature).decode(),
                    "signbytes": self.sign_bytes.hex().upper(),
                },
                indent=2,
            ),
        )

    @classmethod
    def load(cls, file_path: str) -> "FilePVLastSignState":
        st = cls(file_path)
        if os.path.exists(file_path) and os.path.getsize(file_path) > 0:
            with open(file_path) as f:
                d = json.load(f)
            st.height = int(d.get("height", 0))
            st.round = d.get("round", 0)
            st.step = d.get("step", 0)
            st.signature = base64.b64decode(d.get("signature", ""))
            st.sign_bytes = bytes.fromhex(d.get("signbytes", ""))
        return st


def _only_differ_by_timestamp(cls, last_sign_bytes: bytes, new_sign_bytes: bytes):
    """(last timestamp, True) if the two canonical messages are identical
    up to timestamp (file.go:459 checkVotesOnlyDifferByTimestamp)."""
    last, _ = decode_delimited(cls, last_sign_bytes)
    new, _ = decode_delimited(cls, new_sign_bytes)
    last_ts = last.timestamp
    probe = Timestamp(seconds=1, nanos=1)
    last.timestamp = probe
    new.timestamp = probe
    return last_ts, last.encode() == new.encode()


class FilePV:
    """A priv validator backed by key + state files (file.go FilePV)."""

    def __init__(self, key: FilePVKey, last_sign_state: FilePVLastSignState):
        self.key = key
        self.last_sign_state = last_sign_state

    # ---------------------------------------------------- construction

    @classmethod
    def generate(
        cls,
        key_file: str = "",
        state_file: str = "",
        seed: bytes | None = None,
        key_type: str = "ed25519",
    ) -> "FilePV":
        priv = _generate_priv_key(key_type, seed)
        pv = cls(FilePVKey(priv, key_file), FilePVLastSignState(state_file))
        return pv

    @classmethod
    def load(cls, key_file: str, state_file: str) -> "FilePV":
        return cls(FilePVKey.load(key_file), FilePVLastSignState.load(state_file))

    @classmethod
    def load_or_generate(
        cls, key_file: str, state_file: str, key_type: str = "ed25519"
    ) -> "FilePV":
        if os.path.exists(key_file):
            return cls.load(key_file, state_file)
        pv = cls.generate(key_file, state_file, key_type=key_type)
        pv.save()
        return pv

    def save(self) -> None:
        self.key.save()
        self.last_sign_state.save()

    def reset(self) -> None:
        """Danger: wipes double-sign protection (file.go:310)."""
        self.last_sign_state = FilePVLastSignState(self.last_sign_state.file_path)
        self.last_sign_state.save()

    # --------------------------------------------------------- queries

    def get_address(self) -> bytes:
        return self.key.address

    def get_pub_key(self) -> ed25519.PubKey:
        return self.key.pub_key

    # --------------------------------------------------------- signing

    def sign_vote(self, chain_id: str, vote, sign_extension: bool = False) -> None:
        """Sets vote.signature (and extension signature for non-nil
        precommits when sign_extension) — file.go:332 signVote."""
        step = _VOTE_TYPE_TO_STEP.get(vote.type)
        if step is None:
            raise ValueError(f"unknown vote type {vote.type}")
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(vote.height, vote.round, step)
        sign_bytes = vote.sign_bytes(chain_id)

        if sign_extension:
            if vote.type == PRECOMMIT_TYPE and not vote.block_id.is_nil():
                # extensions are non-deterministic: always re-sign
                vote.extension_signature = self.key.priv_key.sign(
                    vote.extension_sign_bytes(chain_id)
                )
            elif vote.extension:
                raise ValueError(
                    "vote extensions are only allowed in non-nil precommits"
                )

        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                vote.signature = lss.signature
                return
            last_ts, ok = _only_differ_by_timestamp(
                CanonicalVote, lss.sign_bytes, sign_bytes
            )
            if ok:
                vote.timestamp = last_ts
                vote.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.key.priv_key.sign(sign_bytes)
        self._save_signed(vote.height, vote.round, step, sign_bytes, sig)
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal) -> None:
        """file.go:402 signProposal."""
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(proposal.height, proposal.round, STEP_PROPOSE)
        sign_bytes = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                proposal.signature = lss.signature
                return
            last_ts, ok = _only_differ_by_timestamp(
                CanonicalProposal, lss.sign_bytes, sign_bytes
            )
            if ok:
                proposal.timestamp = last_ts
                proposal.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")
        sig = self.key.priv_key.sign(sign_bytes)
        self._save_signed(proposal.height, proposal.round, STEP_PROPOSE, sign_bytes, sig)
        proposal.signature = sig

    def sign_bytes(self, data: bytes) -> bytes:
        """Raw signing for p2p handshake proofs (file.go:298)."""
        return self.key.priv_key.sign(data)

    def _save_signed(
        self, height: int, round: int, step: int, sign_bytes: bytes, sig: bytes
    ) -> None:
        lss = self.last_sign_state
        lss.height, lss.round, lss.step = height, round, step
        lss.signature, lss.sign_bytes = sig, sign_bytes
        lss.save()
