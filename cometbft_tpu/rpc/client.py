"""Minimal JSON-RPC HTTP + WebSocket client
(reference: rpc/client/http) — used by tests, the CLI, and anything
driving a node over the wire.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import struct
from urllib.request import Request, urlopen

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class RPCClientError(Exception):
    pass


class HTTPClient:
    def __init__(self, addr: str, timeout: float = 10.0):
        if addr.startswith("tcp://"):
            addr = addr[len("tcp://"):]
        if not addr.startswith("http"):
            addr = "http://" + addr
        self.base = addr.rstrip("/")
        self.timeout = timeout
        self._rid = 0

    def call(self, method: str, **params):
        self._rid += 1
        payload = {
            "jsonrpc": "2.0",
            "id": self._rid,
            "method": method,
            "params": {
                k: (base64.b64encode(v).decode() if isinstance(v, bytes) else v)
                for k, v in params.items()
                if v is not None
            },
        }
        req = Request(
            self.base,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urlopen(req, timeout=self.timeout) as resp:
            out = json.loads(resp.read())
        if "error" in out:
            raise RPCClientError(out["error"])
        return out["result"]

    # conveniences mirroring rpc/client/http
    def status(self):
        return self.call("status")

    def health(self):
        return self.call("health")

    def block(self, height: int | None = None):
        return self.call("block", height=height)

    def commit(self, height: int | None = None):
        return self.call("commit", height=height)

    def validators(self, height: int | None = None, page=1, per_page=30):
        return self.call("validators", height=height, page=page, per_page=per_page)

    def abci_info(self):
        return self.call("abci_info")

    def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = False):
        return self.call(
            "abci_query", path=path, data=data.hex(), height=height, prove=prove
        )

    def broadcast_tx_sync(self, tx: bytes):
        return self.call("broadcast_tx_sync", tx=tx)

    def broadcast_tx_commit(self, tx: bytes):
        return self.call("broadcast_tx_commit", tx=tx)

    def net_info(self):
        return self.call("net_info")


class WSClient:
    """Text-frame WebSocket client for /websocket subscribe."""

    def __init__(self, addr: str, timeout: float = 30.0):
        if addr.startswith("tcp://"):
            addr = addr[len("tcp://"):]
        host, _, port = addr.rpartition(":")
        self.sock = socket.create_connection((host or "127.0.0.1", int(port)), timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            (
                f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        resp = b""
        while b"\r\n\r\n" not in resp:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise RPCClientError("ws handshake failed: connection closed")
            resp += chunk
        status = resp.split(b"\r\n", 1)[0]
        if b"101" not in status:
            raise RPCClientError(f"ws handshake failed: {status!r}")
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()
        ).decode()
        if accept.encode() not in resp:
            raise RPCClientError("ws handshake failed: bad accept key")
        self._rid = 0

    def send(self, method: str, **params) -> None:
        self._rid += 1
        data = json.dumps(
            {"jsonrpc": "2.0", "id": self._rid, "method": method, "params": params}
        ).encode()
        mask = os.urandom(4)
        frame = bytearray([0x81])
        n = len(data)
        if n < 126:
            frame.append(0x80 | n)
        elif n < 1 << 16:
            frame.append(0x80 | 126)
            frame += struct.pack(">H", n)
        else:
            frame.append(0x80 | 127)
            frame += struct.pack(">Q", n)
        frame += mask
        frame += bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        self.sock.sendall(bytes(frame))

    def subscribe(self, query: str) -> None:
        self.send("subscribe", query=query)

    def recv(self) -> dict:
        def read_exact(n: int) -> bytes:
            buf = b""
            while len(buf) < n:
                chunk = self.sock.recv(n - len(buf))
                if not chunk:
                    raise RPCClientError("ws closed")
                buf += chunk
            return buf

        while True:
            hdr = read_exact(2)
            opcode = hdr[0] & 0x0F
            n = hdr[1] & 0x7F
            if n == 126:
                n = struct.unpack(">H", read_exact(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", read_exact(8))[0]
            payload = read_exact(n) if n else b""
            if opcode == 0x8:
                raise RPCClientError("ws closed by server")
            if opcode == 0x1:
                return json.loads(payload)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
