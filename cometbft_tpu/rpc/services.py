"""Data-companion services: block, block-results, version, and the
privileged pruning service (reference: rpc/grpc/server/services/
{blockservice,blockresultservice,versionservice,pruningservice}).

The reference serves these over gRPC; this module is the lightweight
socket transport — the same varint-delimited proto framing the ABCI and
privval sidecars use (abci/client/socket_client.go pattern), with a
method-routed envelope (wire/services_pb.ServiceRequest) and
server-streaming support for GetLatestHeight
(blockservice/service.go:79 streams a height per committed block).
The REAL gRPC transport over the reference's exact service paths lives
in rpc/grpc_services.py and reuses this module's handlers; a
companion_laddr of grpc://host:port selects it (node.py).
"""

from __future__ import annotations

import socket
import struct
import threading

from ..utils.log import get_logger
from ..utils.service import Service
from ..wire import services_pb as pb
from ..wire.proto import decode_varint, encode_varint

_MAX_MSG = 64 * 1024 * 1024


def _read_frame(rfile) -> bytes | None:
    """Read one varint-length-delimited frame from a buffered stream."""
    raw = b""
    while True:
        b1 = rfile.read(1)
        if not b1:
            return None
        raw += b1
        if not b1[0] & 0x80:
            break
        if len(raw) > 10:
            raise ValueError("varint too long")
    n, _ = decode_varint(raw)
    if n > _MAX_MSG:
        raise ValueError("service frame too large")
    data = rfile.read(n)
    if len(data) < n:
        return None
    return data


def _write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(encode_varint(len(payload)) + payload)


class CompanionServiceServer(Service):
    """Hosts the four companion services against live node components.

    block_store / state_store are required; pruner, tx_indexer,
    block_indexer, event_bus are optional (methods needing an absent
    component return an error, matching the reference's per-service
    enablement in config)."""

    def __init__(
        self,
        addr: str,
        block_store,
        state_store,
        pruner=None,
        tx_indexer=None,
        block_indexer=None,
        event_bus=None,
        node_version: str = "",
        abci_version: str = "2.1.0",
        p2p_version: int = 9,
        block_version: int = 11,
        privileged: bool = False,
    ):
        """privileged=False serves the public block/block-results/version
        services and REJECTS pruning.* methods; privileged=True serves
        ONLY pruning.*.  Mirrors the reference's grpc_laddr /
        grpc_privileged_laddr split (node/node.go grpc server setup) so
        operators can firewall the retain-height API separately from the
        read-only data services."""
        super().__init__("CompanionServices")
        self.privileged = privileged
        host, port = addr.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self.block_store = block_store
        self.state_store = state_store
        self.pruner = pruner
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self.versions = (node_version, abci_version, p2p_version, block_version)
        self.logger = get_logger("services")
        self._listener: socket.socket | None = None
        self._conns: set[socket.socket] = set()
        self._mtx = threading.Lock()

    @property
    def laddr(self) -> str:
        return f"{self._host}:{self._port}"

    def on_start(self) -> None:
        self._listener = socket.create_server((self._host, self._port))
        self._port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True, name="svc-accept").start()

    def on_stop(self) -> None:
        from ..utils.netutil import close_socket

        close_socket(self._listener)
        with self._mtx:
            for c in list(self._conns):
                close_socket(c)

    def _accept(self) -> None:
        while self.is_running():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._mtx:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="svc-conn",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        send_mtx = threading.Lock()  # streams + replies interleave
        try:
            while self.is_running():
                frame = _read_frame(rfile)
                if frame is None:
                    return
                req = pb.ServiceRequest.decode(frame)
                if req.method == "block.GetLatestHeight" and not self.privileged:
                    threading.Thread(
                        target=self._stream_latest_height,
                        args=(conn, send_mtx, req.id),
                        daemon=True,
                        name="svc-height-stream",
                    ).start()
                    continue
                resp = self._dispatch(req)
                with send_mtx:
                    _write_frame(conn, resp.encode())
        except (OSError, ValueError) as e:
            self.logger.debug(f"service conn closed: {e}")
        finally:
            with self._mtx:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------------ dispatch

    def _dispatch(self, req: pb.ServiceRequest) -> pb.ServiceResponse:
        try:
            is_pruning = req.method.startswith("pruning.")
            if is_pruning != self.privileged:
                return pb.ServiceResponse(
                    id=req.id,
                    error=(
                        f"method {req.method!r} not served on this listener "
                        f"({'privileged' if self.privileged else 'public'})"
                    ),
                )
            handler = _HANDLERS.get(req.method)
            if handler is None:
                return pb.ServiceResponse(
                    id=req.id, error=f"unknown method {req.method!r}"
                )
            out = handler(self, req.payload)
            return pb.ServiceResponse(id=req.id, payload=out.encode())
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            return pb.ServiceResponse(id=req.id, error=str(e))

    # ---- block service (blockservice/service.go)

    def _get_by_height(self, payload: bytes) -> pb.GetByHeightResponse:
        height = pb.GetByHeightRequest.decode(payload).height
        if height == 0:
            height = self.block_store.height
        base = self.block_store.base
        if height < base or height > self.block_store.height:
            raise ValueError(
                f"height {height} not in store range [{base},{self.block_store.height}]"
            )
        meta = self.block_store.load_block_meta(height)
        block = self.block_store.load_block(height)
        if meta is None or block is None:
            raise ValueError(f"block {height} not found")
        return pb.GetByHeightResponse(
            block_id=meta.block_id, block=block.to_proto()
        )

    def latest_heights(self, live=None):
        """Generator: the current height now, then one height per
        NewBlock event (blockservice/service.go:79 GetLatestHeight) —
        the ONE subscription lifecycle shared by both transports (the
        socket framing below and rpc/grpc_services.py's stream handler).
        live: optional () -> bool liveness predicate REPLACING this
        service's own is_running() (the gRPC wrapper hosts an unstarted
        instance and supplies its own).
        Subscribes BEFORE yielding the initial height: a block that
        commits between the two would otherwise be missed forever."""
        import queue as _q
        import uuid

        sub = None
        subscriber = f"svc-latest-{uuid.uuid4().hex[:12]}"
        try:
            if self.event_bus is not None:
                from ..types.event_bus import EventQueryNewBlock

                sub = self.event_bus.subscribe(subscriber, EventQueryNewBlock)
            yield self.block_store.height
            if sub is None:
                return
            while live() if live is not None else self.is_running():
                try:
                    msg, _events = sub.get(timeout=1.0)
                except _q.Empty:
                    continue
                yield msg.data["block"].header.height
        finally:
            if sub is not None:
                try:
                    self.event_bus.unsubscribe(subscriber, EventQueryNewBlock)
                except Exception as e:  # noqa: BLE001 — teardown after stream end
                    self.logger.debug(f"unsubscribe {subscriber} failed: {e!r}")

    def _stream_latest_height(self, conn, send_mtx, req_id: int) -> None:
        """Socket framing over latest_heights(); the subscription is torn
        down when the conn dies — the write failure surfaces as OSError
        on the next block."""
        try:
            for height in self.latest_heights():
                with send_mtx:
                    _write_frame(
                        conn,
                        pb.ServiceResponse(
                            id=req_id,
                            payload=pb.GetLatestHeightResponse(
                                height=height
                            ).encode(),
                        ).encode(),
                    )
        except (OSError, ValueError):
            return

    # ---- block-results service (blockresultservice/service.go)

    def _get_block_results(self, payload: bytes) -> pb.GetBlockResultsResponse:
        height = pb.GetBlockResultsRequest.decode(payload).height
        if height == 0:
            height = self.block_store.height
        resp = self.state_store.load_finalize_block_response(height)
        if resp is None:
            raise ValueError(f"no block results for height {height}")
        return pb.GetBlockResultsResponse(
            height=height,
            tx_results=list(resp.tx_results or []),
            finalize_block_events=list(resp.events or []),
            validator_updates=list(resp.validator_updates or []),
            app_hash=resp.app_hash,
        )

    # ---- version service (versionservice/service.go)

    def _get_version(self, payload: bytes) -> pb.GetVersionResponse:
        node, abci, p2p, block = self.versions
        return pb.GetVersionResponse(node=node, abci=abci, p2p=p2p, block=block)

    # ---- pruning service (pruningservice/service.go) — privileged

    def _need_pruner(self):
        if self.pruner is None:
            raise ValueError("pruning service not enabled")
        return self.pruner

    def _set_block_retain(self, payload: bytes) -> pb.Empty:
        h = pb.SetBlockRetainHeightRequest.decode(payload).height
        self._need_pruner().set_companion_block_retain_height(h)
        return pb.Empty()

    def _get_block_retain(self, payload: bytes) -> pb.GetBlockRetainHeightResponse:
        p = self._need_pruner()
        return pb.GetBlockRetainHeightResponse(
            app_retain_height=p.app_block_retain_height(),
            pruning_service_retain_height=p.companion_block_retain_height(),
        )

    def _set_block_results_retain(self, payload: bytes) -> pb.Empty:
        h = pb.SetBlockResultsRetainHeightRequest.decode(payload).height
        self._need_pruner().set_block_results_retain_height(h)
        return pb.Empty()

    def _get_block_results_retain(
        self, payload: bytes
    ) -> pb.GetBlockResultsRetainHeightResponse:
        return pb.GetBlockResultsRetainHeightResponse(
            pruning_service_retain_height=(
                self._need_pruner().block_results_retain_height()
            )
        )

    def _set_tx_indexer_retain(self, payload: bytes) -> pb.Empty:
        h = pb.SetTxIndexerRetainHeightRequest.decode(payload).height
        self._need_pruner().set_tx_indexer_retain_height(h)
        return pb.Empty()

    def _get_tx_indexer_retain(
        self, payload: bytes
    ) -> pb.GetTxIndexerRetainHeightResponse:
        return pb.GetTxIndexerRetainHeightResponse(
            height=self._need_pruner().tx_indexer_retain_height()
        )

    def _set_block_indexer_retain(self, payload: bytes) -> pb.Empty:
        h = pb.SetBlockIndexerRetainHeightRequest.decode(payload).height
        self._need_pruner().set_block_indexer_retain_height(h)
        return pb.Empty()

    def _get_block_indexer_retain(
        self, payload: bytes
    ) -> pb.GetBlockIndexerRetainHeightResponse:
        return pb.GetBlockIndexerRetainHeightResponse(
            height=self._need_pruner().block_indexer_retain_height()
        )


_HANDLERS = {
    "block.GetByHeight": CompanionServiceServer._get_by_height,
    "block_results.GetBlockResults": CompanionServiceServer._get_block_results,
    "version.GetVersion": CompanionServiceServer._get_version,
    "pruning.SetBlockRetainHeight": CompanionServiceServer._set_block_retain,
    "pruning.GetBlockRetainHeight": CompanionServiceServer._get_block_retain,
    "pruning.SetBlockResultsRetainHeight": CompanionServiceServer._set_block_results_retain,
    "pruning.GetBlockResultsRetainHeight": CompanionServiceServer._get_block_results_retain,
    "pruning.SetTxIndexerRetainHeight": CompanionServiceServer._set_tx_indexer_retain,
    "pruning.GetTxIndexerRetainHeight": CompanionServiceServer._get_tx_indexer_retain,
    "pruning.SetBlockIndexerRetainHeight": CompanionServiceServer._set_block_indexer_retain,
    "pruning.GetBlockIndexerRetainHeight": CompanionServiceServer._get_block_indexer_retain,
}


class CompanionServiceClient:
    """Typed client for the companion services (the data-companion side).

    Thread-compatible for request/response; GetLatestHeight streaming
    owns the connection while active."""

    def __init__(self, addr: str, timeout: float = 10.0):
        host, port = addr.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._rfile = self._sock.makefile("rb")
        self._next_id = 1
        self._mtx = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _call(self, method: str, req_msg) -> bytes:
        with self._mtx:
            rid = self._next_id
            self._next_id += 1
            _write_frame(
                self._sock,
                pb.ServiceRequest(
                    id=rid, method=method, payload=req_msg.encode()
                ).encode(),
            )
            frame = _read_frame(self._rfile)
            if frame is None:
                raise ConnectionError("service connection closed")
            resp = pb.ServiceResponse.decode(frame)
        if resp.id != rid:
            # a stray stream frame on a shared connection — decoding it as
            # this call's response type would return garbage silently
            raise RuntimeError(
                f"response id {resp.id} != request id {rid}; do not mix "
                "unary calls with an active latest_height_stream on one client"
            )
        if resp.error:
            raise RuntimeError(resp.error)
        return resp.payload

    # block
    def get_by_height(self, height: int = 0) -> pb.GetByHeightResponse:
        return pb.GetByHeightResponse.decode(
            self._call("block.GetByHeight", pb.GetByHeightRequest(height=height))
        )

    def latest_height_stream(self):
        """Generator of heights; consumes the connection."""
        with self._mtx:
            rid = self._next_id
            self._next_id += 1
            _write_frame(
                self._sock,
                pb.ServiceRequest(
                    id=rid,
                    method="block.GetLatestHeight",
                    payload=pb.GetLatestHeightRequest().encode(),
                ).encode(),
            )
        while True:
            frame = _read_frame(self._rfile)
            if frame is None:
                return
            resp = pb.ServiceResponse.decode(frame)
            if resp.error:
                raise RuntimeError(resp.error)
            yield pb.GetLatestHeightResponse.decode(resp.payload).height

    # block results
    def get_block_results(self, height: int = 0) -> pb.GetBlockResultsResponse:
        return pb.GetBlockResultsResponse.decode(
            self._call(
                "block_results.GetBlockResults",
                pb.GetBlockResultsRequest(height=height),
            )
        )

    # version
    def get_version(self) -> pb.GetVersionResponse:
        return pb.GetVersionResponse.decode(
            self._call("version.GetVersion", pb.GetVersionRequest())
        )

    # pruning
    def set_block_retain_height(self, height: int) -> None:
        self._call(
            "pruning.SetBlockRetainHeight",
            pb.SetBlockRetainHeightRequest(height=height),
        )

    def get_block_retain_height(self) -> pb.GetBlockRetainHeightResponse:
        return pb.GetBlockRetainHeightResponse.decode(
            self._call("pruning.GetBlockRetainHeight", pb.Empty())
        )

    def set_block_results_retain_height(self, height: int) -> None:
        self._call(
            "pruning.SetBlockResultsRetainHeight",
            pb.SetBlockResultsRetainHeightRequest(height=height),
        )

    def get_block_results_retain_height(self) -> int:
        return pb.GetBlockResultsRetainHeightResponse.decode(
            self._call("pruning.GetBlockResultsRetainHeight", pb.Empty())
        ).pruning_service_retain_height

    def set_tx_indexer_retain_height(self, height: int) -> None:
        self._call(
            "pruning.SetTxIndexerRetainHeight",
            pb.SetTxIndexerRetainHeightRequest(height=height),
        )

    def get_tx_indexer_retain_height(self) -> int:
        return pb.GetTxIndexerRetainHeightResponse.decode(
            self._call("pruning.GetTxIndexerRetainHeight", pb.Empty())
        ).height

    def set_block_indexer_retain_height(self, height: int) -> None:
        self._call(
            "pruning.SetBlockIndexerRetainHeight",
            pb.SetBlockIndexerRetainHeightRequest(height=height),
        )

    def get_block_indexer_retain_height(self) -> int:
        return pb.GetBlockIndexerRetainHeightResponse.decode(
            self._call("pruning.GetBlockIndexerRetainHeight", pb.Empty())
        ).height
