"""JSON-RPC layer (reference: rpc/): server, routes, clients."""

from .client import HTTPClient, RPCClientError, WSClient
from .core import Environment, ROUTES, RPCError
from .server import RPCServer

__all__ = [
    "RPCServer",
    "Environment",
    "ROUTES",
    "RPCError",
    "HTTPClient",
    "WSClient",
    "RPCClientError",
]
