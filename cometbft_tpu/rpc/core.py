"""RPC handlers against a node Environment
(reference: rpc/core/{env,status,blocks,mempool,consensus,abci,net}.go).

Each handler takes already-decoded params and returns a JSON-serializable
dict; the server layer (rpc/server.py) does JSON-RPC framing, parameter
coercion, and the websocket event bridge.
"""

from __future__ import annotations

import time

from ..mempool.mempool import MempoolError
from ..utils.log import get_logger
from ..types.event_bus import EventQueryTx
from ..wire import abci_pb as abci
from ..indexer import tx_hash
from .serializers import (
    b64,
    block_id_json,
    block_json,
    commit_json,
    events_json,
    header_json,
    hex_up,
    tx_result_json,
    validator_json,
)

_log = get_logger("rpc.core")


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.data = data


class Environment:
    """Pointers into the node (rpc/core/env.go)."""

    def __init__(self, node):
        self.node = node

    # shortcuts
    @property
    def state(self):
        return self.node.consensus_state.state

    @property
    def block_store(self):
        return self.node.block_store

    # ------------------------------------------------------------- info

    def health(self) -> dict:
        """Wire-compatible liveness probe (rpc/core/health.go): an empty
        object, by contract — it answers iff the RPC thread is alive.
        Readiness (is the accelerator sane, are the loops beating) is
        /tpu_health's job; keeping them separate lets a load balancer
        drain a wedged node without a restart loop killing it."""
        return {}

    def tpu_health(self) -> dict:
        """Deep node-health view (ours, no reference analogue): the
        health sentinel's snapshot — tri-state `state` (ok | degraded |
        wedged), the last hang-proof accelerator probe, per-loop
        heartbeat ages against their deadlines, and the path of the last
        stall-forensics artifact (utils/healthmon).  `ready` is the
        load-balancer verdict: route away when false.  With the sentinel
        off (`COMETBFT_TPU_HEALTH` unset) the route still answers with
        `{"enabled": false}` so callers can use it as a liveness poll."""
        from ..utils import healthmon

        return healthmon.snapshot()

    def status(self) -> dict:
        """rpc/core/status.go."""
        n = self.node
        latest_height = self.block_store.height
        meta = self.block_store.load_block_meta(latest_height)
        base_meta = self.block_store.load_base_meta()
        pv_addr = b""
        pv_power = 0
        if n.priv_validator is not None:
            pv_addr = n.priv_validator.key.priv_key.pub_key().address()
            idx, val = self.state.validators.get_by_address(pv_addr)
            pv_power = val.voting_power if val else 0
        return {
            "node_info": {
                "id": n.node_key.id(),
                "listen_addr": n.listen_addr or n.config.p2p.laddr,
                "network": n.genesis.chain_id,
                "version": n.node_info.version,
                "moniker": n.config.base.moniker,
            },
            "sync_info": {
                "latest_block_hash": hex_up(
                    meta.block_id.hash if meta and meta.block_id else b""
                ),
                "latest_app_hash": hex_up(self.state.app_hash),
                "latest_block_height": str(latest_height),
                "latest_block_time": (
                    header_json(_hdr(meta))["time"] if meta else "0001-01-01T00:00:00Z"
                ),
                "earliest_block_height": str(
                    base_meta.header.height if base_meta else self.block_store.base
                ),
                "catching_up": bool(
                    n.consensus_reactor.wait_sync
                    or (n.blocksync_reactor.pool.is_running())
                ),
            },
            "validator_info": {
                "address": hex_up(pv_addr),
                "voting_power": str(pv_power),
            },
        }

    def net_info(self) -> dict:
        peers = self.node.switch.peers.list()
        return {
            "listening": self.node.switch.is_running(),
            "listeners": [self.node.listen_addr or ""],
            "n_peers": str(len(peers)),
            "peers": [
                {
                    "node_info": {
                        "id": p.node_info.node_id,
                        "moniker": p.node_info.moniker,
                    },
                    "is_outbound": p.outbound,
                    "remote_ip": "",
                }
                for p in peers
            ],
        }

    _GENESIS_CHUNK_SIZE = 2 * 1024 * 1024  # rpc/core/env.go:37

    def _genesis_chunks(self) -> list[bytes]:
        """Genesis JSON split into 2 MB chunks, computed once
        (rpc/core/env.go genesis-chunks rules)."""
        cached = getattr(self, "_gen_chunks", None)
        if cached is None:
            raw = self.node.genesis.to_json().encode()
            n = self._GENESIS_CHUNK_SIZE
            cached = [raw[i : i + n] for i in range(0, len(raw), n)] or [b""]
            self._gen_chunks = cached
        return cached

    def genesis(self) -> dict:
        import json as _json

        if len(self._genesis_chunks()) > 1:
            # rpc/core/net.go:113 ErrGenesisRespSize: oversized genesis
            # must be fetched via /genesis_chunked
            raise RPCError(
                -32603,
                "genesis response is large, please use the genesis_chunked API instead",
            )
        return {"genesis": _json.loads(self.node.genesis.to_json())}

    def genesis_chunked(self, chunk=0) -> dict:
        """(rpc/core/net.go:131 GenesisChunked)"""
        chunks = self._genesis_chunks()
        cid = int(chunk or 0)
        if cid < 0 or cid >= len(chunks):
            raise RPCError(
                -32603,
                f"chunk id {cid} out of range: genesis has {len(chunks)} chunks",
            )
        return {
            "chunk": str(cid),
            "total": str(len(chunks)),
            "data": b64(chunks[cid]),
        }

    # ------------------------------------------------- unsafe p2p controls

    def _require_unsafe(self) -> None:
        """Unsafe routes are registered only when rpc.unsafe is on
        (rpc/core/routes.go:51-57 AddUnsafeRoutes); double-check at call
        time so a misrouted dispatch can never dial on a safe node."""
        if not getattr(self.node.config.rpc, "unsafe", False):
            raise RPCError(
                -32601, "unsafe RPC commands are disabled: set rpc.unsafe"
            )

    @staticmethod
    def _addr_list(value) -> list[str]:
        """Address-list param: JSON array (POST), JSON-encoded string or
        comma-separated string (URI query) — never character iteration."""
        if isinstance(value, str):
            import json as _json

            try:
                parsed = _json.loads(value)
                value = parsed if isinstance(parsed, list) else [str(parsed)]
            except ValueError:
                value = [s for s in value.split(",") if s]
        return [str(v) for v in value]

    def dial_seeds(self, seeds=None) -> dict:
        """(rpc/core/net.go:55 UnsafeDialSeeds)"""
        self._require_unsafe()
        seeds = self._addr_list(seeds or [])
        if not seeds:
            raise RPCError(-32602, "no seeds provided")
        self.node.switch.dial_peers_async(seeds)
        return {"log": "Dialing seeds in progress. See /net_info for details"}

    def dial_peers(self, peers=None, persistent=False, **_ignored) -> dict:
        """(rpc/core/net.go:70 UnsafeDialPeers)"""
        self._require_unsafe()
        peers = self._addr_list(peers or [])
        if not peers:
            raise RPCError(-32602, "no peers provided")
        if isinstance(persistent, str):
            persistent = persistent.lower() in ("1", "true", "t")
        self.node.switch.dial_peers_async(peers, persistent=bool(persistent))
        return {"log": "Dialing peers in progress. See /net_info for details"}

    # ----------------------------------------------------------- blocks

    def _height_or_latest(self, height) -> int:
        latest = self.block_store.height
        if height in (None, 0, "0", ""):
            return latest
        h = int(height)
        if h <= 0:
            raise RPCError(-32603, f"height must be positive, got {h}")
        if h > latest:
            raise RPCError(
                -32603, f"height {h} must be less than or equal to {latest}"
            )
        return h

    def block(self, height=None) -> dict:
        h = self._height_or_latest(height)
        blk = self.block_store.load_block(h)
        meta = self.block_store.load_block_meta(h)
        if blk is None or meta is None:
            raise RPCError(-32603, f"no block at height {h}")
        return {
            "block_id": {
                "hash": hex_up(meta.block_id.hash),
                "parts": {
                    "total": meta.block_id.part_set_header.total,
                    "hash": hex_up(meta.block_id.part_set_header.hash),
                },
            },
            "block": block_json(blk),
        }

    def header(self, height=None) -> dict:
        """rpc/core/blocks.go Header."""
        h = self._height_or_latest(height)
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no header at height {h}")
        return {"header": header_json(_hdr(meta))}

    def header_by_hash(self, hash="") -> dict:
        """rpc/core/blocks.go HeaderByHash."""
        blk = self.block_store.load_block_by_hash(_parse_hash(hash))
        if blk is None:
            raise RPCError(-32603, f"no header with hash {hash}")
        return {"header": header_json(blk.header)}

    def block_by_hash(self, hash="") -> dict:
        """rpc/core/blocks.go BlockByHash."""
        blk = self.block_store.load_block_by_hash(_parse_hash(hash))
        if blk is None:
            raise RPCError(-32603, f"no block with hash {hash}")
        return self.block(blk.header.height)

    def blockchain(self, minHeight=0, maxHeight=0) -> dict:  # noqa: N803 — wire names
        """rpc/core/blocks.go BlockchainInfo: metas newest-first, capped
        at 20 (the reference's limit)."""
        latest = self.block_store.height
        base = self.block_store.base
        maxh = min(int(maxHeight) or latest, latest)
        minh = max(int(minHeight) or base, base)
        if minh > maxh:
            raise RPCError(
                -32602,
                f"min height {minh} can't be greater than max height {maxh}",
            )
        minh = max(minh, maxh - 19)
        metas = []
        for h in range(maxh, minh - 1, -1):
            meta = self.block_store.load_block_meta(h)
            if meta is None:
                continue
            metas.append(
                {
                    "block_id": {
                        "hash": hex_up(meta.block_id.hash),
                        "parts": {
                            "total": meta.block_id.part_set_header.total,
                            "hash": hex_up(meta.block_id.part_set_header.hash),
                        },
                    },
                    "block_size": str(getattr(meta, "block_size", 0)),
                    "header": header_json(_hdr(meta)),
                    "num_txs": str(getattr(meta, "num_txs", 0)),
                }
            )
        return {"last_height": str(latest), "block_metas": metas}

    def check_tx(self, tx: bytes) -> dict:
        """rpc/core/mempool.go CheckTx: run CheckTx without adding to the
        mempool."""
        from ..wire import abci_pb as apb

        res = self.node.app_conns.mempool.check_tx(apb.CheckTxRequest(tx=tx))
        return {
            "code": res.code,
            "data": b64(res.data) if res.data else None,
            "log": res.log,
            "gas_wanted": str(res.gas_wanted),
            "gas_used": str(res.gas_used),
        }

    def broadcast_evidence(self, evidence="") -> dict:
        """rpc/core/evidence.go BroadcastEvidence: base64 proto-encoded
        Evidence (the JSON-RPC carries the deterministic proto bytes)."""
        import base64 as _b64

        from ..types.evidence import evidence_from_proto
        from ..wire import types_pb as tpb

        try:
            raw = _b64.b64decode(evidence)
            ev = evidence_from_proto(tpb.EvidenceProto.decode(raw))
        except Exception as e:  # noqa: BLE001
            raise RPCError(-32602, f"invalid evidence: {e}") from e
        pool = getattr(self.node, "evidence_pool", None)
        if pool is None:
            raise RPCError(-32603, "evidence pool not available")
        try:
            pool.add_evidence(ev)
        except Exception as e:  # noqa: BLE001
            raise RPCError(-32603, f"evidence rejected: {e}") from e
        return {"hash": hex_up(ev.hash())}

    def dump_consensus_state(self) -> dict:
        """rpc/core/consensus.go DumpConsensusState: the deep round-state
        dump incl. per-peer state."""
        out = self.consensus_state()
        peers = []
        for p in self.node.switch.peers.list() if self.node.switch else []:
            peers.append(
                {
                    "node_address": p.id,
                    "peer_state": {"connected": True},
                }
            )
        out["peers"] = peers
        rs = self.node.consensus_state.get_round_state()
        votes = []
        if rs.votes:
            for rnd in sorted(rs.votes.round_vote_sets):
                pv = rs.votes.prevotes(rnd)
                pc = rs.votes.precommits(rnd)
                votes.append(
                    {
                        "round": rnd,
                        "prevotes_bit_array": _bits(pv),
                        "precommits_bit_array": _bits(pc),
                    }
                )
        out["round_state"]["height_vote_set"] = votes
        return out

    def commit(self, height=None) -> dict:
        h = self._height_or_latest(height)
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no block at height {h}")
        commit = self.block_store.load_block_commit(h)
        canonical = True
        if commit is None:
            commit = self.block_store.load_seen_commit(h)
            canonical = False
        if commit is None:
            raise RPCError(-32603, f"no commit for height {h}")
        return {
            "signed_header": {
                "header": header_json(_hdr(meta)),
                "commit": commit_json(commit),
            },
            "canonical": canonical,
        }

    def validators(self, height=None, page=1, per_page=30) -> dict:
        h = self._height_or_latest(height)
        vals = self.node.state_store.load_validators(h)
        if vals is None:
            raise RPCError(-32603, f"no validator set at height {h}")
        page = max(1, int(page or 1))
        per_page = min(100, max(1, int(per_page or 30)))
        start = (page - 1) * per_page
        sel = vals.validators[start : start + per_page]
        return {
            "block_height": str(h),
            "validators": [validator_json(v) for v in sel],
            "count": str(len(sel)),
            "total": str(vals.size()),
        }

    # ----------------------------------------------------------- indexer

    def tx(self, hash="", prove=False) -> dict:
        """rpc/core/tx.go Tx: lookup by hash in the tx indexer; with
        prove, attach the Merkle proof of inclusion in the block's
        data_hash (types/tx.go Txs.Proof)."""
        h = bytes.fromhex(hash) if isinstance(hash, str) else hash
        rec = self.node.tx_indexer.get(h)
        if rec is None:
            raise RPCError(-32603, f"tx {h.hex()} not found")
        out = self._tx_record_json(h, rec)
        if _as_bool(prove):
            out["proof"] = self._tx_inclusion_proof(rec)
        return out

    def _tx_inclusion_proof(self, rec: dict, _cache: dict | None = None) -> dict:
        from ..types.tx import tx_proof

        height = int(rec["height"])
        blk = _cache.get(height) if _cache is not None else None
        if blk is None:
            blk = self.block_store.load_block(height)
            if blk is None:
                raise RPCError(-32603, f"block {rec['height']} not found")
            if _cache is not None:
                _cache[height] = blk
        index = int(rec["index"])
        root, proof = tx_proof(blk.data.txs, index)
        return {
            "root_hash": hex_up(root),
            "data": rec["tx"],
            "proof": {
                "total": str(proof.total),
                "index": str(proof.index),
                "leaf_hash": b64(proof.leaf_hash),
                "aunts": [b64(a) for a in proof.aunts],
            },
        }

    def merkle_proof(self, height=None, indices="") -> dict:
        """Batched tx-inclusion proofs (ours, no reference analogue):
        one call returns device-generated Merkle proofs for MANY leaf
        indices of a block's data_hash tree.  Queries ride the verify
        service's PROOF class (models/proof_server.prove), so concurrent
        light-client requests coalesce into one device dispatch behind
        the scheduler; every degraded route answers the same bytes as
        crypto/merkle.proofs_from_byte_slices.

        ``indices``: a JSON list of ints or a comma-separated string;
        count capped by COMETBFT_TPU_PROOF_QUERY_MAX (-32602 beyond it).
        Proof JSON matches tx(prove=true)'s shape, one entry per index
        in the caller's order."""
        from ..utils import envknobs

        h = self._height_or_latest(height)
        blk = self.block_store.load_block(h)
        if blk is None:
            raise RPCError(-32603, f"block {h} not found")
        txs = blk.data.txs
        if not txs:
            raise RPCError(-32602, f"block {h} has no txs to prove")
        if isinstance(indices, str):
            parts = [p for p in indices.split(",") if p.strip()]
        elif isinstance(indices, (list, tuple)):
            parts = list(indices)
        else:
            parts = [indices]
        if not parts:
            raise RPCError(-32602, "indices must name at least one leaf")
        cap = max(1, envknobs.get_int(envknobs.PROOF_QUERY_MAX))
        if len(parts) > cap:
            raise RPCError(
                -32602,
                f"too many indices ({len(parts)} > {cap}, "
                f"COMETBFT_TPU_PROOF_QUERY_MAX)",
            )
        try:
            idxs = [int(p) for p in parts]
        except (TypeError, ValueError) as e:
            raise RPCError(-32602, f"invalid indices: {e}") from e
        for i in idxs:
            if i < 0 or i >= len(txs):
                raise RPCError(
                    -32602,
                    f"index {i} out of range for {len(txs)} txs",
                )
        from ..models import proof_server
        from ..types.tx import tx_hash as _tx_hash

        leaves = [_tx_hash(tx) for tx in txs]
        root, proofs = proof_server.prove(leaves, idxs)
        return {
            "height": str(h),
            "total": str(len(txs)),
            "root_hash": hex_up(root),
            "proofs": [
                {
                    "total": str(p.total),
                    "index": str(p.index),
                    "leaf_hash": b64(p.leaf_hash),
                    "aunts": [b64(a) for a in p.aunts],
                }
                for p in proofs
            ],
        }

    @staticmethod
    def _order(recs: list, order_by: str, keyfn) -> list:
        """order_by semantics (rpc/core/tx.go): "asc" | "desc" | "" (asc)."""
        if order_by in ("", None, "asc"):
            return sorted(recs, key=keyfn)
        if order_by == "desc":
            return sorted(recs, key=keyfn, reverse=True)
        raise RPCError(-32602, "order_by must be 'asc' or 'desc'")

    def tx_search(self, query="", prove=False, page=1, per_page=30, order_by="") -> dict:
        """rpc/core/tx.go TxSearch over the kv indexer."""
        try:
            recs = self.node.tx_indexer.search(query, limit=10_000)
        except ValueError as e:
            raise RPCError(-32602, f"invalid query: {e}") from e
        recs = self._order(
            recs, order_by, lambda r: (int(r["height"]), int(r["index"]))
        )
        page = max(1, int(page or 1))
        per_page = min(100, max(1, int(per_page or 30)))
        start = (page - 1) * per_page
        sel = recs[start : start + per_page]
        import base64 as _b64

        prove = _as_bool(prove)
        blk_cache: dict = {}  # page-of-results often shares blocks
        out = []
        for r in sel:
            j = self._tx_record_json(tx_hash(_b64.b64decode(r["tx"])), r)
            if prove:
                j["proof"] = self._tx_inclusion_proof(r, blk_cache)
            out.append(j)
        return {"txs": out, "total_count": str(len(recs))}

    def block_search(self, query="", page=1, per_page=30, order_by="") -> dict:
        try:
            heights = self.node.block_indexer.search(query, limit=10_000)
        except ValueError as e:
            raise RPCError(-32602, f"invalid query: {e}") from e
        heights = self._order(heights, order_by, lambda h: h)
        page = max(1, int(page or 1))
        per_page = min(100, max(1, int(per_page or 30)))
        sel = heights[(page - 1) * per_page : (page - 1) * per_page + per_page]
        blocks = []
        for h in sel:
            meta = self.block_store.load_block_meta(h)
            blk = self.block_store.load_block(h)
            if meta is None or blk is None:
                continue
            blocks.append(
                {
                    "block_id": {"hash": hex_up(meta.block_id.hash)},
                    "block": block_json(blk),
                }
            )
        return {"blocks": blocks, "total_count": str(len(heights))}

    def block_results(self, height=None) -> dict:
        """rpc/core/blocks.go BlockResults from the stored
        FinalizeBlockResponse."""
        h = self._height_or_latest(height)
        resp = self.node.state_store.load_finalize_block_response(h)
        if resp is None:
            raise RPCError(-32603, f"no results for height {h}")
        return {
            "height": str(h),
            "txs_results": [tx_result_json(r) for r in (resp.tx_results or [])],
            "finalize_block_events": events_json(resp.events or []),
            "validator_updates": [
                {
                    "pub_key_type": vu.pub_key_type,
                    "power": str(vu.power),
                }
                for vu in (resp.validator_updates or [])
            ],
            "app_hash": hex_up(resp.app_hash),
        }

    @staticmethod
    def _tx_record_json(h: bytes, rec: dict) -> dict:
        return {
            "hash": hex_up(h),
            "height": str(rec["height"]),
            "index": rec["index"],
            "tx_result": rec["result"],
            "tx": rec["tx"],
        }

    # ------------------------------------------------------------ abci

    def abci_info(self) -> dict:
        resp = self.node.app_conns.query.info(abci.InfoRequest())
        return {
            "response": {
                "data": resp.data,
                "version": resp.version,
                "app_version": str(resp.app_version),
                "last_block_height": str(resp.last_block_height),
                "last_block_app_hash": b64(resp.last_block_app_hash),
            }
        }

    def abci_query(self, path="", data="", height=0, prove=False) -> dict:
        if isinstance(data, str):
            data = bytes.fromhex(data) if data else b""
        prove = _as_bool(prove)
        resp = self.node.app_conns.query.query(
            abci.QueryRequest(
                path=path, data=data, height=int(height or 0), prove=bool(prove)
            )
        )
        proof_ops = None
        if getattr(resp, "proof_ops", None) and resp.proof_ops.ops:
            proof_ops = {
                "ops": [
                    {"type": op.type, "key": b64(op.key), "data": b64(op.data)}
                    for op in resp.proof_ops.ops
                ]
            }
        return {
            "response": {
                "code": resp.code,
                "log": resp.log,
                "key": b64(resp.key),
                "value": b64(resp.value),
                "proof_ops": proof_ops,
                "height": str(resp.height),
            }
        }

    # --------------------------------------------------------- mempool

    def broadcast_tx_async(self, tx: bytes) -> dict:
        import threading

        from ..crypto import hash as tmhash

        threading.Thread(
            target=self._check_tx_quiet, args=(tx,), daemon=True,
            name="rpc-checktx",
        ).start()
        return {"code": 0, "data": "", "log": "", "hash": hex_up(tmhash.sum(tx))}

    def _check_tx_quiet(self, tx: bytes) -> None:
        try:
            self.node.mempool.check_tx(tx)
        except Exception as e:  # noqa: BLE001 — async broadcast reports nothing
            # rejected txs are normal here (broadcast_tx_async has no
            # reply channel); debug keeps the reason findable without spam
            _log.debug(f"async check_tx failed: {e!r}")

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        from ..crypto import hash as tmhash

        try:
            self.node.mempool.check_tx(tx)
            code, log = 0, ""
        except MempoolError as e:
            code, log = getattr(e, "code", 1) or 1, str(e)
        return {"code": code, "data": "", "log": log, "hash": hex_up(tmhash.sum(tx))}

    def broadcast_tx_commit(self, tx: bytes, timeout: float = 30.0) -> dict:
        """rpc/core/mempool.go:86 — CheckTx, then wait for the tx event."""
        from ..crypto import hash as tmhash

        tx_hash = tmhash.sum(tx)
        sub = self.node.event_bus.subscribe(
            f"tx-wait-{tx_hash.hex()[:16]}-{time.monotonic_ns()}", EventQueryTx
        )
        try:
            try:
                self.node.mempool.check_tx(tx)
            except MempoolError as e:
                return {
                    "check_tx": {"code": getattr(e, "code", 1) or 1, "log": str(e)},
                    "tx_result": {},
                    "hash": hex_up(tx_hash),
                    "height": "0",
                }
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RPCError(-32603, "timed out waiting for tx to be included")
                import queue as _q

                try:
                    msg, _ = sub.get(timeout=min(remaining, 1.0))
                except _q.Empty:
                    continue
                d = msg.data
                if d.get("tx") == tx:
                    return {
                        "check_tx": {"code": 0, "log": ""},
                        "tx_result": tx_result_json(d["result"]),
                        "hash": hex_up(tx_hash),
                        "height": str(d["height"]),
                    }
        finally:
            self.node.event_bus.pubsub.unsubscribe_all(sub.subscriber)

    def unconfirmed_txs(self, limit=30) -> dict:
        mp = self.node.mempool
        txs = mp.reap_max_txs(int(limit or 30))
        return {
            "n_txs": str(len(txs)),
            "total": str(mp.size()),
            "total_bytes": str(mp.size_bytes()),
            "txs": [b64(t) for t in txs],
        }

    def unconfirmed_tx(self, hash="") -> dict:
        """rpc/core/mempool.go UnconfirmedTx: fetch one pending tx by key."""
        h = bytes.fromhex(hash) if isinstance(hash, str) else hash
        entry = self.node.mempool.get_entry(h)
        if entry is None:
            raise RPCError(-32603, f"tx {h.hex()} not found in mempool")
        return {"tx": b64(entry.tx)}

    def unsafe_flush_mempool(self) -> dict:
        """rpc/core/mempool.go UnsafeFlushMempool (unsafe-gated,
        routes.go:63)."""
        self._require_unsafe()
        self.node.mempool.flush()
        return {}

    def num_unconfirmed_txs(self) -> dict:
        mp = self.node.mempool
        return {
            "n_txs": str(mp.size()),
            "total": str(mp.size()),
            "total_bytes": str(mp.size_bytes()),
            "txs": None,
        }

    # -------------------------------------------------------- consensus

    def consensus_state(self) -> dict:
        rs = self.node.consensus_state.get_round_state()
        return {
            "round_state": {
                "height/round/step": f"{rs.height}/{rs.round}/{rs.step}",
                "start_time": str(rs.start_time_ns),
                "proposal_block_hash": hex_up(
                    rs.proposal_block.hash() if rs.proposal_block else b""
                ),
            }
        }

    def dump_consensus_trace(self) -> dict:
        """Flight-recorder dump (ours, no reference analogue): the
        bounded ring of recent step transitions, vote/proposal arrivals,
        timeout fires, and watchdog re-kicks — the TEMPORAL complement
        to dump_consensus_state's point-in-time deep dump.  Entries are
        oldest-first; `evicted` says how much history scrolled out of
        the ring (utils/flightrec.py)."""
        from ..utils.flightrec import recorder

        return recorder().dump()

    def height_timeline(self, limit=None) -> dict:
        """Per-height consensus timeline (ours, no reference analogue):
        for each of the last N heights, the wall time the pipeline
        reached every phase (proposal received, block assembled, 2/3
        prevote, 2/3 precommit, commit, apply), the per-phase deltas in
        seconds, and the height's verify-batch attribution — "why was
        height H slow" in one request (utils/heightline.py).  `limit`
        keeps only the newest N heights."""
        from ..utils.heightline import registry

        lim = None
        if limit is not None and limit != "":
            try:
                lim = int(limit)
            except (TypeError, ValueError):
                raise RPCError(-32602, f"bad limit {limit!r}")
        return registry().snapshot(limit=lim)

    # --------------------------------------------- fault injection (chaos)

    def _require_fault_rpc(self) -> None:
        """The fault routes exist for the chaos harness (e2e/scenarios,
        scripts/chaos.py); they are live only when the node was started
        with COMETBFT_TPU_FAULT_RPC=1 — a production node rejects them
        the way unsafe p2p controls reject without rpc.unsafe."""
        from ..utils import envknobs

        if not envknobs.get_bool(envknobs.FAULT_RPC):
            raise RPCError(
                -32601,
                "fault-injection RPC is disabled: set COMETBFT_TPU_FAULT_RPC=1",
            )

    def arm_fault(self, name=None, value=None) -> dict:
        """Arm a named fault in the registry (utils/fail.py): the chaos
        harness's live injection entry — a backend wedge, a lossy link,
        a byzantine double-sign — into a running node, deterministically
        and without touching its process."""
        self._require_fault_rpc()
        from ..utils import fail

        if not name:
            raise RPCError(-32602, "missing fault name")
        try:
            fail.arm(str(name), float(value) if value is not None else 1.0)
        except ValueError as e:
            raise RPCError(-32602, str(e)) from e
        _log.warning(f"fault armed via RPC: {name}={value if value is not None else 1}")
        return {"armed": fail.active()}

    def clear_fault(self, name=None) -> dict:
        """Clear one fault (or all, with no name): the heal half of
        every chaos scenario."""
        self._require_fault_rpc()
        from ..utils import fail

        if name:
            fail.clear(str(name))
        else:
            fail.clear_all()
        _log.warning(f"fault cleared via RPC: {name or 'ALL'}")
        return {"armed": fail.active()}

    def faults(self) -> dict:
        """Armed-fault snapshot + per-fault fire tallies (readable with
        the arm/clear routes disabled — observing is never unsafe)."""
        from ..utils import envknobs, fail

        return {
            "rpc_enabled": envknobs.get_bool(envknobs.FAULT_RPC),
            "armed": fail.active(),
            "fired": fail.fired(),
        }

    def verify_svc_status(self) -> dict:
        """Verify-service scheduler snapshot (ours, no reference
        analogue): per-class queue depths, dispatched/rejected batch
        tallies, the effective batch/deadline/weight configuration, and
        — when COMETBFT_TPU_VERIFYRPC_ADDR points this node at a shared
        out-of-process plane — the remote client's breaker state,
        trip/restore tallies, and pending/resend counts under `remote`
        (verifysvc/service.py + remote.py).  Complements the
        `verify_svc_*`/`verify_rpc_*` series on /metrics with an
        on-demand structured view."""
        from ..verifysvc.service import global_service

        return global_service().stats()

    def consensus_params(self, height=None) -> dict:
        h = self._height_or_latest(height)
        params = self.node.state_store.load_consensus_params(h)
        if params is None:
            params = self.state.consensus_params
        return {
            "block_height": str(h),
            "consensus_params": {
                "block": {
                    "max_bytes": str(params.block.max_bytes),
                    "max_gas": str(params.block.max_gas),
                },
                "evidence": {
                    "max_age_num_blocks": str(params.evidence.max_age_num_blocks),
                    "max_age_duration": str(params.evidence.max_age_duration_ns),
                    "max_bytes": str(params.evidence.max_bytes),
                },
                "validator": {
                    "pub_key_types": list(params.validator.pub_key_types)
                },
                "version": {"app": str(params.version.app)},
                "synchrony": {
                    "precision": str(params.synchrony.precision_ns),
                    "message_delay": str(params.synchrony.message_delay_ns),
                },
                "feature": {
                    "vote_extensions_enable_height": str(
                        params.feature.vote_extensions_enable_height
                    ),
                    "pbts_enable_height": str(params.feature.pbts_enable_height),
                },
            },
        }


def _as_bool(v) -> bool:
    """URI-route params arrive as strings; 'false' must not be truthy."""
    if isinstance(v, str):
        return v.lower() in ("1", "true", "t")
    return bool(v)


def _parse_hash(h: str) -> bytes:
    """Accept plain or 0x-prefixed hex; malformed input is a -32602."""
    if h.startswith("0x"):
        h = h[2:]
    try:
        return bytes.fromhex(h)
    except ValueError as e:
        raise RPCError(-32602, f"invalid hash {h!r}: {e}") from e


def _bits(vote_set) -> str:
    """'xx_x_' bit-array rendering of who voted (bits.go String)."""
    if vote_set is None:
        return ""
    return "".join("x" if b else "_" for b in vote_set.votes_bit_array)


def _hdr(meta):
    from ..types.block import Header

    return Header.from_proto(meta.header)


ROUTES = {
    "health": ("", Environment.health),
    "tpu_health": ("", Environment.tpu_health),
    "status": ("", Environment.status),
    "net_info": ("", Environment.net_info),
    "genesis": ("", Environment.genesis),
    "genesis_chunked": ("chunk", Environment.genesis_chunked),
    # unsafe routes (reference gates behind config unsafe,
    # rpc/core/routes.go:51-57); the handlers re-check rpc.unsafe
    "dial_seeds": ("seeds", Environment.dial_seeds),
    "dial_peers": ("peers,persistent", Environment.dial_peers),
    "block": ("height", Environment.block),
    "block_by_hash": ("hash", Environment.block_by_hash),
    "block_results": ("height", Environment.block_results),
    "blockchain": ("minHeight,maxHeight", Environment.blockchain),
    "header": ("height", Environment.header),
    "header_by_hash": ("hash", Environment.header_by_hash),
    "commit": ("height", Environment.commit),
    "tx": ("hash,prove", Environment.tx),
    "merkle_proof": ("height,indices", Environment.merkle_proof),
    "tx_search": ("query,prove,page,per_page,order_by", Environment.tx_search),
    "block_search": ("query,page,per_page,order_by", Environment.block_search),
    "unconfirmed_tx": ("hash", Environment.unconfirmed_tx),
    "unsafe_flush_mempool": ("", Environment.unsafe_flush_mempool),
    "validators": ("height,page,per_page", Environment.validators),
    "abci_info": ("", Environment.abci_info),
    "abci_query": ("path,data,height,prove", Environment.abci_query),
    "broadcast_tx_async": ("tx", Environment.broadcast_tx_async),
    "broadcast_tx_sync": ("tx", Environment.broadcast_tx_sync),
    "broadcast_tx_commit": ("tx", Environment.broadcast_tx_commit),
    "check_tx": ("tx", Environment.check_tx),
    "broadcast_evidence": ("evidence", Environment.broadcast_evidence),
    "unconfirmed_txs": ("limit", Environment.unconfirmed_txs),
    "num_unconfirmed_txs": ("", Environment.num_unconfirmed_txs),
    "consensus_state": ("", Environment.consensus_state),
    "dump_consensus_state": ("", Environment.dump_consensus_state),
    "dump_consensus_trace": ("", Environment.dump_consensus_trace),
    "height_timeline": ("limit", Environment.height_timeline),
    "verify_svc_status": ("", Environment.verify_svc_status),
    # fault injection (chaos harness; live only with COMETBFT_TPU_FAULT_RPC=1)
    "arm_fault": ("name,value", Environment.arm_fault),
    "clear_fault": ("name", Environment.clear_fault),
    "faults": ("", Environment.faults),
    "consensus_params": ("height", Environment.consensus_params),
}
