"""True gRPC data-companion services (reference: rpc/grpc/server —
blockservice, blockresultservice, versionservice, pruningservice).

Serves the reference's exact service paths over grpcio:

  cometbft.services.block.v1.BlockService/GetByHeight
  cometbft.services.block.v1.BlockService/GetLatestHeight   (streaming)
  cometbft.services.block_results.v1.BlockResultsService/GetBlockResults
  cometbft.services.version.v1.VersionService/GetVersion
  cometbft.services.pruning.v1.PruningService/{Set,Get}*RetainHeight

The message bodies ride the framework's deterministic codec
(wire/services_pb.py, field numbers matching the reference protos), and
the business logic is the SAME handler methods the socket-framed
companion server uses (rpc/services.py) — this module only swaps the
transport.  The public/privileged listener split mirrors grpc_laddr /
grpc_privileged_laddr (node/node.go): privileged=True serves ONLY the
pruning service so operators can firewall the retain-height API.
"""

from __future__ import annotations

from ..utils.log import get_logger
from ..utils.service import Service
from ..wire import services_pb as pb
from .services import _HANDLERS, CompanionServiceServer

_BLOCK = "cometbft.services.block.v1.BlockService"
_RESULTS = "cometbft.services.block_results.v1.BlockResultsService"
_VERSION = "cometbft.services.version.v1.VersionService"
_PRUNING = "cometbft.services.pruning.v1.PruningService"

# full gRPC path -> the socket server's envelope method name
GRPC_PATHS: dict[str, str] = {
    f"/{_BLOCK}/GetByHeight": "block.GetByHeight",
    f"/{_RESULTS}/GetBlockResults": "block_results.GetBlockResults",
    f"/{_VERSION}/GetVersion": "version.GetVersion",
    **{
        f"/{_PRUNING}/{m.split('.', 1)[1]}": m
        for m in _HANDLERS
        if m.startswith("pruning.")
    },
}
_STREAM_PATH = f"/{_BLOCK}/GetLatestHeight"


def _status_for(e: ValueError):
    """Map a handler ValueError onto the gRPC status code the reference
    services return for the same condition: missing data -> NOT_FOUND
    (height outside the store, pruned results), a service the node isn't
    running -> UNIMPLEMENTED (pruning without a pruner), anything else
    about the request itself -> INVALID_ARGUMENT.  Without this mapping
    grpcio turns every handler exception into UNKNOWN, which clients
    can't distinguish from a server bug."""
    import grpc

    msg = str(e).lower()
    if "not found" in msg or "not in store range" in msg or "no block results" in msg:
        return grpc.StatusCode.NOT_FOUND
    if "not enabled" in msg:
        return grpc.StatusCode.UNIMPLEMENTED
    return grpc.StatusCode.INVALID_ARGUMENT


class GrpcCompanionServer(Service):
    """gRPC front end over the companion-service handlers.

    Takes the same components as CompanionServiceServer; an internal
    (never-started) instance carries them so both transports execute
    identical logic."""

    def __init__(self, addr: str, privileged: bool = False, **components):
        super().__init__("GrpcCompanionServices")
        self.addr = addr
        self.privileged = privileged
        # host the handlers without opening the socket listener
        self._inner = CompanionServiceServer(
            addr="127.0.0.1:0", privileged=privileged, **components
        )
        self._server = None
        self.port = 0
        self.logger = get_logger("grpc-services")

    def on_start(self) -> None:
        import grpc
        from concurrent import futures

        outer = self
        inner = self._inner

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                path = details.method
                if path == _STREAM_PATH:
                    if outer.privileged:
                        return None  # public service; not on this listener
                    return grpc.unary_stream_rpc_method_handler(
                        outer._latest_height_stream,
                        request_deserializer=bytes,
                        response_serializer=lambda m: m.encode(),
                    )
                method = GRPC_PATHS.get(path)
                if method is None:
                    return None
                if method.startswith("pruning.") != outer.privileged:
                    return None  # wrong listener for this service
                handler = _HANDLERS[method]

                def unary(payload: bytes, ctx):
                    try:
                        return handler(inner, payload)
                    except ValueError as e:
                        # map domain errors to proper status codes — the
                        # reference services return NotFound/
                        # InvalidArgument, not UNKNOWN
                        # (blockservice/service.go GetByHeight)
                        ctx.abort(_status_for(e), str(e))

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=bytes,
                    response_serializer=lambda m: m.encode(),
                )

        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="rpc-grpc"
            ),
            handlers=(Handler(),)
        )
        self.port = self._server.add_insecure_port(self.addr)
        if self.port == 0:
            raise OSError(f"grpc companion server failed to bind {self.addr!r}")
        self._server.start()
        kind = "privileged" if self.privileged else "public"
        self.logger.info(f"{kind} gRPC companion services on port {self.port}")

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait()
            self._server = None

    def _latest_height_stream(self, _payload: bytes, ctx):
        """gRPC framing over the shared subscription generator
        (rpc/services.py latest_heights); ends when the client cancels
        or this server stops."""
        for height in self._inner.latest_heights(
            live=lambda: self.is_running() and ctx.is_active()
        ):
            yield pb.GetLatestHeightResponse(height=height)


class GrpcCompanionClient:
    """Thin unary client for the gRPC companion services (the reference
    ships generated clients; this one plugs the framework codec into
    grpcio directly)."""

    def __init__(self, addr: str, timeout: float = 10.0):
        import grpc

        self._channel = grpc.insecure_channel(addr)
        self.timeout = timeout

    def close(self) -> None:
        self._channel.close()

    def _unary(self, path: str, req_msg, resp_cls):
        call = self._channel.unary_unary(
            path,
            request_serializer=lambda m: m.encode(),
            response_deserializer=resp_cls.decode,
        )
        return call(req_msg, timeout=self.timeout)

    def get_by_height(self, height: int = 0) -> pb.GetByHeightResponse:
        return self._unary(
            f"/{_BLOCK}/GetByHeight",
            pb.GetByHeightRequest(height=height),
            pb.GetByHeightResponse,
        )

    def latest_height_stream(self):
        call = self._channel.unary_stream(
            _STREAM_PATH,
            request_serializer=lambda m: m.encode(),
            response_deserializer=pb.GetLatestHeightResponse.decode,
        )
        return call(pb.GetLatestHeightRequest())

    def get_block_results(self, height: int = 0) -> pb.GetBlockResultsResponse:
        return self._unary(
            f"/{_RESULTS}/GetBlockResults",
            pb.GetBlockResultsRequest(height=height),
            pb.GetBlockResultsResponse,
        )

    def get_version(self) -> pb.GetVersionResponse:
        return self._unary(
            f"/{_VERSION}/GetVersion",
            pb.GetVersionRequest(),
            pb.GetVersionResponse,
        )

    def set_block_retain_height(self, height: int) -> None:
        self._unary(
            f"/{_PRUNING}/SetBlockRetainHeight",
            pb.SetBlockRetainHeightRequest(height=height),
            pb.Empty,
        )

    def get_block_retain_height(self) -> pb.GetBlockRetainHeightResponse:
        return self._unary(
            f"/{_PRUNING}/GetBlockRetainHeight",
            pb.Empty(),
            pb.GetBlockRetainHeightResponse,
        )
