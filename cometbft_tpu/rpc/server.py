"""JSON-RPC server: HTTP POST + GET with URI params + WebSocket events
(reference: rpc/jsonrpc/server/{http_server,http_json_handler,
ws_handler}.go — rebuilt on the stdlib threading HTTP server; the
WebSocket endpoint implements the RFC 6455 handshake + text frames
directly, which is all the event stream needs).
"""

from __future__ import annotations

import base64
import hashlib
import json
import queue
import socketserver
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from ..utils.log import get_logger
from .core import ROUTES, Environment, RPCError
from .serializers import events_json

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def _coerce_tx(v):
    """JSON-RPC tx params arrive base64 (POST) or 0x-hex/quoted (GET)."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        if v.startswith("0x"):
            return bytes.fromhex(v[2:])
        if v.startswith('"') and v.endswith('"'):
            return v[1:-1].encode()
        try:
            return base64.b64decode(v, validate=True)
        except Exception:  # noqa: BLE001
            return v.encode()
    return v


class RPCServer:
    def __init__(self, node):
        self.env = Environment(node)
        self.node = node
        self.logger = get_logger("rpc")
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.listen_addr: str | None = None

    def start(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        host = host or "127.0.0.1"
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                server._handle_jsonrpc(self, body)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path == "/websocket":
                    server._handle_websocket(self)
                    return
                method = url.path.strip("/")
                params = dict(parse_qsl(url.query))
                req = {
                    "jsonrpc": "2.0",
                    "id": -1,
                    "method": method,
                    "params": params,
                }
                server._handle_jsonrpc(self, json.dumps(req).encode())

        class _Srv(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Srv((host, int(port)), Handler)
        self.listen_addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="rpc-http"
        )
        self._thread.start()
        self.logger.info(f"RPC listening on {self.listen_addr}")

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    # ------------------------------------------------------------ JSON-RPC

    def _handle_jsonrpc(self, handler, body: bytes) -> None:
        try:
            req = json.loads(body or b"{}")
        except ValueError as e:
            # UnicodeDecodeError (non-UTF8 bodies) is a ValueError but
            # NOT a JSONDecodeError — catch the whole family or garbage
            # input kills the connection instead of getting a -32700
            self._reply(handler, None, error={"code": -32700, "message": str(e)})
            return
        if not isinstance(req, dict):
            self._reply(
                handler, None,
                error={"code": -32600, "message": "request must be an object"},
            )
            return
        rid = req.get("id", -1)
        method = req.get("method", "")
        params = req.get("params") or {}
        if not isinstance(method, str):
            # non-string method (list/object) would TypeError on the
            # dict lookup and kill the connection
            self._reply(
                handler, rid,
                error={"code": -32600, "message": "method must be a string"},
            )
            return
        route = ROUTES.get(method)
        if route is None:
            self._reply(
                handler, rid,
                error={"code": -32601, "message": f"Method not found: {method}"},
            )
            return
        param_names, fn = route
        names = [n for n in param_names.split(",") if n]
        kwargs = {}
        if isinstance(params, dict):
            for n in names:
                if n in params:
                    kwargs[n] = params[n]
        elif isinstance(params, list):
            kwargs = dict(zip(names, params))
        if "tx" in kwargs:
            kwargs["tx"] = _coerce_tx(kwargs["tx"])
        try:
            result = fn(self.env, **kwargs)
            self._reply(handler, rid, result=result)
        except RPCError as e:
            self._reply(
                handler, rid,
                error={"code": e.code, "message": str(e), "data": e.data},
            )
        except Exception as e:  # noqa: BLE001
            self.logger.error(f"rpc {method} failed: {e}")
            self._reply(
                handler, rid,
                error={"code": -32603, "message": f"Internal error: {e}"},
            )

    def _reply(self, handler, rid, result=None, error=None) -> None:
        msg = {"jsonrpc": "2.0", "id": rid}
        if error is not None:
            msg["error"] = error
        else:
            msg["result"] = result
        data = json.dumps(msg).encode()
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # ----------------------------------------------------------- websocket

    def _handle_websocket(self, handler) -> None:
        """RFC 6455 server side: handshake, then serve subscribe /
        unsubscribe over JSON-RPC text frames (ws_handler.go)."""
        key = handler.headers.get("Sec-WebSocket-Key")
        if not key:
            handler.send_response(400)
            handler.end_headers()
            return
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()
        ).decode()
        handler.send_response(101, "Switching Protocols")
        handler.send_header("Upgrade", "websocket")
        handler.send_header("Connection", "Upgrade")
        handler.send_header("Sec-WebSocket-Accept", accept)
        handler.end_headers()

        conn = handler.connection
        conn_id = f"ws-{id(handler)}"
        send_mtx = threading.Lock()
        subs: dict[str, object] = {}
        stop = threading.Event()

        def send_text(obj) -> None:
            data = json.dumps(obj).encode()
            frame = bytearray([0x81])
            n = len(data)
            if n < 126:
                frame.append(n)
            elif n < 1 << 16:
                frame.append(126)
                frame += struct.pack(">H", n)
            else:
                frame.append(127)
                frame += struct.pack(">Q", n)
            frame += data
            with send_mtx:
                conn.sendall(bytes(frame))

        def pump(query_expr: str, sub) -> None:
            while not stop.is_set() and not sub.cancelled.is_set():
                try:
                    msg, events = sub.out.get(timeout=0.5)
                except queue.Empty:
                    continue
                try:
                    send_text(
                        {
                            "jsonrpc": "2.0",
                            "id": f"{conn_id}#event",
                            "result": {
                                "query": query_expr,
                                "data": {
                                    "type": f"tendermint/event/{msg.event_type}",
                                    "value": _event_value_json(msg),
                                },
                                "events": events,
                            },
                        }
                    )
                except OSError:
                    stop.set()
                    return

        try:
            while not stop.is_set():
                req = self._read_ws_frame(conn)
                if req is None:
                    break
                try:
                    msg = json.loads(req)
                except ValueError:
                    continue
                if not isinstance(msg, dict):
                    # valid JSON but not an object: same guard as the
                    # HTTP path, or '[1]' kills the whole WS connection
                    continue
                method = msg.get("method")
                rid = msg.get("id", -1)
                params = msg.get("params") or {}
                if not isinstance(params, dict):
                    params = {}  # same leniency as the HTTP path
                if method == "subscribe":
                    q = params.get("query", "")
                    try:
                        sub = self.node.event_bus.subscribe(conn_id, q)
                    except Exception as e:  # noqa: BLE001
                        send_text({
                            "jsonrpc": "2.0", "id": rid,
                            "error": {"code": -32603, "message": str(e)},
                        })
                        continue
                    subs[q] = sub
                    threading.Thread(
                        target=pump, args=(q, sub), daemon=True,
                        name="rpc-ws-pump",
                    ).start()
                    send_text({"jsonrpc": "2.0", "id": rid, "result": {}})
                elif method == "unsubscribe":
                    q = params.get("query", "")
                    self.node.event_bus.pubsub.unsubscribe(conn_id, q)
                    subs.pop(q, None)
                    send_text({"jsonrpc": "2.0", "id": rid, "result": {}})
                elif method == "unsubscribe_all":
                    self.node.event_bus.pubsub.unsubscribe_all(conn_id)
                    subs.clear()
                    send_text({"jsonrpc": "2.0", "id": rid, "result": {}})
                else:
                    send_text({
                        "jsonrpc": "2.0", "id": rid,
                        "error": {"code": -32601, "message": "ws supports subscribe/unsubscribe"},
                    })
        finally:
            stop.set()
            self.node.event_bus.pubsub.unsubscribe_all(conn_id)
            handler.close_connection = True

    @staticmethod
    def _read_ws_frame(conn) -> str | None:
        """One client->server text frame (masked per RFC 6455)."""

        def read_exact(n: int) -> bytes | None:
            buf = b""
            while len(buf) < n:
                chunk = conn.recv(n - len(buf))
                if not chunk:
                    return None
                buf += chunk
            return buf

        hdr = read_exact(2)
        if hdr is None:
            return None
        opcode = hdr[0] & 0x0F
        masked = hdr[1] & 0x80
        n = hdr[1] & 0x7F
        if n == 126:
            ext = read_exact(2)
            if ext is None:
                return None
            n = struct.unpack(">H", ext)[0]
        elif n == 127:
            ext = read_exact(8)
            if ext is None:
                return None
            n = struct.unpack(">Q", ext)[0]
        mask = read_exact(4) if masked else b"\x00" * 4
        if mask is None:
            return None
        payload = read_exact(n) if n else b""
        if payload is None:
            return None
        data = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        if opcode == 0x8:  # close
            return None
        if opcode != 0x1:  # only text frames carry JSON-RPC
            return ""
        return data.decode("utf-8", "replace")


def _event_value_json(msg) -> dict:
    """Typed event payload -> JSON (responses.go ResultEvent shapes)."""
    from .serializers import block_json, header_json, tx_result_json

    d = msg.data
    if msg.event_type == "NewBlock":
        return {
            "block": block_json(d["block"]),
            "block_id": {"hash": d["block_id"].hash.hex().upper()},
        }
    if msg.event_type == "NewBlockHeader":
        return {"header": header_json(d["header"])}
    if msg.event_type == "Tx":
        return {
            "TxResult": {
                "height": str(d["height"]),
                "index": d["index"],
                "tx": base64.b64encode(d["tx"]).decode(),
                "result": tx_result_json(d["result"]),
            }
        }
    if msg.event_type == "NewBlockEvents":
        return {
            "height": str(d["height"]),
            "events": events_json(d["events"]),
            "num_txs": str(d["num_txs"]),
        }
    return {"event": msg.event_type}
