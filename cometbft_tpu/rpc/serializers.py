"""JSON shapes for RPC results (reference: rpc/core/types/responses.go +
the amino-JSON conventions: hashes hex-uppercase, binary payloads
base64, times RFC3339, int64s as strings).
"""

from __future__ import annotations

import base64


def hex_up(b: bytes) -> str:
    return b.hex().upper()


def b64(b: bytes) -> str:
    return base64.b64encode(b or b"").decode()


def ts_json(t) -> str:
    if t is None:
        return "0001-01-01T00:00:00Z"
    return t.to_rfc3339() if hasattr(t, "to_rfc3339") else _rfc3339(t)


def _rfc3339(t) -> str:
    import datetime

    ns = t.unix_ns()
    dt = datetime.datetime.fromtimestamp(ns // 10**9, datetime.timezone.utc)
    frac = ns % 10**9
    # strftime %Y does NOT zero-pad years < 1000 on glibc: the zero time
    # (year 1, absent commit signatures) must still round-trip as
    # RFC 3339 "0001-01-01T00:00:00Z"
    base = (
        f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}"
        f"T{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}"
    )
    return f"{base}.{frac:09d}Z" if frac else base + "Z"


def block_id_json(bid) -> dict:
    return {
        "hash": hex_up(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": hex_up(bid.part_set_header.hash),
        },
    }


def header_json(h) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app or 0)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": ts_json(h.time),
        "last_block_id": block_id_json(h.last_block_id),
        "last_commit_hash": hex_up(h.last_commit_hash),
        "data_hash": hex_up(h.data_hash),
        "validators_hash": hex_up(h.validators_hash),
        "next_validators_hash": hex_up(h.next_validators_hash),
        "consensus_hash": hex_up(h.consensus_hash),
        "app_hash": hex_up(h.app_hash),
        "last_results_hash": hex_up(h.last_results_hash),
        "evidence_hash": hex_up(h.evidence_hash),
        "proposer_address": hex_up(h.proposer_address),
    }


def commit_sig_json(cs) -> dict:
    return {
        "block_id_flag": cs.block_id_flag,
        "validator_address": hex_up(cs.validator_address),
        "timestamp": ts_json(cs.timestamp),
        "signature": b64(cs.signature) if cs.signature else None,
    }


def commit_json(c) -> dict:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": block_id_json(c.block_id),
        "signatures": [commit_sig_json(s) for s in c.signatures],
    }


def block_json(b) -> dict:
    return {
        "header": header_json(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": []},  # typed evidence JSON: indexer work
        "last_commit": commit_json(b.last_commit) if b.last_commit else None,
    }


_AMINO_PUBKEY_NAMES = {
    "ed25519": "tendermint/PubKeyEd25519",
    "secp256k1": "tendermint/PubKeySecp256k1",
    "bls12_381": "cometbft/PubKeyBls12_381",
    "secp256k1eth": "cometbft/PubKeySecp256k1eth",
}


def validator_json(v) -> dict:
    kt = v.pub_key.type
    return {
        "address": hex_up(v.address),
        "pub_key": {
            "type": _AMINO_PUBKEY_NAMES.get(kt, kt),
            "value": b64(v.pub_key.bytes()),
        },
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def tx_result_json(r) -> dict:
    return {
        "code": r.code,
        "data": b64(r.data) if r.data else None,
        "log": r.log,
        "codespace": getattr(r, "codespace", ""),
        "gas_wanted": str(getattr(r, "gas_wanted", 0)),
        "gas_used": str(getattr(r, "gas_used", 0)),
        "events": events_json(getattr(r, "events", []) or []),
    }


def events_json(events) -> list:
    return [
        {
            "type": ev.type,
            "attributes": [
                {"key": a.key, "value": a.value, "index": bool(getattr(a, "index", False))}
                for a in ev.attributes
            ],
        }
        for ev in events
    ]
