"""Evidence subsystem: pool, verification, and gossip
(reference: internal/evidence/).
"""

from .pool import ErrInvalidEvidence, EvidenceError, EvidencePool
from .reactor import EVIDENCE_STREAM, EvidenceReactor
from .verify import (
    EvidenceVerificationError,
    is_evidence_expired,
    verify_duplicate_vote,
    verify_light_client_attack,
)

__all__ = [
    "EvidencePool",
    "EvidenceError",
    "ErrInvalidEvidence",
    "EvidenceReactor",
    "EVIDENCE_STREAM",
    "EvidenceVerificationError",
    "is_evidence_expired",
    "verify_duplicate_vote",
    "verify_light_client_attack",
]
