"""Evidence reactor: gossips pending evidence to peers.

Reference: internal/evidence/reactor.go — one stream (0x38), a
per-peer broadcast routine that cycles over the pending list every
~10 s (most evidence commits within a block, so the cycle is just above
block cadence), pacing by the peer's consensus height so evidence isn't
sent before the peer can verify it.
"""

from __future__ import annotations

import threading
import time

from ..p2p.conn.connection import StreamDescriptor
from ..p2p.reactor import Reactor
from ..utils.log import get_logger
from ..wire import types_pb as pb
from ..types.evidence import evidence_from_proto, evidence_to_proto
from ..types.msg_validation import validate_evidence_list
from .pool import ErrInvalidEvidence, EvidencePool

EVIDENCE_STREAM = 0x38
BROADCAST_INTERVAL = 10.0  # reactor.go broadcastEvidenceIntervalS
PEER_CATCHUP_SLEEP = 0.1
MAX_MSG_BYTES = 1 << 20


class EvidenceReactor(Reactor):
    def __init__(self, evpool: EvidencePool, broadcast_interval: float = BROADCAST_INTERVAL):
        super().__init__("EvidenceReactor")
        self.evpool = evpool
        self.interval = broadcast_interval
        self.logger = get_logger("ev-reactor")

    def stream_descriptors(self) -> list[StreamDescriptor]:
        return [
            StreamDescriptor(
                id=EVIDENCE_STREAM, priority=6, send_queue_capacity=100
            )
        ]

    def add_peer(self, peer) -> None:
        threading.Thread(
            target=self._broadcast_routine, args=(peer,), daemon=True,
            name=f"ev-broadcast-{peer.id[:8]}",
        ).start()

    def receive(self, stream_id: int, peer, msg_bytes: bytes) -> None:
        msg = pb.EvidenceListProto.decode(msg_bytes)
        # validate-before-use: the receive side holds inbound batches to
        # the same byte budget the send side batches under; a raise here
        # disconnects the peer
        validate_evidence_list(msg, len(msg_bytes))
        for evp in msg.evidence or []:
            try:
                ev = evidence_from_proto(evp)
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"undecodable evidence from {peer.id}: {e}")
                self._punish(peer, str(e))
                return
            try:
                self.evpool.add_evidence(ev)
            except ErrInvalidEvidence as e:
                self.logger.error(f"peer {peer.id} sent invalid evidence: {e}")
                self._punish(peer, str(e))
                return
            except Exception as e:  # noqa: BLE001
                # not necessarily the peer's fault (e.g. we lack context)
                self.logger.error(f"failed to add evidence: {e}")

    def _punish(self, peer, reason: str) -> None:
        if self.switch is not None:
            self.switch.stop_peer_for_error(peer, f"evidence: {reason}")

    # ---------------------------------------------------------- broadcast

    def _broadcast_routine(self, peer) -> None:
        """Cycle over the pending list, batching under the message cap
        (reactor.go broadcastEvidenceRoutine redesigned as a periodic
        sweep: the pool's admission feed cuts the sleep short when fresh
        evidence lands)."""
        if not peer.has_channel(EVIDENCE_STREAM):
            return  # peer runs no evidence reactor
        seq = self.evpool.add_seq() - 1  # send everything already pending
        while self.is_running() and peer.is_running():
            evs, _ = self.evpool.pending_evidence(-1)
            batch, size = [], 0
            for ev in evs:
                if not self._peer_can_verify(peer, ev):
                    continue
                raw = evidence_to_proto(ev)
                sz = len(raw.encode())
                if batch and size + sz > MAX_MSG_BYTES:
                    self._send(peer, batch)
                    batch, size = [], 0
                batch.append(raw)
                size += sz
            if batch:
                self._send(peer, batch)
            seq = self.evpool.wait_new_evidence(seq, self.interval)

    def _peer_can_verify(self, peer, ev) -> bool:
        """Don't ship evidence the peer is too far behind to check
        (reactor.go prepareEvidenceMessage peer-height gating)."""
        ps = peer.get("consensus_peer_state")
        if ps is None:
            return True  # no consensus reactor on this peer: best effort
        return ps.height >= ev.height()

    def _send(self, peer, batch) -> None:
        wire = pb.EvidenceListProto(evidence=batch).encode()
        if not peer.send(EVIDENCE_STREAM, wire):
            time.sleep(PEER_CATCHUP_SLEEP)
