"""Evidence pool: verified byzantine-behavior proofs awaiting inclusion.

Reference: internal/evidence/pool.go.  Same lifecycle — consensus reports
conflicting votes into a buffer; Update() at each committed height turns
them into DuplicateVoteEvidence stamped with that block's time, moves
included evidence to the committed set, and prunes by age — but the
storage is a straight prefix layout over the db abstraction (pending
records sort by (height, hash) so PendingEvidence pops oldest-first)
instead of the reference's clist + orderedcode layering.
"""

from __future__ import annotations

import struct
import threading

from ..types.evidence import (
    DuplicateVoteEvidence,
    Evidence,
    evidence_from_proto,
    evidence_to_proto,
)
from ..utils.log import get_logger
from . import verify as verify_mod

_PENDING = b"evP:"
_COMMITTED = b"evC:"


class EvidenceError(Exception):
    pass


class ErrInvalidEvidence(EvidenceError):
    def __init__(self, ev, reason):
        super().__init__(f"invalid evidence {ev!r}: {reason}")
        self.evidence = ev
        self.reason = reason


def _key(prefix: bytes, ev: Evidence) -> bytes:
    return prefix + struct.pack(">q", ev.height()) + ev.hash()


class EvidencePool:
    """sm.EvidencePool contract: pending_evidence / check_evidence /
    update / report_conflicting_votes (+ add_evidence from the reactor)."""

    def __init__(self, db, state_store, block_store):
        self.db = db
        self.state_store = state_store
        self.block_store = block_store
        self.logger = get_logger("evidence")
        self._mtx = threading.Lock()
        state = state_store.load()
        if state is None:
            raise EvidenceError("cannot start evidence pool without state")
        self.state = state
        self._consensus_buffer: list[tuple] = []  # (vote_a, vote_b)
        self._size = sum(1 for _ in self.db.iterator(_PENDING, _PENDING + b"\xff"))
        self.pruning_height = 0
        self.pruning_time_ns = 0
        # wakes the gossip reactor when new evidence lands
        self._added = threading.Condition(self._mtx)
        self._add_seq = 0

    # ------------------------------------------------------------- queries

    def size(self) -> int:
        with self._mtx:
            return self._size

    def pending_evidence(self, max_bytes: int) -> tuple[list[Evidence], int]:
        """Oldest-first pending evidence under the byte budget
        (pool.go:142); returns (list, proto size)."""
        out, total = [], 0
        for _, raw in self.db.iterator(_PENDING, _PENDING + b"\xff"):
            ev = evidence_from_proto_bytes(raw)
            sz = len(raw)
            if max_bytes >= 0 and total + sz > max_bytes:
                break
            out.append(ev)
            total += sz
        return out, total

    def is_pending(self, ev: Evidence) -> bool:
        return self.db.has(_key(_PENDING, ev))

    def is_committed(self, ev: Evidence) -> bool:
        return self.db.has(_key(_COMMITTED, ev))

    # ----------------------------------------------------------- admission

    def add_evidence(self, ev: Evidence) -> None:
        """Gossip/RPC entry: verify against state, persist (pool.go:190)."""
        if self.is_pending(ev):
            return
        if self.is_committed(ev):
            return  # stale gossip from a peer that's behind — not a fault
        try:
            ev.validate_basic()
            verify_mod.verify(self, ev)
        except Exception as e:  # noqa: BLE001
            raise ErrInvalidEvidence(ev, e) from e
        self._add_pending(ev)
        self.logger.info(f"verified new evidence of byzantine behavior: {ev!r}")

    def report_conflicting_votes(self, vote_a, vote_b) -> None:
        """Consensus entry (pool.go:235): buffered until the height
        finishes so the evidence carries the committed block's time."""
        with self._mtx:
            self._consensus_buffer.append((vote_a, vote_b))

    # back-compat shim for callers that pass pre-built evidence
    def add_evidence_from_consensus(self, ev: DuplicateVoteEvidence) -> None:
        self.report_conflicting_votes(ev.vote_a, ev.vote_b)

    def check_evidence(self, ev_list: list[Evidence]) -> None:
        """Verify a proposed block's evidence list (pool.go:248)."""
        from ..types.evidence import LightClientAttackEvidence

        seen = set()
        for ev in ev_list:
            # light attacks are always re-verified: a different conflicting
            # block can share a hash prefix (pool.go:248 comment)
            if isinstance(ev, LightClientAttackEvidence) or not self.is_pending(ev):
                if self.is_committed(ev):
                    raise ErrInvalidEvidence(ev, "evidence was already committed")
                ev.validate_basic()
                try:
                    verify_mod.verify(self, ev)
                except Exception as e:  # noqa: BLE001
                    raise ErrInvalidEvidence(ev, e) from e
                if not self.is_pending(ev):
                    self._add_pending(ev)  # have it ready for ABCI
            h = ev.hash()
            if h in seen:
                raise ErrInvalidEvidence(ev, "duplicate evidence in block")
            seen.add(h)

    # -------------------------------------------------------------- update

    def update(self, state, ev_list: list[Evidence]) -> None:
        """Called by the executor after every applied block (pool.go:161)."""
        if state.last_block_height <= self.state.last_block_height:
            raise EvidenceError(
                f"update to height {state.last_block_height} <= "
                f"{self.state.last_block_height}"
            )
        self._process_consensus_buffer(state)
        with self._mtx:
            self.state = state
        self._mark_committed(ev_list)
        if (
            self.size() > 0
            and state.last_block_height > self.pruning_height
            and state.last_block_time.unix_ns() > self.pruning_time_ns
        ):
            self.pruning_height, self.pruning_time_ns = self._prune_expired()

    def _process_consensus_buffer(self, state) -> None:
        with self._mtx:
            buffered, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buffered:
            try:
                if vote_a.height == state.last_block_height:
                    ev = DuplicateVoteEvidence.from_votes(
                        vote_a, vote_b, state.last_block_time, state.last_validators
                    )
                elif vote_a.height < state.last_block_height:
                    val_set = self.state_store.load_validators(vote_a.height)
                    meta = self.block_store.load_block_meta(vote_a.height)
                    if val_set is None or meta is None:
                        self.logger.error(
                            f"no stored context for conflicting votes at "
                            f"height {vote_a.height}"
                        )
                        continue
                    ev = DuplicateVoteEvidence.from_votes(
                        vote_a, vote_b, meta.header.time, val_set
                    )
                else:
                    self.logger.error(
                        f"conflicting votes from future height {vote_a.height}"
                    )
                    continue
                if self.is_pending(ev) or self.is_committed(ev):
                    continue
                self._add_pending(ev)
                self.logger.info(
                    f"duplicate vote evidence created from consensus: {ev!r}"
                )
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"failed to form duplicate vote evidence: {e}")

    def _mark_committed(self, ev_list: list[Evidence]) -> None:
        if not ev_list:
            return
        height = self.state.last_block_height
        sets, deletes = [], []
        with self._mtx:
            for ev in ev_list:
                sets.append((_key(_COMMITTED, ev), struct.pack(">q", height)))
                pk = _key(_PENDING, ev)
                if self.db.has(pk):
                    deletes.append(pk)
                    self._size -= 1
            self.db.write_batch(sets, deletes)

    def _prune_expired(self) -> tuple[int, int]:
        """Drop expired pending evidence; returns (height, time) at which
        the next earliest evidence expires (pool.go:458)."""
        params = self.state.consensus_params.evidence
        deletes = []
        next_h, next_t = self.state.last_block_height, self.state.last_block_time.unix_ns()
        with self._mtx:
            for k, raw in self.db.iterator(_PENDING, _PENDING + b"\xff"):
                ev = evidence_from_proto_bytes(raw)
                if verify_mod.is_evidence_expired(
                    self.state.last_block_height,
                    self.state.last_block_time.unix_ns(),
                    ev.height(),
                    ev.time().unix_ns(),
                    params,
                ):
                    deletes.append(k)
                else:
                    # first non-expired entry: everything later is newer
                    next_h = ev.height() + params.max_age_num_blocks + 1
                    next_t = ev.time().unix_ns() + params.max_age_duration_ns
                    break
            if deletes:
                self.db.write_batch([], deletes)
                self._size -= len(deletes)
        return next_h, next_t

    # ------------------------------------------------------------ plumbing

    def _add_pending(self, ev: Evidence) -> None:
        with self._mtx:
            self.db.set(_key(_PENDING, ev), evidence_to_proto(ev).encode())
            self._size += 1
            self._add_seq += 1
            self._added.notify_all()

    def wait_new_evidence(self, last_seq: int, timeout: float) -> int:
        with self._added:
            if self._add_seq == last_seq:
                self._added.wait(timeout)
            return self._add_seq

    def add_seq(self) -> int:
        with self._mtx:
            return self._add_seq


def evidence_from_proto_bytes(raw: bytes) -> Evidence:
    from ..wire import types_pb as pb

    return evidence_from_proto(pb.EvidenceProto.decode(raw))
