"""Evidence verification against full-node state.

Reference: internal/evidence/verify.go — verify() time/expiry gates
(:20-46), VerifyDuplicateVote (:164), VerifyLightClientAttack (:110).
The commit checks route through types/validation.py and therefore hit
the TPU batch verifier for large sets; all signatures are always checked
(the evidence will punish validators, so every flag must be right).
"""

from __future__ import annotations

from fractions import Fraction

from ..types.evidence import DuplicateVoteEvidence, LightClientAttackEvidence
from ..types.validation import (
    verify_commit_light,
    verify_commit_light_trusting,
)
from ..verifysvc.service import Klass as _VerifyKlass

DEFAULT_TRUST_LEVEL = Fraction(1, 3)  # light.DefaultTrustLevel


class EvidenceVerificationError(Exception):
    pass


def is_evidence_expired(
    chain_height: int,
    chain_time_ns: int,
    ev_height: int,
    ev_time_ns: int,
    params,
) -> bool:
    """Both age bounds must be exceeded (evidence params, pool.go:320)."""
    age_blocks = chain_height - ev_height
    age_ns = chain_time_ns - ev_time_ns
    return (
        age_blocks > params.max_age_num_blocks
        and age_ns > params.max_age_duration_ns
    )


def verify(evpool, ev) -> None:
    """Full verification of one piece of evidence against pool state
    (verify.go:20)."""
    state = evpool.state
    params = state.consensus_params.evidence

    meta = evpool.block_store.load_block_meta(ev.height())
    if meta is None:
        raise EvidenceVerificationError(
            f"no header at evidence height {ev.height()}"
        )
    ev_time = meta.header.time
    if ev.time().unix_ns() != ev_time.unix_ns():
        raise EvidenceVerificationError(
            f"evidence time {ev.time()} differs from block time {ev_time}"
        )
    if is_evidence_expired(
        state.last_block_height,
        state.last_block_time.unix_ns(),
        ev.height(),
        ev_time.unix_ns(),
        params,
    ):
        raise EvidenceVerificationError(
            f"evidence from height {ev.height()} is too old "
            f"(min height {state.last_block_height - params.max_age_num_blocks})"
        )

    if isinstance(ev, DuplicateVoteEvidence):
        val_set = evpool.state_store.load_validators(ev.height())
        if val_set is None:
            raise EvidenceVerificationError(
                f"no validator set stored for height {ev.height()}"
            )
        verify_duplicate_vote(ev, state.chain_id, val_set)
    elif isinstance(ev, LightClientAttackEvidence):
        common_sh = _signed_header_at(evpool.block_store, ev.height())
        common_vals = evpool.state_store.load_validators(ev.height())
        if common_vals is None:
            raise EvidenceVerificationError(
                f"no validator set stored for height {ev.height()}"
            )
        conflict_h = ev.conflicting_block.height
        if conflict_h != ev.height():
            trusted_sh = _signed_header_at_or_latest(
                evpool.block_store, conflict_h, ev
            )
        else:
            trusted_sh = common_sh
        verify_light_client_attack(
            ev, common_sh, trusted_sh, common_vals, state.chain_id
        )
    else:
        raise EvidenceVerificationError(
            f"unrecognized evidence type {type(ev).__name__}"
        )


def verify_duplicate_vote(ev: DuplicateVoteEvidence, chain_id: str, val_set) -> None:
    """verify.go:164."""
    a, b = ev.vote_a, ev.vote_b
    _, val = val_set.get_by_address(a.validator_address)
    if val is None:
        raise EvidenceVerificationError(
            f"address {a.validator_address.hex()} was not a validator at "
            f"height {ev.height()}"
        )
    if (a.height, a.round, a.type) != (b.height, b.round, b.type):
        raise EvidenceVerificationError("votes differ in height/round/type")
    if a.validator_address != b.validator_address:
        raise EvidenceVerificationError("validator addresses do not match")
    if a.block_id == b.block_id:
        raise EvidenceVerificationError(
            "block IDs are the same — this is not equivocation"
        )
    if val.pub_key.address() != a.validator_address:
        raise EvidenceVerificationError("address does not match pubkey")
    if val.voting_power != ev.validator_power:
        raise EvidenceVerificationError(
            f"validator power {ev.validator_power} != {val.voting_power}"
        )
    if val_set.total_voting_power() != ev.total_voting_power:
        raise EvidenceVerificationError(
            f"total power {ev.total_voting_power} != "
            f"{val_set.total_voting_power()}"
        )
    if not val.pub_key.verify_signature(a.sign_bytes(chain_id), a.signature):
        raise EvidenceVerificationError("invalid signature on vote A")
    if not val.pub_key.verify_signature(b.sign_bytes(chain_id), b.signature):
        raise EvidenceVerificationError("invalid signature on vote B")


def verify_light_client_attack(
    ev: LightClientAttackEvidence,
    common_sh,
    trusted_sh,
    common_vals,
    chain_id: str,
) -> None:
    """verify.go:110 — 1/3 of the common set signed the conflicting
    header; 2/3 of its claimed set signed it; and it genuinely conflicts."""
    cb = ev.conflicting_block
    if common_sh.header.height != cb.height:
        # lunatic: single trusting jump from the common header
        # CONSENSUS class, not background: evidence carried by a
        # proposed block verifies on the consensus critical path
        # (BlockExecutor.validate_block -> check_evidence), and a
        # lower class here would let mempool load starve prevotes on
        # exactly the blocks that carry evidence
        verify_commit_light_trusting(
            chain_id,
            common_vals,
            cb.signed_header.commit,
            DEFAULT_TRUST_LEVEL,
            count_all_signatures=True,
            klass=_VerifyKlass.CONSENSUS,
        )
    elif ev.conflicting_header_is_invalid(trusted_sh.header):
        raise EvidenceVerificationError(
            "common height equals conflicting height, but the conflicting "
            "header is not correctly derived"
        )

    verify_commit_light(
        chain_id,
        cb.validator_set,
        cb.signed_header.commit.block_id,
        cb.height,
        cb.signed_header.commit,
        count_all_signatures=True,
        klass=_VerifyKlass.CONSENSUS,
    )

    if ev.total_voting_power != common_vals.total_voting_power():
        raise EvidenceVerificationError(
            f"total power {ev.total_voting_power} != "
            f"{common_vals.total_voting_power()}"
        )

    if cb.height > trusted_sh.header.height:
        # forward lunatic: must violate monotonic time
        if cb.time.unix_ns() > trusted_sh.header.time.unix_ns():
            raise EvidenceVerificationError(
                "conflicting block does not violate monotonic time"
            )
    elif trusted_sh.header.hash() == cb.hash:
        raise EvidenceVerificationError(
            "conflicting header is identical to the trusted header"
        )

    # the reported byzantine validators must be exactly the derivable set
    want = ev.get_byzantine_validators(common_vals, trusted_sh)
    got = ev.byzantine_validators
    if len(want) != len(got) or any(
        w.address != g.address or w.voting_power != g.voting_power
        for w, g in zip(want, got)
    ):
        raise EvidenceVerificationError(
            "byzantine validator list does not match the evidence"
        )


def _signed_header_at(block_store, height: int):
    from ..types.light_block import SignedHeader

    meta = block_store.load_block_meta(height)
    commit = block_store.load_block_commit(height)
    if meta is None or commit is None:
        raise EvidenceVerificationError(f"no header/commit at height {height}")
    from ..types.block import Header

    return SignedHeader(Header.from_proto(meta.header), commit)


def _signed_header_at_or_latest(block_store, height: int, ev):
    try:
        return _signed_header_at(block_store, height)
    except EvidenceVerificationError:
        # forward lunatic attack: fall back to our latest header — for the
        # attack to be provable, monotonic time must be violated, i.e. our
        # newest block must NOT be older than the conflicting one
        # (verify.go:70-84)
        latest = block_store.height
        sh = _signed_header_at(block_store, latest)
        if sh.header.time.unix_ns() < ev.conflicting_block.time.unix_ns():
            raise EvidenceVerificationError(
                f"latest block time {sh.header.time} is before conflicting "
                f"block time {ev.conflicting_block.time}"
            )
        return sh
