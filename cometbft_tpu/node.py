"""Node assembly: wire every subsystem into a runnable node
(reference: node/node.go:315-595 NewNodeWithCliParams + node/setup.go).

Wiring order follows the reference: DBs → state load → proxy app
(4 ABCI connections) → EventBus → privval → ABCI handshake/replay →
mempool (+ reactor) → evidence pool (+ reactor) → BlockExecutor →
blocksync reactor → consensus state/reactor → statesync reactor →
transport + switch.  start() then listens, starts the switch, dials
persistent peers, and kicks off statesync when enabled
(node.go:598 OnStart, setup.go:569 startStateSync).
"""

from __future__ import annotations

import os

from .abci.kvstore import KVStoreApplication, default_lanes
from .config import Config
from .consensus.config import ConsensusConfig
from .consensus.reactor import ConsensusReactor
from .consensus.replay import Handshaker
from .consensus.state import ConsensusState
from .evidence import EvidencePool, EvidenceReactor
from .blocksync import BlocksyncReactor
from .light import BlockStoreProvider, TrustOptions
from .mempool import CListMempool, MempoolReactor
from .mempool import MempoolConfig as MemCfg
from .p2p.key import NodeKey
from .p2p.node_info import NodeInfo
from .p2p.switch import Switch
from .p2p.transport import TCPTransport
from .privval import FilePV
from .proxy import local_client_creator, new_app_conns, remote_client_creator
from .state.execution import BlockExecutor
from .state.state import make_genesis_state
from .state.store import StateStore
from .statesync import LightClientStateProvider, StatesyncReactor
from .store.block_store import BlockStore
from .store.db import DB, MemDB, PrefixDB, SQLiteDB
from .types.event_bus import EventBus
from .types.genesis import GenesisDoc
from .utils.log import get_logger


def _strip_tcp(addr: str) -> str:
    return addr[len("tcp://"):] if addr.startswith("tcp://") else addr


def default_db_provider(cfg: Config) -> DB:
    """config/db.go DefaultDBProvider."""
    if cfg.base.db_backend == "memdb":
        return MemDB()
    os.makedirs(cfg.db_dir(), exist_ok=True)
    if cfg.base.db_backend == "native":
        from .store.native_db import NativeDB

        return NativeDB(os.path.join(cfg.db_dir(), "cometbft.kvlog"))
    return SQLiteDB(os.path.join(cfg.db_dir(), "cometbft.db"))


def _companion_server(laddr: str, **components):
    """Companion-service server for a listen address: grpc:// picks the
    real gRPC transport, anything else the varint-framed socket one."""
    if laddr.startswith("grpc://"):
        from .rpc.grpc_services import GrpcCompanionServer

        return GrpcCompanionServer(laddr[len("grpc://"):], **components)
    from .rpc.services import CompanionServiceServer

    return CompanionServiceServer(_strip_tcp(laddr), **components)


def make_app(cfg: Config):
    """The in-process demo apps, or a socket client creator for an
    external app (proxy/client.go DefaultClientCreator)."""
    pa = cfg.base.proxy_app
    snap = cfg.base.app_snapshot_interval
    if pa == "kvstore":
        return local_client_creator(
            KVStoreApplication(lanes=default_lanes(), snapshot_interval=snap)
        )
    if pa == "kvstore-merkle":
        # Merkle-committed state: app_hash is a root over the kv pairs and
        # Query(prove=True) serves ValueOp proofs the light client can
        # verify end-to-end (light/rpc.py abci_query)
        return local_client_creator(
            KVStoreApplication(
                lanes=default_lanes(), snapshot_interval=snap, merkle_state=True
            )
        )
    if pa == "noop":
        from .abci.types import BaseApplication

        return local_client_creator(BaseApplication())
    if pa.startswith("grpc://"):
        from .abci.grpc_transport import grpc_client_creator

        return grpc_client_creator(pa)
    return remote_client_creator(_strip_tcp(pa))


class Node:
    """A full node (node/node.go:91)."""

    def __init__(
        self,
        config: Config,
        genesis: GenesisDoc | None = None,
        client_creator=None,
        db: DB | None = None,
        metrics_hub=None,
    ):
        # metrics_hub: optional per-node utils/metrics.Hub so multiple
        # in-process Nodes (tests/tools) keep separate registries; None =
        # the process-global hub (one node per process, the e2e layout —
        # the reference scopes metrics per node via its provider fn too,
        # node.go DefaultMetricsProvider)
        self._metrics_hub = metrics_hub
        self.config = config
        self.logger = get_logger("node")
        genesis = genesis or GenesisDoc.load(config.genesis_file())
        self.genesis = genesis

        # ---- storage (setup.go:165 initDBs)
        self.db = db if db is not None else default_db_provider(config)
        self.block_store = BlockStore(PrefixDB(self.db, b"bs/"))
        self.state_store = StateStore(PrefixDB(self.db, b"ss/"))

        state = self.state_store.load()
        if state is None:
            state = make_genesis_state(genesis)
            self.state_store.bootstrap(state)
        self.state = state

        # ---- ABCI app, 4 named connections (setup.go:179)
        self.app_conns = new_app_conns(client_creator or make_app(config))
        self.app_conns.start()

        # ---- event bus + indexers (setup.go:188,197)
        self.event_bus = EventBus()
        from .indexer import (
            BlockIndexer,
            IndexerService,
            NullBlockIndexer,
            NullTxIndexer,
            TxIndexer,
        )

        if config.base.tx_index == "kv":
            self.tx_indexer = TxIndexer(PrefixDB(self.db, b"txi/"))
            self.block_indexer = BlockIndexer(PrefixDB(self.db, b"bli/"))
        elif config.base.tx_index == "psql":
            from .indexer.sink import (
                BlockSinkAdapter,
                SQLEventSink,
                TxSinkAdapter,
            )

            sink = SQLEventSink.from_conn_string(
                config.base.psql_conn, self.genesis.chain_id
            )
            self.tx_indexer = TxSinkAdapter(sink)
            self.block_indexer = BlockSinkAdapter(sink)
        else:
            self.tx_indexer = NullTxIndexer()
            self.block_indexer = NullBlockIndexer()
        self.indexer_service = IndexerService(
            self.tx_indexer, self.block_indexer, self.event_bus
        )

        # ---- node identity (also the privval listener's conn identity)
        self.node_key = NodeKey.load_or_gen(config.node_key_file())

        # ---- privval (node.go:388): file-based, or a remote signer
        # dialing into priv_validator_laddr
        self.signer_endpoint = None
        if config.base.priv_validator_laddr:
            from .privval import (
                RetrySignerClient,
                SignerClient,
                SignerListenerEndpoint,
            )

            laddr = _strip_tcp(config.base.priv_validator_laddr)
            self.signer_endpoint = SignerListenerEndpoint(
                laddr, identity_key=self.node_key.priv_key
            )
            self.logger.info(
                f"waiting for remote signer on {self.signer_endpoint.listen_addr}"
            )
            if not self.signer_endpoint.wait_for_signer(30.0):
                raise RuntimeError("remote signer never connected")
            self.priv_validator = RetrySignerClient(
                SignerClient(self.signer_endpoint, genesis.chain_id)
            )
        else:
            self.priv_validator = FilePV.load_or_generate(
                config.priv_validator_key_file(),
                config.priv_validator_state_file(),
            )

        # ---- statesync decision (node.go:403): enabled + fresh node only
        self.statesync_enabled = (
            config.statesync.enable and state.last_block_height == 0
        )

        # ---- ABCI handshake / replay (setup.go:229) — skipped when state
        # sync will bootstrap the app instead
        if not self.statesync_enabled:
            Handshaker(
                self.state_store,
                state,
                self.block_store,
                genesis,
                event_bus=self.event_bus,
            ).handshake(self.app_conns)

        # ---- mempool + reactor (setup.go:277)
        mp_cfg = MemCfg(
            size=config.mempool.size,
            max_tx_bytes=config.mempool.max_tx_bytes,
            max_txs_bytes=config.mempool.max_txs_bytes,
            cache_size=config.mempool.cache_size,
            keep_invalid_txs_in_cache=config.mempool.keep_invalid_txs_in_cache,
            recheck=config.mempool.recheck,
            broadcast=config.mempool.broadcast,
        )
        lane_info = self._lane_info()
        self.mempool = CListMempool(
            mp_cfg,
            self.app_conns.mempool,
            height=state.last_block_height,
            lane_priorities=lane_info[0],
            default_lane=lane_info[1],
        )
        # gossip stays closed until blocksync/statesync hand off
        wait_sync = config.base.block_sync or self.statesync_enabled
        self.mempool_reactor = MempoolReactor(self.mempool, wait_sync=wait_sync)

        # ---- evidence (node.go:441)
        self.evidence_pool = EvidencePool(
            PrefixDB(self.db, b"ev/"), self.state_store, self.block_store
        )
        self.evidence_reactor = EvidenceReactor(self.evidence_pool)

        # ---- background pruner (node.go pruner wiring)
        from .state.pruner import Pruner

        self.pruner = Pruner(
            PrefixDB(self.db, b"pr/"),
            self.state_store,
            self.block_store,
            tx_indexer=self.tx_indexer,
            block_indexer=self.block_indexer,
        )

        # ---- executor (node.go:458)
        self.block_executor = BlockExecutor(
            self.state_store,
            self.app_conns.consensus,
            self.mempool,
            ev_pool=self.evidence_pool,
            block_store=self.block_store,
            event_bus=self.event_bus,
            pruner=self.pruner,
        )

        # ---- blocksync reactor (node.go:478)
        local_addr = (
            self.priv_validator.key.priv_key.pub_key().address()
            if self.priv_validator
            else b""
        )
        self.blocksync_reactor = BlocksyncReactor(
            state,
            self.block_executor,
            self.block_store,
            block_sync=config.base.block_sync and not self.statesync_enabled,
            local_addr=local_addr,
        )

        # ---- consensus (node.go:486)
        cs_cfg = config.consensus
        if isinstance(cs_cfg, ConsensusConfig) and cs_cfg.wal_path:
            cs_cfg.wal_path = config.wal_file()
        self.consensus_state = ConsensusState(
            cs_cfg,
            state,
            self.block_executor,
            self.block_store,
            self.mempool,
            ev_pool=self.evidence_pool,
            event_bus=self.event_bus,
        )
        self.consensus_state.set_priv_validator(self.priv_validator)
        self.consensus_reactor = ConsensusReactor(
            self.consensus_state,
            wait_sync=config.base.block_sync or self.statesync_enabled,
        )

        # ---- statesync reactor (node.go:527)
        state_provider = None
        if self.statesync_enabled:
            state_provider = self._make_state_provider()
        self.statesync_reactor = StatesyncReactor(
            self.app_conns.snapshot,
            self.app_conns.query,
            state_provider=state_provider,
            enabled=self.statesync_enabled,
        )

        # ---- transport + switch (setup.go:411,485)
        self.node_info = NodeInfo(
            node_id=self.node_key.id(),
            listen_addr=config.p2p.laddr,
            network=genesis.chain_id,
            moniker=config.base.moniker,
        )
        self.transport = TCPTransport(self.node_key, self.node_info)
        self.switch = Switch(
            self.transport,
            send_rate=config.p2p.send_rate,
            recv_rate=config.p2p.recv_rate,
        )
        self.switch.add_reactor("MEMPOOL", self.mempool_reactor)
        self.switch.add_reactor("BLOCKSYNC", self.blocksync_reactor)
        self.switch.add_reactor("CONSENSUS", self.consensus_reactor)
        self.switch.add_reactor("EVIDENCE", self.evidence_reactor)
        self.switch.add_reactor("STATESYNC", self.statesync_reactor)

        # ---- PEX + address book (setup.go:547)
        self.pex_reactor = None
        if config.p2p.pex:
            from .p2p.pex import AddrBook, PexReactor

            self.addr_book = AddrBook(config._abs(config.p2p.addr_book_file))
            for addr in (config.p2p.seeds or "").split(","):
                if addr.strip():
                    self.addr_book.add_address(addr.strip(), src="config")
            for addr in (config.p2p.persistent_peers or "").split(","):
                if addr.strip():
                    self.addr_book.add_address(addr.strip(), src="config")
            self.pex_reactor = PexReactor(
                self.addr_book,
                seed_mode=config.p2p.seed_mode,
                target_outbound=config.p2p.max_num_outbound_peers,
            )
            self.switch.add_reactor("PEX", self.pex_reactor)

        self.listen_addr: str | None = None
        self.rpc_server = None  # attached by start() when configured
        self.companion_server = None
        self.companion_privileged_server = None

        # ---- metrics (node.go:983 Prometheus server; metricsgen sets)
        from .utils.metrics import NodeMetrics, Registry

        # the hub's registry carries the per-package call-site metrics
        # (consensus rounds, mempool rejects, p2p stream bytes, store
        # latencies — utils/metrics.Hub); node-level gauges join it so
        # /metrics exposes one coherent set
        from .utils.metrics import hub as _metrics_hub

        _h = self._metrics_hub if self._metrics_hub is not None else _metrics_hub()
        self.metrics_registry = _h.registry
        if getattr(_h, "node_metrics", None) is None:
            _h.node_metrics = NodeMetrics(self.metrics_registry)
        self.metrics = _h.node_metrics
        self._metrics_httpd = None
        self._pprof_httpd = None

    # ---------------------------------------------------------------- util

    def _lane_info(self):
        from .wire import abci_pb

        try:
            info = self.app_conns.query.info(abci_pb.InfoRequest())
            lanes = {e.key: e.value for e in (info.lane_priorities or [])}
            if lanes:
                return lanes, info.default_lane
        except Exception as e:  # noqa: BLE001
            self.logger.error(f"failed to fetch lane info: {e}")
        return None, ""

    def _make_state_provider(self):
        sscfg = self.config.statesync
        # the local stores are empty; providers must be remote: the
        # configured rpc_servers become light HTTP providers
        # (statesync/stateprovider.go:58 rpcClient per server); tests may
        # inject in-process providers via `state_providers`
        providers = getattr(self, "state_providers", None)
        if not providers and sscfg.rpc_servers:
            from .light.rpc import HTTPProvider
            from .rpc.client import HTTPClient

            providers = [
                HTTPProvider(self.genesis.chain_id, HTTPClient(addr.strip()))
                for addr in sscfg.rpc_servers.split(",")
                if addr.strip()
            ]
        if not providers:
            providers = [
                BlockStoreProvider(
                    self.genesis.chain_id, self.block_store, self.state_store
                )
            ]
        return LightClientStateProvider(
            self.genesis.chain_id,
            self.genesis.initial_height,
            providers[0],
            providers[1:],
            TrustOptions(
                period_ns=int(sscfg.trust_period * 1e9),
                height=sscfg.trust_height,
                hash=bytes.fromhex(sscfg.trust_hash),
            ),
        )

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """node.go:598 OnStart."""
        self.indexer_service.start()
        self.pruner.start()
        self.listen_addr = self.transport.listen(_strip_tcp(self.config.p2p.laddr))
        self.switch.start()
        peers = [
            p.strip()
            for p in self.config.p2p.persistent_peers.split(",")
            if p.strip()
        ]
        if peers:
            self.switch.dial_peers_async(peers, persistent=True)
        if self.statesync_enabled:
            self.statesync_reactor.run(
                self.state_store,
                self.block_store,
                discovery_time=self.config.statesync.discovery_time,
            )
        if self.config.rpc.laddr:
            try:
                from .rpc.server import RPCServer

                self.rpc_server = RPCServer(self)
                self.rpc_server.start(_strip_tcp(self.config.rpc.laddr))
            except ImportError:
                pass
        if self.config.rpc.companion_laddr:
            from . import __version__

            # public data services only — the pruner is deliberately not
            # handed to this listener (rpc/services.py privileged split).
            # grpc:// serves the reference's real gRPC services
            # (rpc/grpc_services.py); tcp:// keeps the socket framing.
            self.companion_server = _companion_server(
                self.config.rpc.companion_laddr,
                block_store=self.block_store,
                state_store=self.state_store,
                event_bus=self.event_bus,
                node_version=__version__,
            )
            self.companion_server.start()
        if self.config.rpc.companion_privileged_laddr:
            from . import __version__

            self.companion_privileged_server = _companion_server(
                self.config.rpc.companion_privileged_laddr,
                block_store=self.block_store,
                state_store=self.state_store,
                pruner=self.pruner,
                tx_indexer=self.tx_indexer,
                block_indexer=self.block_indexer,
                event_bus=self.event_bus,
                node_version=__version__,
                privileged=True,
            )
            self.companion_privileged_server.start()
        if self.pex_reactor is not None:
            self.addr_book.save()
        self._start_metrics()
        # health sentinel (utils/healthmon): knob-gated; off keeps every
        # healthmon.beat() call in the loops a zero-overhead no-op
        from .utils import healthmon as _healthmon

        self._healthmon = _healthmon.maybe_start()
        if self._healthmon is not None:
            self.logger.info(
                "health sentinel on: probe every "
                f"{self._healthmon.probe_period_s:g}s, /tpu_health serving"
            )
        # verify service: start the scheduler (and with it the failover
        # watchdog) NOW, not lazily on first submit — a device that
        # wedges while the node is verify-idle must already be tripped
        # to CPU fallback when the first commit/CheckTx batch arrives,
        # not strand it and only then notice.  Same for a configured
        # remote plane: the breaker's dial/probe loop should already
        # know whether the plane is reachable before the first batch.
        from .crypto import batch as _crypto_batch
        from .verifysvc.service import remote_plane_configured

        if _crypto_batch.device_capable() or remote_plane_configured():
            from .verifysvc.service import global_service

            global_service()._ensure_started()
        self.logger.info(
            f"node {self.node_key.id()[:8]} started: p2p {self.listen_addr}"
        )

    def _start_metrics(self) -> None:
        """Event-fed + sampled metrics, optionally served on the
        Prometheus listener (node.go:983)."""
        import threading
        import time as _time

        from .types import validation as _validation
        from .types.event_bus import EventQueryNewBlock

        # the hook is process-global: install at start, clear at stop so
        # multi-node processes don't cross-pollinate registries
        self._verify_observer = self.metrics.verify_commit_seconds.observe
        _validation.VERIFY_LATENCY_OBSERVER = self._verify_observer
        sub = self.event_bus.subscribe("metrics", EventQueryNewBlock)
        last_block_time = [None]

        from .utils import healthmon as _healthmon

        def pump():
            import queue as _q

            while self.switch.is_running():
                _healthmon.beat("metrics-pump")
                try:
                    msg, _ = sub.get(timeout=0.5)
                except _q.Empty:
                    continue
                blk = msg.data["block"]
                m = self.metrics
                m.consensus_height.set(blk.header.height)
                m.consensus_num_txs.set(len(blk.data.txs))
                m.consensus_total_txs.inc(len(blk.data.txs))
                m.consensus_validators.set(
                    self.consensus_state.state.validators.size()
                )
                t = blk.header.time.unix_ns()
                if last_block_time[0] is not None:
                    m.consensus_block_interval.observe(
                        (t - last_block_time[0]) / 1e9
                    )
                last_block_time[0] = t
            _healthmon.retire("metrics-pump")

        def sample():
            while self.switch.is_running():
                _healthmon.beat("metrics-sample")
                self.metrics.mempool_size.set(self.mempool.size())
                self.metrics.mempool_size_bytes.set(self.mempool.size_bytes())
                self.metrics.p2p_peers.set(self.switch.num_peers())
                rs = self.consensus_state.get_round_state()
                self.metrics.consensus_rounds.set(max(rs.round, 0))
                _time.sleep(2.0)
            _healthmon.retire("metrics-sample")

        threading.Thread(target=pump, daemon=True, name="metrics-pump").start()
        threading.Thread(target=sample, daemon=True, name="metrics-sample").start()

        if self.config.instrumentation.prometheus:
            from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

            registry = self.metrics_registry

            class H(BaseHTTPRequestHandler):
                def log_message(self, *a):
                    pass

                def do_GET(self):
                    body = registry.expose_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            addr = self.config.instrumentation.prometheus_listen_addr
            host, _, port = addr.rpartition(":")
            self._metrics_httpd = ThreadingHTTPServer(
                (host or "0.0.0.0", int(port)), H
            )
            threading.Thread(
                target=self._metrics_httpd.serve_forever,
                daemon=True,
                name="prometheus",
            ).start()
            self.logger.info(f"Prometheus metrics on {addr}")

        if self.config.instrumentation.pprof_laddr:
            # separate opt-in profiling listener (node.go:1004-1018 puts
            # pprof on its own pprof_laddr, never the metrics port)
            from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

            from .utils.debugdump import heap_summary, thread_dump

            class P(BaseHTTPRequestHandler):
                def log_message(self, *a):
                    pass

                def do_GET(self):
                    if self.path.startswith("/debug/threads"):
                        body = thread_dump().encode()
                    elif self.path.startswith("/debug/heap"):
                        body = heap_summary().encode()
                    else:
                        body = b"endpoints: /debug/threads /debug/heap\n"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            paddr = self.config.instrumentation.pprof_laddr
            phost, _, pport = paddr.rpartition(":")
            try:
                self._pprof_httpd = ThreadingHTTPServer(
                    (phost or "127.0.0.1", int(pport)), P
                )
            except OSError as e:
                # an observability endpoint must never take down the
                # node: a restarted node's configured pprof port can be
                # transiently held by an ephemeral outbound socket
                self.logger.error(f"pprof endpoint unavailable ({paddr}): {e}")
                self._pprof_httpd = None
            else:
                threading.Thread(
                    target=self._pprof_httpd.serve_forever,
                    daemon=True,
                    name="pprof",
                ).start()
                self.logger.info(f"debug/profiling endpoints on {paddr}")

    def _stop_quietly(self, label: str, fn) -> None:
        """Shutdown must reach every subsystem even when one of them
        fails to die cleanly — but a failure is a leak suspect (socket,
        thread, fd), never silent."""
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — keep tearing down the rest
            self.logger.warning(f"{label} shutdown failed: {e!r}")

    def stop(self) -> None:
        from .types import validation as _validation

        if getattr(self, "_healthmon", None) is not None:
            from .utils import healthmon as _healthmon

            self._stop_quietly("health sentinel", _healthmon.uninstall)
            self._healthmon = None
        if _validation.VERIFY_LATENCY_OBSERVER is getattr(
            self, "_verify_observer", None
        ):
            _validation.VERIFY_LATENCY_OBSERVER = None
        if self._metrics_httpd is not None:
            self._stop_quietly("metrics httpd", self._metrics_httpd.shutdown)
            self._stop_quietly("metrics httpd", self._metrics_httpd.server_close)
        if self._pprof_httpd is not None:
            self._stop_quietly("pprof httpd", self._pprof_httpd.shutdown)
            self._stop_quietly("pprof httpd", self._pprof_httpd.server_close)
        if self.rpc_server is not None:
            self._stop_quietly("rpc server", self.rpc_server.stop)
        if self.companion_server is not None:
            self._stop_quietly("companion server", self.companion_server.stop)
        if self.companion_privileged_server is not None:
            self._stop_quietly(
                "companion privileged server",
                self.companion_privileged_server.stop,
            )
        self._stop_quietly("switch", self.switch.stop)
        if self.indexer_service.is_running():
            self._stop_quietly("indexer service", self.indexer_service.stop)
        if self.pruner.is_running():
            self._stop_quietly("pruner", self.pruner.stop)
        if self.signer_endpoint is not None:
            self._stop_quietly("signer endpoint", self.signer_endpoint.close)
        if self.pex_reactor is not None:
            # keep PEX-learned peers for restart
            self._stop_quietly("addr book save", self.addr_book.save)
        self._stop_quietly("abci connections", self.app_conns.stop)

    def is_running(self) -> bool:
        return self.switch.is_running()


class InspectNode:
    """A crippled node serving RPC straight off the stores — consensus
    never runs (reference: internal/inspect; `cometbft inspect`).  For
    post-mortem debugging of a halted chain."""

    def __init__(self, config: Config):
        self.config = config
        self.logger = get_logger("inspect")
        self.genesis = GenesisDoc.load(config.genesis_file())
        self.db = default_db_provider(config)
        self.block_store = BlockStore(PrefixDB(self.db, b"bs/"))
        self.state_store = StateStore(PrefixDB(self.db, b"ss/"))
        from .indexer import BlockIndexer, TxIndexer

        self.tx_indexer = TxIndexer(PrefixDB(self.db, b"txi/"))
        self.block_indexer = BlockIndexer(PrefixDB(self.db, b"bli/"))
        state = self.state_store.load()
        if state is None:
            raise RuntimeError("no state to inspect")

        # the shims Environment dereferences
        class _CS:
            pass

        self.consensus_state = _CS()
        self.consensus_state.state = state

        class _Reactor:
            wait_sync = False

        self.consensus_reactor = _Reactor()

        class _Pool:
            @staticmethod
            def is_running():
                return False

        class _BS:
            pool = _Pool()

        self.blocksync_reactor = _BS()
        from .mempool import NopMempool

        self.mempool = NopMempool()
        self.event_bus = EventBus()
        self.node_key = NodeKey.load_or_gen(config.node_key_file())
        self.node_info = NodeInfo(
            node_id=self.node_key.id(),
            network=self.genesis.chain_id,
            moniker=config.base.moniker,
        )
        self.priv_validator = None

        class _Peers:
            @staticmethod
            def list():
                return []

        class _Switch:
            peers = _Peers()

            @staticmethod
            def is_running():
                return False

        self.switch = _Switch()
        self.listen_addr = None
        self.app_conns = None  # abci_* endpoints will error: no app here
        self.rpc_server = None

    def start(self) -> None:
        from .rpc.server import RPCServer

        self.rpc_server = RPCServer(self)
        self.rpc_server.start(_strip_tcp(self.config.rpc.laddr))

    def stop(self) -> None:
        if self.rpc_server is not None:
            self.rpc_server.stop()
        self.db.close()
