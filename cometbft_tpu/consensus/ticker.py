"""Timeout scheduling (reference: internal/consensus/ticker.go).

One outstanding timeout at a time: scheduling a newer (H,R,S) replaces
the pending one; stale timeouts (older than the current round state) are
never delivered.  Fired timeouts are posted to the state machine's queue
as TimeoutInfo.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round: int
    step: int


class TimeoutTicker:
    """threading.Timer-backed ticker (ticker.go timeoutTicker)."""

    def __init__(self, fire: Callable[[TimeoutInfo], None]):
        self._fire = fire
        self._timer: threading.Timer | None = None
        self._pending: TimeoutInfo | None = None
        self._mtx = threading.Lock()
        self._stopped = False

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace any pending timeout with this one (ticker.go
        ScheduleTimeout; newer round states always win)."""
        with self._mtx:
            if self._stopped:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._pending = ti
            self._timer = threading.Timer(ti.duration, self._on_fire, args=(ti,))
            self._timer.daemon = True
            self._timer.start()

    def _on_fire(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if self._stopped or self._pending is not ti:
                return  # replaced meanwhile
            self._pending = None
        self._fire(ti)

    def stop(self) -> None:
        with self._mtx:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._pending = None
