"""Timeout scheduling (reference: internal/consensus/ticker.go).

One outstanding timeout at a time: scheduling a newer (H,R,S) replaces
the pending one; stale timeouts (older than the current round state) are
never delivered.  Fired timeouts are posted to the state machine's queue
as TimeoutInfo.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable

from ..utils import tracing
from ..utils.metrics import hub as _mhub


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round: int
    step: int


def _should_skip(new: TimeoutInfo, pending: TimeoutInfo) -> bool:
    """(ticker.go:130 shouldSkipTick) — new is older than, or a
    duplicate of, the pending timeout."""
    if new.height < pending.height:
        return True
    return new.height == pending.height and (
        new.round < pending.round
        or (
            new.round == pending.round
            and pending.step > 0
            and new.step <= pending.step
        )
    )


class TimeoutTicker:
    """threading.Timer-backed ticker (ticker.go timeoutTicker)."""

    def __init__(self, fire: Callable[[TimeoutInfo], None]):
        self._fire = fire
        self._timer: threading.Timer | None = None
        self._pending: TimeoutInfo | None = None
        self._last_fired: TimeoutInfo | None = None
        self._mtx = threading.Lock()
        self._stopped = False

    def _arm_locked(self, ti: TimeoutInfo) -> None:
        self._pending = ti
        self._timer = threading.Timer(ti.duration, self._on_fire, args=(ti,))
        self._timer.daemon = True
        self._timer.start()

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace any pending timeout with a NEWER one (ticker.go
        ScheduleTimeout + shouldSkipTick): an older or duplicate (H,R,S)
        never clobbers the armed timer.  Without this rule a delayed
        schedule for an earlier step cancels the live timer, the
        replacement is then dropped as stale by the state machine, and
        the round wedges with nothing pending — the evaporating-timeout
        class behind the liveness-watchdog fires."""
        with self._mtx:
            if self._stopped:
                return
            if self._pending is not None and _should_skip(ti, self._pending):
                return
            # Post-fire skip (reference timeoutRoutine: `ti` keeps the
            # LAST timeout as the shouldSkipTick comparison point even
            # after it fires, ticker.go:171-183): with nothing pending, a
            # schedule that is older than — or a duplicate of — the
            # timeout that just fired is a stale tick from before the
            # state machine advanced; re-arming it would deliver a
            # timeout the machine then drops as stale, leaving the round
            # with a cancelled real timer.  Only the watchdog may re-arm
            # a duplicate, via schedule_if_idle below.
            if (
                self._pending is None
                and self._last_fired is not None
                and _should_skip(ti, self._last_fired)
            ):
                return
            if self._timer is not None:
                self._timer.cancel()
            self._arm_locked(ti)

    def schedule_if_idle(self, ti: TimeoutInfo) -> bool:
        """Schedule ONLY when no timeout is pending.  Used by the liveness
        watchdog: an unconditional schedule() could replace a legitimate
        newer timeout the machine armed between the watchdog's idle sample
        and its re-kick, and the replacement (carrying the watchdog's stale
        (H,R,S)) would then be dropped as stale — cancelling the real
        timer.  The check and the arm happen under one lock so that window
        does not exist.  Deliberately bypasses the post-fire duplicate
        skip in schedule(): the watchdog's whole job is re-arming the
        exact (H,R,S) whose delivery evaporated."""
        with self._mtx:
            if self._stopped or self._pending is not None:
                return False
            self._arm_locked(ti)
            return True

    def _on_fire(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if self._stopped or self._pending is not ti:
                return  # replaced meanwhile
            self._pending = None
            self._last_fired = ti  # stays the skip reference while idle
        _mhub().cs_timeout_fired.inc(step=str(ti.step))
        if tracing.enabled():
            tracing.instant(
                "cs.timeout_fire",
                {"height": ti.height, "round": ti.round, "step": ti.step},
            )
        self._fire(ti)

    def stop(self) -> None:
        with self._mtx:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._pending = None
            self._last_fired = None
