"""Timeout scheduling (reference: internal/consensus/ticker.go).

One outstanding timeout at a time: scheduling a newer (H,R,S) replaces
the pending one; stale timeouts (older than the current round state) are
never delivered.  Fired timeouts are posted to the state machine's queue
as TimeoutInfo.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round: int
    step: int


class TimeoutTicker:
    """threading.Timer-backed ticker (ticker.go timeoutTicker)."""

    def __init__(self, fire: Callable[[TimeoutInfo], None]):
        self._fire = fire
        self._timer: threading.Timer | None = None
        self._pending: TimeoutInfo | None = None
        self._mtx = threading.Lock()
        self._stopped = False

    def _arm_locked(self, ti: TimeoutInfo) -> None:
        self._pending = ti
        self._timer = threading.Timer(ti.duration, self._on_fire, args=(ti,))
        self._timer.daemon = True
        self._timer.start()

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace any pending timeout with this one (ticker.go
        ScheduleTimeout; newer round states always win)."""
        with self._mtx:
            if self._stopped:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._arm_locked(ti)

    def schedule_if_idle(self, ti: TimeoutInfo) -> bool:
        """Schedule ONLY when no timeout is pending.  Used by the liveness
        watchdog: an unconditional schedule() could replace a legitimate
        newer timeout the machine armed between the watchdog's idle sample
        and its re-kick, and the replacement (carrying the watchdog's stale
        (H,R,S)) would then be dropped as stale — cancelling the real
        timer.  The check and the arm happen under one lock so that window
        does not exist."""
        with self._mtx:
            if self._stopped or self._pending is not None:
                return False
            self._arm_locked(ti)
            return True

    def _on_fire(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if self._stopped or self._pending is not ti:
                return  # replaced meanwhile
            self._pending = None
        self._fire(ti)

    def stop(self) -> None:
        with self._mtx:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._pending = None
