"""The Tendermint consensus state machine (reference:
internal/consensus/state.go, 2,792 LoC).

One worker thread (receive_routine, state.go:795) serializes every input
— peer messages, our own internally-routed proposals/votes, and timeouts
— and every input is WAL-logged before it mutates state (state.go:839).
Round flow: NewRound → Propose → Prevote → (PrevoteWait) → Precommit →
(PrecommitWait) → Commit; on +2/3 precommits finalize_commit saves the
block, fsyncs EndHeight into the WAL, applies the block through the
executor (whose LastCommit verification is the TPU hot path next height)
and schedules round 0 of the next height.
"""

from __future__ import annotations

import copy
import queue
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..state.execution import BlockExecutor
from ..state.state import State as SMState
from ..types import event_bus as events
from ..types.block import Block, BlockID, Commit
from ..types.part_set import Part, PartSet
from ..types.proposal import Proposal
from ..types.validators import ValidatorSet
from ..types.vote import Vote, VoteError
from ..types.vote_set import ErrVoteConflictingVotes, VoteSet
from ..utils import healthmon, tracing
from ..utils.flightrec import recorder as _flightrec
from ..utils.heightline import registry as _heightline
from ..utils.log import get_logger
from ..utils.service import Service
from ..verifysvc.service import Klass as _VerifyKlass
from ..wire import wal_pb
from ..wire.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE, Timestamp
from .config import ConsensusConfig
from .ticker import TimeoutInfo, TimeoutTicker
from .types import (
    STEP_COMMIT,
    STEP_NEW_HEIGHT,
    STEP_NEW_ROUND,
    STEP_PRECOMMIT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
    HeightVoteSet,
    RoundState,
)
from .wal import WAL, NilWAL, WALSearchOptions

_NS = 1_000_000_000


# ------------------------------------------------------------ queue items


@dataclass
class ProposalMessage:
    proposal: Proposal


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part


@dataclass
class VoteMessage:
    vote: Vote
    # chaos only (utils/fail `double_sign`): send to every peer without
    # consulting/updating the has-vote gossip bookkeeping.  Vote gossip
    # dedups by validator INDEX, so an equivocating pair from one node
    # would otherwise have its second vote suppressed at the send seam
    # and no honest vote set would ever hold both — a byzantine sender
    # doesn't honor gossip etiquette, and neither does the injection.
    bypass_gossip_dedup: bool = False


@dataclass
class MsgInfo:
    msg: object
    peer_id: str  # "" = internal
    receive_time_ns: int = 0


class ConsensusError(Exception):
    pass


class ConsensusState(Service):
    """internal/consensus/state.go State."""

    def __init__(
        self,
        config: ConsensusConfig,
        state: SMState,
        block_exec: BlockExecutor,
        block_store,
        tx_notifier,  # mempool (txs_available / enable_txs_available)
        ev_pool=None,
        wal=None,
        event_bus=None,
    ):
        super().__init__("ConsensusState")
        self.config = config
        self.block_exec = block_exec
        self.block_store = block_store
        self.tx_notifier = tx_notifier
        self.ev_pool = ev_pool
        self.event_bus = event_bus or events.NopEventBus()
        self.wal = wal or NilWAL()
        self.logger = get_logger("consensus")

        self.priv_validator = None
        self.priv_validator_pub_key = None

        self.rs = RoundState()
        self.state = None  # set by update_to_state

        self._queue: queue.Queue[MsgInfo | TimeoutInfo] = queue.Queue(maxsize=1000)
        self._ticker = TimeoutTicker(self._enqueue_timeout)
        self._thread: threading.Thread | None = None
        self._mtx = threading.RLock()
        self._replay_mode = False

        # hooks for tests/reactor: called with (vote) / (proposal) / (part)
        self.on_new_round_step = lambda rs: None
        self.decide_proposal_hook = None  # override for byzantine tests
        # reactor seam: own proposals/votes/parts that must reach peers
        self.broadcast_hook = None  # Callable[[object], None] | None
        # reactor seam: fired for every vote added to our sets (HasVote)
        self.has_vote_hook = None  # Callable[[Vote], None] | None
        self.new_valid_block_hook = None  # Callable[[RoundState, bool], None]

        self.update_to_state(state)

    # ------------------------------------------------------ wiring helpers

    def set_priv_validator(self, pv) -> None:
        with self._mtx:
            self.priv_validator = pv
            if pv is not None:
                self.priv_validator_pub_key = pv.get_pub_key()

    # ---------------------------------------------------------- lifecycle

    def on_start(self) -> None:
        if isinstance(self.wal, NilWAL) and self.config.wal_path:
            self.wal = WAL(self.config.wal_path)
        if isinstance(self.wal, WAL):
            self.wal.start()
            self._catchup_replay(self.rs.height)
        self._thread = threading.Thread(
            target=self._receive_routine, name="cs-receive", daemon=True
        )
        self._thread.start()
        self._schedule_round0(self.rs)
        threading.Thread(
            target=self._watchdog_routine, name="cs-watchdog", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._ticker.stop()
        self._enqueue(None)  # wake the routine so it can exit
        if self._thread:
            self._thread.join(timeout=5)
        if isinstance(self.wal, WAL):
            self.wal.stop()

    # --------------------------------------------------------- public API

    def _enqueue(self, item) -> None:
        """Never block the caller (reactor threads): shed peer load when
        the machine is saturated rather than deadlocking."""
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            self.logger.error("consensus queue full; dropping input")

    def _enqueue_timeout(self, item) -> None:
        """Timeouts are control-plane and must NEVER be shed: a dropped
        round timeout leaves no pending timer and nothing scheduled — the
        machine wedges until peer input arrives (one of the evaporating-
        timeout paths behind the post-restart stalls).  The ticker thread
        may safely block until the receive loop drains the queue."""
        while self.is_running():
            try:
                self._queue.put(item, timeout=1.0)
                return
            except queue.Full:
                self.logger.error("consensus queue full; RETRYING timeout enqueue")

    def add_vote(self, vote: Vote, peer_id: str = "") -> None:
        self._enqueue(MsgInfo(VoteMessage(vote), peer_id, time.time_ns()))

    def set_proposal(self, proposal: Proposal, peer_id: str = "") -> None:
        self._enqueue(MsgInfo(ProposalMessage(proposal), peer_id, time.time_ns()))

    def add_proposal_block_part(
        self, height: int, round: int, part: Part, peer_id: str = ""
    ) -> None:
        self._enqueue(
            MsgInfo(BlockPartMessage(height, round, part), peer_id, time.time_ns())
        )

    def get_round_state(self) -> RoundState:
        """Shallow copy under lock (state.go GetRoundState): reactor gossip
        threads read height/round/parts while the consensus thread mutates
        them across height transitions; a live reference allows torn reads."""
        with self._mtx:
            return copy.copy(self.rs)

    def is_proposer(self) -> bool:
        with self._mtx:
            return (
                self.priv_validator_pub_key is not None
                and self.rs.validators is not None
                and self.rs.validators.get_proposer().address
                == self.priv_validator_pub_key.address()
            )

    # -------------------------------------------------------- state reset

    def update_to_state(self, state: SMState) -> None:
        """Prepare RoundState for state.last_block_height+1
        (state.go updateToState)."""
        with self._mtx:
            # the committed round's commit time anchors the next height's
            # start time (reference updateToState uses cs.CommitTime)
            commit_time = self.rs.commit_time_ns or time.time_ns()
            # last precommits become LastCommit for the next proposal
            last_precommits = None
            if (
                self.rs.commit_round > -1
                and self.rs.votes is not None
                and self.rs.height == state.last_block_height
            ):
                vs = self.rs.votes.precommits(self.rs.commit_round)
                if vs is not None and vs.has_two_thirds_majority():
                    last_precommits = vs

            height = state.last_block_height + 1
            if height == 1:
                height = state.initial_height

            validators = state.validators
            ext_enabled = state.consensus_params.feature.vote_extensions_enabled(height)

            self.rs = RoundState(
                height=height,
                round=0,
                step=STEP_NEW_HEIGHT,
                validators=validators.copy() if validators else None,
                votes=HeightVoteSet(
                    state.chain_id, height, validators, ext_enabled
                )
                if validators
                else None,
                commit_round=-1,
                last_commit=last_precommits,
                last_validators=state.last_validators.copy()
                if state.last_validators
                else None,
            )
            self.rs.start_time_ns = commit_time + state.next_block_delay_ns
            self.state = state

    # ------------------------------------------------------- WAL catchup

    def _catchup_replay(self, height: int) -> None:
        """Replay WAL records after EndHeight(height-1) into the machine
        (replay.go:97 catchupReplay)."""
        end = self.state.last_block_height
        recs = self.wal.search_for_end_height(
            end, WALSearchOptions(ignore_data_corruption_errors=True)
        )
        if recs is None:
            return
        self._replay_mode = True
        try:
            for rec in recs:
                self._replay_record(rec)
        finally:
            self._replay_mode = False
        self.logger.info(f"replayed {len(recs)} WAL records after height {end}")

    def _replay_record(self, rec: wal_pb.TimedWALMessageProto) -> None:
        m = rec.msg
        which = m.which()
        if which == "msg_info":
            mi = m.msg_info
            if mi.vote is not None:
                self._handle_msg(MsgInfo(VoteMessage(Vote.from_proto(mi.vote)), mi.peer_id))
            elif mi.proposal is not None:
                self._handle_msg(
                    MsgInfo(
                        ProposalMessage(Proposal.from_proto(mi.proposal)),
                        mi.peer_id,
                        mi.receive_time_ns,
                    )
                )
            elif mi.block_part is not None:
                self._handle_msg(
                    MsgInfo(
                        BlockPartMessage(
                            mi.block_part_height,
                            mi.block_part_round,
                            Part.from_proto(mi.block_part),
                        ),
                        mi.peer_id,
                    )
                )
        elif which == "timeout_info":
            ti = m.timeout_info
            self._handle_timeout(
                TimeoutInfo(ti.duration_ms / 1000.0, ti.height, ti.round, ti.step)
            )

    # ------------------------------------------------------ receive loop

    def _receive_routine(self) -> None:
        try:
            self._receive_loop()
        finally:
            healthmon.retire("cs-receive")

    def _receive_loop(self) -> None:
        while True:
            # bounded get, not a bare blocking one: the heartbeat must
            # tick while the machine idles, and go stale only while a
            # single input is stuck in processing (e.g. a VerifyCommit
            # against a wedged device) — exactly what the health
            # sentinel audits
            healthmon.beat("cs-receive")
            try:
                item = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:
                return
            try:
                if isinstance(item, TimeoutInfo):
                    self._wal_write_timeout(item)
                    with self._mtx:
                        self._handle_timeout(item)
                else:
                    self._wal_write_msg(item)
                    with self._mtx:
                        self._handle_msg(item)
            except (ConsensusError, VoteError, ValueError) as e:
                # malformed/adversarial peer input is a per-message error
                # (state.go:900 logs and continues); never a reason to halt
                self.logger.error(f"error handling consensus input: {e}")
            except Exception as e:  # noqa: BLE001 - halt, never sign wrongly
                self.logger.error(f"consensus failure: {e!r}")
                import traceback

                traceback.print_exc()
                # post-mortem: flight-recorder ring + thread dump to a
                # file before the state machine goes dark
                try:
                    from ..utils.debugdump import crash_report

                    path = crash_report(f"consensus failure: {e!r}")
                    self.logger.error(f"crash report written to {path}")
                except Exception as dump_err:  # noqa: BLE001 — never mask the cause
                    self.logger.warning(
                        f"crash report failed (original error {e!r} "
                        f"stands): {dump_err!r}"
                    )
                return

    def _wal_write_msg(self, mi: MsgInfo) -> None:
        if self._replay_mode:
            return
        msg = mi.msg
        p = wal_pb.MsgInfoProto(
            peer_id=mi.peer_id, receive_time_ns=mi.receive_time_ns
        )
        if isinstance(msg, VoteMessage):
            p.vote = msg.vote.to_proto()
        elif isinstance(msg, ProposalMessage):
            p.proposal = msg.proposal.to_proto()
        elif isinstance(msg, BlockPartMessage):
            p.block_part = msg.part.to_proto()
            p.block_part_height = msg.height
            p.block_part_round = msg.round
        rec = wal_pb.WALMessageProto(msg_info=p)
        if isinstance(msg, VoteMessage) and mi.peer_id == "":
            self.wal.write_sync(rec)  # our own votes: fsync before send
        else:
            self.wal.write(rec)

    def _wal_write_timeout(self, ti: TimeoutInfo) -> None:
        if self._replay_mode:
            return
        self.wal.write(
            wal_pb.WALMessageProto(
                timeout_info=wal_pb.TimeoutInfoProto(
                    duration_ms=int(ti.duration * 1000),
                    height=ti.height,
                    round=ti.round,
                    step=ti.step,
                )
            )
        )

    # ---------------------------------------------------------- handlers

    def _handle_msg(self, mi: MsgInfo) -> None:
        msg = mi.msg
        if isinstance(msg, ProposalMessage):
            self._set_proposal(msg.proposal, mi.receive_time_ns)
        elif isinstance(msg, BlockPartMessage):
            self._add_proposal_block_part(msg, mi.peer_id)
        elif isinstance(msg, VoteMessage):
            self._try_add_vote(msg.vote, mi.peer_id)
        else:
            self.logger.error(f"unknown msg type {type(msg)}")

    _WATCHDOG_INTERVAL = 10.0

    # Marker emitted on every watchdog re-kick.  The e2e runner greps node
    # logs for EXACTLY this token (e2e/runner.py check_watchdog_fires) —
    # a shared constant so the log wording and the checker can't drift.
    WATCHDOG_LOG_TOKEN = "consensus-watchdog-rekick"

    # Process-wide count of watchdog re-kicks.  The watchdog is a
    # backstop for already-fixed bug classes (timeout shedding, duplicate
    # blocksync handoff); a healthy machine NEVER needs it, so the test
    # suite asserts this stays zero (conftest fails any test that bumps
    # it) — matching the reference, which has no watchdog at all
    # (internal/consensus/state.go:795-884).
    watchdog_fire_count = 0

    def _watchdog_routine(self) -> None:
        """Liveness backstop: if the machine sits at the same (H, R, S)
        across two intervals with an EMPTY queue and NO pending timeout,
        every scheduled timeout has evaporated (the class of bug behind
        the post-restart stalls: stale-rs swaps, dropped ticker fires).
        Re-kick by scheduling the current step's timeout; steps that wait
        on peer input instead re-announce our round step so peers resend.
        Healthy nodes never trigger: progress, a pending timer, or queued
        input all reset the check."""
        kickable = (
            STEP_NEW_HEIGHT,
            STEP_NEW_ROUND,
            STEP_PROPOSE,
            STEP_PREVOTE_WAIT,
            STEP_PRECOMMIT_WAIT,
        )
        last = None
        stalled_checks = 0
        while self.is_running():
            healthmon.beat("cs-watchdog")
            time.sleep(self._WATCHDOG_INTERVAL)
            rs = self.rs
            cur = (rs.height, rs.round, rs.step)
            idle = (
                cur == last
                and self._ticker._pending is None
                and self._queue.empty()
            )
            # deliberate idle: waiting for txs before proposing
            # (create_empty_blocks=false) is not a stall
            waiting_for_txs = (
                rs.step == STEP_NEW_ROUND
                and not self.config.create_empty_blocks
                and self.tx_notifier is not None
                and self.tx_notifier.size() == 0
            )
            if idle and not waiting_for_txs and not self._replay_mode:
                stalled_checks += 1
                if stalled_checks >= 2:
                    # Re-read the round state at the last instant: the
                    # machine may have progressed since the idle samples.
                    # If it did, there is no stall — bail instead of
                    # kicking the CURRENT step (a 0.05 s re-kick of a
                    # just-entered propose step would time it out almost
                    # immediately) or counting a false fire.
                    with self._mtx:
                        rs = self.rs
                        cur = (rs.height, rs.round, rs.step)
                    if cur != last:
                        stalled_checks = 0
                        last = cur
                        continue
                    fired = False
                    if rs.step in kickable:
                        # schedule_if_idle never replaces a pending
                        # (legitimate) timeout armed in the window
                        fired = self._ticker.schedule_if_idle(
                            TimeoutInfo(0.05, rs.height, rs.round, rs.step)
                        )
                    elif self._queue.empty():
                        # waiting on votes/parts: re-announce so peers
                        # re-route what we're missing
                        self.on_new_round_step(rs)
                        fired = True
                    if fired:
                        ConsensusState.watchdog_fire_count += 1
                        _flightrec().record(
                            "watchdog", height=cur[0], round=cur[1], step=cur[2]
                        )
                        self.logger.error(
                            f"{self.WATCHDOG_LOG_TOKEN}: no progress at "
                            f"h={cur[0]} r={cur[1]} step={cur[2]}, "
                            "no pending timeout — re-kicked"
                        )
                    stalled_checks = 0
            else:
                stalled_checks = 0
            last = cur
        healthmon.retire("cs-watchdog")

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        rs = self.rs
        stale = ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < rs.step
        )
        if not self._replay_mode:
            _flightrec().record(
                "timeout",
                height=ti.height,
                round=ti.round,
                step=ti.step,
                duration_s=ti.duration,
                stale=stale,
            )
        if stale:
            return
        if ti.step == STEP_NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == STEP_NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == STEP_PROPOSE:
            self.event_bus.publish_timeout_propose(rs.round_state_event())
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == STEP_PREVOTE_WAIT:
            self.event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == STEP_PRECOMMIT_WAIT:
            self.event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise ConsensusError(f"invalid timeout step {ti.step}")

    # -------------------------------------------------------- round entry

    def _schedule_round0(self, rs: RoundState) -> None:
        sleep = max(0.0, (rs.start_time_ns - time.time_ns()) / _NS)
        self._ticker.schedule(TimeoutInfo(sleep, rs.height, 0, STEP_NEW_HEIGHT))

    def _update_round_step(self, round: int, step: int) -> None:
        self.rs.round = round
        self.rs.step = step
        if not self._replay_mode:
            # same guard as the event-bus publishes: WAL-replayed history
            # must not flood the post-mortem ring with stale entries
            _flightrec().record(
                "step", height=self.rs.height, round=round, step=step
            )
        if tracing.enabled():
            tracing.instant(
                "cs.step",
                {"height": self.rs.height, "round": round, "step": step},
            )
        ev = self.rs.round_state_event()
        if not self._replay_mode:
            self.event_bus.publish_new_round_step(ev)
        self.on_new_round_step(self.rs)

    def _enter_new_round(self, height: int, round: int) -> None:
        rs = self.rs
        if rs.height != height or round < rs.round or (
            rs.round == round and rs.step != STEP_NEW_HEIGHT
        ):
            return
        validators = rs.validators
        if rs.round < round:
            validators = validators.copy()
            validators.increment_proposer_priority(round - rs.round)
        from ..utils.metrics import hub as _mhub

        m = _mhub()
        now = time.monotonic()
        if getattr(self, "_round_started_at", None) is not None:
            m.cs_round_duration.observe(now - self._round_started_at)
        self._round_started_at = now
        m.cs_validators_power.set(validators.total_voting_power())
        self._update_round_step(round, STEP_NEW_ROUND)
        if not self._replay_mode:
            # height timeline: first round entry stamps "start"; later
            # rounds only bump the recorded max round (first-mark-wins)
            hl = _heightline()
            hl.set_current(height)
            hl.mark(height, "start", round_=round)
        rs.validators = validators
        if round != 0:
            # round advanced: drop the stale proposal (state.go:1102)
            rs.proposal = None
            rs.proposal_receive_time_ns = 0
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round + 1)
        rs.triggered_timeout_precommit = False
        self.event_bus.publish_new_round(rs.round_state_event())

        wait_for_txs = (
            not self.config.create_empty_blocks
            and round == 0
            and self.tx_notifier is not None
            and self.tx_notifier.size() == 0
        )
        if wait_for_txs:
            if self.config.create_empty_blocks_interval > 0:
                self._ticker.schedule(
                    TimeoutInfo(
                        self.config.create_empty_blocks_interval,
                        height,
                        round,
                        STEP_NEW_ROUND,
                    )
                )
            self._wait_for_txs(height, round)
        else:
            self._enter_propose(height, round)

    def _wait_for_txs(self, height: int, round: int) -> None:
        def waiter():
            self.tx_notifier.txs_available().wait()
            self._queue.put(TimeoutInfo(0, height, round, STEP_NEW_ROUND))

        threading.Thread(
            target=waiter, daemon=True, name="cs-tx-waiter"
        ).start()

    # ------------------------------------------------------------ propose

    def _enter_propose(self, height: int, round: int) -> None:
        rs = self.rs
        if rs.height != height or round < rs.round or (
            rs.round == round and rs.step >= STEP_PROPOSE
        ):
            return
        self._update_round_step(round, STEP_PROPOSE)
        self._ticker.schedule(
            TimeoutInfo(self.config.propose_timeout(round), height, round, STEP_PROPOSE)
        )
        if self.priv_validator is not None and self.is_proposer():
            self._decide_proposal(height, round)
        if self._is_proposal_complete():
            self._enter_prevote(height, rs.round)

    def _decide_proposal(self, height: int, round: int) -> None:
        """state.go:1226 defaultDecideProposal."""
        if self.decide_proposal_hook is not None:
            self.decide_proposal_hook(self, height, round)
            return
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            last_ext_commit = self._load_last_extended_commit(height)
            # PBTS: the proposer stamps its own clock, clamped above the
            # previous block's time so a lagging clock can't produce an
            # invalid non-monotonic block (the reference instead WAITS for
            # the clock to pass lastBlockTime before proposing; clamping
            # trades that head start for the round's liveness).  BFT time
            # (the default) derives the block time from the commit median.
            block_time = None
            if self.state.consensus_params.feature.pbts_enabled(height):
                block_time = Timestamp.from_unix_ns(
                    max(
                        time.time_ns(),
                        self.state.last_block_time.unix_ns() + 1,
                    )
                )
            try:
                block, block_parts = self.block_exec.create_proposal_block(
                    height,
                    self.state,
                    last_ext_commit,
                    self.priv_validator_pub_key.address(),
                    block_time=block_time,
                )
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"failed to create proposal block: {e}")
                return
        bid = BlockID(
            hash=block.hash(),
            part_set_header=block_parts.header,
        )
        proposal = Proposal(
            height=height,
            round=round,
            pol_round=rs.valid_round,
            block_id=bid,
            # the proposal carries the BLOCK's time (state.go:1252) — PBTS
            # receivers check proposal.timestamp == block.header.time
            timestamp=block.header.time,
        )
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:  # noqa: BLE001
            if not self._replay_mode:
                self.logger.error(f"propose step; failed signing proposal: {e}")
            return
        # internal inputs are WAL-logged exactly like peer inputs
        self._internal_msg(MsgInfo(ProposalMessage(proposal), "", time.time_ns()))
        for i in range(block_parts.header.total):
            self._internal_msg(
                MsgInfo(BlockPartMessage(height, round, block_parts.get_part(i)), "", 0)
            )
        self.logger.info(f"signed proposal {height}/{round} {bid.hash.hex()[:12]}")
        from ..utils.metrics import hub as _mhub

        _mhub().cs_proposal_create_count.inc()

    def _load_last_extended_commit(self, height: int):
        if height == self.state.initial_height:
            return None
        ext_enabled = self.state.consensus_params.feature.vote_extensions_enabled(
            height - 1
        )
        if ext_enabled:
            ec = self.block_store.load_block_extended_commit(height - 1)
            if ec is not None:
                return ec
        # plain commit wrapped as extension-less extended commit
        if self.rs.last_commit is not None and self.rs.last_commit.has_two_thirds_majority():
            return self.rs.last_commit.make_extended_commit()
        commit = self.block_store.load_seen_commit(height - 1)
        if commit is None:
            raise ConsensusError(f"no commit found for height {height - 1}")
        from ..types.block import ExtendedCommit, ExtendedCommitSig

        return ExtendedCommit(
            height=commit.height,
            round=commit.round,
            block_id=commit.block_id,
            extended_signatures=[
                ExtendedCommitSig(commit_sig=cs) for cs in commit.signatures
            ],
        )

    def _is_proposal_complete(self) -> bool:
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    # --------------------------------------------------- proposal intake

    def _set_proposal(self, proposal: Proposal, receive_time_ns: int) -> None:
        """state.go defaultSetProposal."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round
        ):
            raise ConsensusError("invalid proposal POLRound")
        from ..utils.metrics import hub as _mhub

        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            _mhub().cs_proposal_receive_count.inc(status="rejected")
            raise ConsensusError("invalid proposal signature")
        _mhub().cs_proposal_receive_count.inc(status="accepted")
        if not self._replay_mode:
            _flightrec().record(
                "proposal",
                height=proposal.height,
                round=proposal.round,
                pol_round=proposal.pol_round,
                block=proposal.block_id.hash.hex()[:12],
            )
            _heightline().mark(
                proposal.height, "proposal", round_=proposal.round
            )
        rs.proposal = proposal
        rs.proposal_receive_time_ns = receive_time_ns
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(proposal.block_id.part_set_header)

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> None:
        """state.go addProposalBlockPart."""
        rs = self.rs
        if msg.height != rs.height:
            return
        if rs.proposal_block_parts is None:
            return  # no proposal yet: can't validate the part against a header
        added = rs.proposal_block_parts.add_part(msg.part)
        if not added or not rs.proposal_block_parts.is_complete():
            return
        rs.proposal_block = Block.decode(rs.proposal_block_parts.assemble())
        if not self._replay_mode:
            _heightline().mark(rs.height, "full_block", round_=rs.round)
        self.logger.info(
            f"received complete proposal block h={rs.proposal_block.header.height} "
            f"hash={rs.proposal_block.hash().hex()[:12]}"
        )
        self.event_bus.publish_complete_proposal(rs.round_state_event())

        # +2/3 prevotes for this block in the current round -> update valid
        prevotes = rs.votes.prevotes(rs.round)
        bid, has_maj = prevotes.two_thirds_majority() if prevotes else (None, False)
        if has_maj and not bid.is_nil() and rs.valid_round < rs.round:
            if rs.proposal_block.hash() == bid.hash:
                rs.valid_round = rs.round
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts

        if rs.step <= STEP_PROPOSE and self._is_proposal_complete():
            self._enter_prevote(rs.height, rs.round)
        elif rs.step == STEP_COMMIT:
            self._try_finalize_commit(rs.height)

    # ------------------------------------------------------------ prevote

    def _enter_prevote(self, height: int, round: int) -> None:
        rs = self.rs
        if rs.height != height or round < rs.round or (
            rs.round == round and rs.step >= STEP_PREVOTE
        ):
            return
        self._update_round_step(round, STEP_PREVOTE)
        self._do_prevote(height, round)

    def _do_prevote(self, height: int, round: int) -> None:
        """state.go defaultDoPrevote: prevote locked block, else validate
        the proposal and prevote it, else nil.  With PBTS enabled
        (state.go:1440-1460), a fresh proposal (POLRound == -1) must carry
        the block's own timestamp and arrive within the synchrony bounds,
        or we prevote nil."""
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(PREVOTE_TYPE, rs.locked_block.hash(), rs.locked_block_parts.header)
            return
        if rs.proposal_block is None:
            self._sign_add_vote(PREVOTE_TYPE, b"", None)
            return
        if rs.proposal is not None and self.state.consensus_params.feature.pbts_enabled(
            height
        ):
            # EVERY proposal must carry the block's own time under PBTS;
            # only the timeliness window is restricted to fresh proposals
            # (POLRound == -1) — a re-proposed POL'd block was already
            # judged timely in its original round (state.go:1440-1460)
            if rs.proposal.timestamp.unix_ns() != rs.proposal_block.header.time.unix_ns():
                self.logger.info(
                    "prevote: proposal timestamp != block time; prevoting nil"
                )
                self._sign_add_vote(PREVOTE_TYPE, b"", None)
                return
            if rs.proposal.pol_round == -1:
                sp = self.state.consensus_params.synchrony.in_round(
                    rs.proposal.round
                )
                if not rs.proposal.is_timely(rs.proposal_receive_time_ns, sp):
                    self.logger.info(
                        f"prevote: proposal not timely "
                        f"(ts={rs.proposal.timestamp.unix_ns()} "
                        f"recv={rs.proposal_receive_time_ns} "
                        f"delay={sp.message_delay_ns} prec={sp.precision_ns}); "
                        "prevoting nil"
                    )
                    self._sign_add_vote(PREVOTE_TYPE, b"", None)
                    return
        try:
            self.block_exec.validate_block(
                self.state, rs.proposal_block, klass=_VerifyKlass.CONSENSUS
            )
            accepted = self.block_exec.process_proposal(rs.proposal_block, self.state)
        except Exception as e:  # noqa: BLE001
            self.logger.error(f"prevote: invalid proposal block: {e}")
            accepted = False
        if accepted:
            self._sign_add_vote(
                PREVOTE_TYPE,
                rs.proposal_block.hash(),
                rs.proposal_block_parts.header,
            )
        else:
            self._sign_add_vote(PREVOTE_TYPE, b"", None)

    # ---------------------------------------------------------- precommit

    def _enter_precommit(self, height: int, round: int) -> None:
        """state.go:1609 enterPrecommit."""
        rs = self.rs
        if rs.height != height or round < rs.round or (
            rs.round == round and rs.step >= STEP_PRECOMMIT
        ):
            return
        self._update_round_step(round, STEP_PRECOMMIT)
        prevotes = rs.votes.prevotes(round)
        bid, has_maj = prevotes.two_thirds_majority() if prevotes else (None, False)

        if not has_maj:
            # no polka: precommit nil
            self._sign_add_vote(PRECOMMIT_TYPE, b"", None)
            return

        self.event_bus.publish_polka(rs.round_state_event())
        if not bid.is_nil() and not self._replay_mode:
            _heightline().mark(height, "prevote_23", round_=round)

        if bid.is_nil():
            # polka for nil: precommit nil and unlock (state.go:1661)
            if rs.locked_block is not None:
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                self.event_bus.publish_lock(rs.round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, b"", None)
            return

        if rs.locked_block is not None and rs.locked_block.hash() == bid.hash:
            # relock
            rs.locked_round = round
            self.event_bus.publish_relock(rs.round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, bid.hash, bid.part_set_header)
            return

        if rs.proposal_block is not None and rs.proposal_block.hash() == bid.hash:
            # lock onto the polka block
            try:
                self.block_exec.validate_block(
                    self.state, rs.proposal_block, klass=_VerifyKlass.CONSENSUS
                )
            except Exception as e:
                raise ConsensusError(f"precommit: +2/3 prevoted an invalid block: {e}")
            rs.locked_round = round
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            self.event_bus.publish_lock(rs.round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, bid.hash, bid.part_set_header)
            return

        # polka for a block we don't have: precommit nil, fetch it
        rs.proposal_block = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.header == bid.part_set_header:
            rs.proposal_block_parts = PartSet(bid.part_set_header)
        self._sign_add_vote(PRECOMMIT_TYPE, b"", None)

    # ------------------------------------------------------------- commit

    def _enter_commit(self, height: int, commit_round: int) -> None:
        rs = self.rs
        if rs.height != height or rs.step >= STEP_COMMIT:
            return
        rs.commit_time_ns = time.time_ns()
        self._update_round_step(rs.round, STEP_COMMIT)
        if not self._replay_mode:
            # commit entry doubles as the +2/3-precommit observation
            # point — _enter_commit is only reached on a precommit
            # majority, so both marks share commit_time_ns
            hl = _heightline()
            hl.mark(
                height, "precommit_23",
                wall_ns=rs.commit_time_ns, round_=commit_round,
            )
            hl.mark(
                height, "commit",
                wall_ns=rs.commit_time_ns, round_=commit_round,
            )
        rs.commit_round = commit_round
        precommits = rs.votes.precommits(commit_round)
        bid, ok = precommits.two_thirds_majority()
        if not ok:
            raise ConsensusError("enterCommit without +2/3 precommits")
        # locked block takes precedence if it matches
        if rs.locked_block is not None and rs.locked_block.hash() == bid.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        elif rs.proposal_block is None or rs.proposal_block.hash() != bid.hash:
            rs.proposal_block = None
            if rs.proposal_block_parts is None or rs.proposal_block_parts.header != bid.part_set_header:
                rs.proposal_block_parts = PartSet(bid.part_set_header)
            # Announce which parts we have (none) so peers that already
            # marked parts as sent to us reset their view and re-send
            # (state.go enterCommit → reactor NewValidBlockMessage; without
            # this a catchup node entering commit without the block stalls
            # forever — peers one-shot their catchup part sends).
            if self.new_valid_block_hook is not None and not self._replay_mode:
                self.new_valid_block_hook(rs, True)
            return  # wait for parts
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        bid, ok = precommits.two_thirds_majority() if precommits else (None, False)
        if not ok or bid.is_nil():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != bid.hash:
            return
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """state.go:1834: save → WAL EndHeight → apply → next height."""
        rs = self.rs
        bid, _ = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts

        self.block_exec.validate_block(
            self.state, block, klass=_VerifyKlass.CONSENSUS
        )

        from ..utils.fail import fail_point

        precommits = rs.votes.precommits(rs.commit_round)
        commit = precommits.make_commit()
        fail_point("before save_block")  # state.go:1872
        if self.block_store.height < block.header.height:
            ext_enabled = self.state.consensus_params.feature.vote_extensions_enabled(
                height
            )
            if ext_enabled:
                self.block_store.save_block_with_extended_commit(
                    block, block_parts, precommits.make_extended_commit()
                )
            else:
                self.block_store.save_block(block, block_parts, commit)

        fail_point("before WAL end_height")  # state.go:1889
        self.wal.write_sync(
            wal_pb.WALMessageProto(end_height=wal_pb.EndHeightProto(height=height))
        )
        fail_point("after WAL end_height")  # state.go:1912

        # metricsgen set: absentees + block size (metrics.go RecordConsMetrics)
        from ..utils.metrics import hub as _mhub

        m = _mhub()
        missing = 0
        missing_power = 0
        for i, cs in enumerate(commit.signatures):
            if not cs.for_block():
                missing += 1
                _, v = rs.validators.get_by_index(i)
                if v is not None:
                    missing_power += v.voting_power
        m.cs_missing_validators.set(missing)
        m.cs_missing_validators_power.set(missing_power)
        m.cs_block_size_bytes.set(block_parts.byte_size)

        state_copy = self.state.copy()
        new_state = self.block_exec.apply_verified_block(state_copy, bid, block)
        self.update_to_state(new_state)
        if not self._replay_mode:
            hl = _heightline()
            hl.mark(height, "apply", round_=rs.commit_round)
            # verify batches between now and the next round-0 entry
            # belong to the height we just moved to
            hl.set_current(self.rs.height)
        self._schedule_round0(self.rs)

    # --------------------------------------------------------------- votes

    def _try_add_vote(self, vote: Vote, peer_id: str) -> None:
        try:
            self._add_vote(vote, peer_id)
        except VoteError as e:
            if isinstance(e, ErrVoteConflictingVotes):
                if self.ev_pool is not None and peer_id:
                    # buffer the raw votes; the pool forms the evidence at
                    # the next Update() so it carries the committed block's
                    # timestamp and validator set (pool.go:235)
                    try:
                        self.ev_pool.report_conflicting_votes(
                            vote, e.conflicting_vote
                        )
                    except Exception as ee:  # noqa: BLE001
                        self.logger.error(f"failed to record equivocation: {ee}")
                self.logger.info("found conflicting vote (equivocation)")
            else:
                self.logger.info(f"vote rejected: {e}")

    def _add_vote(self, vote: Vote, peer_id: str) -> None:
        rs = self.rs
        # precommit from the previous height (late commit vote)
        if vote.height + 1 == rs.height and vote.type == PRECOMMIT_TYPE:
            from ..utils.metrics import hub as _mhub

            _mhub().cs_late_votes.inc(vote_type="precommit")
            if rs.step != STEP_NEW_HEIGHT or rs.last_commit is None:
                return
            if rs.last_commit.add_vote(vote):
                self.event_bus.publish_vote(vote)
            return
        if vote.height != rs.height:
            return

        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return
        if not self._replay_mode:
            _flightrec().record(
                "vote",
                height=vote.height,
                round=vote.round,
                vote_type=vote.type,
                val_index=vote.validator_index,
                peer=peer_id or "self",
            )
        self.event_bus.publish_vote(vote)
        if self.has_vote_hook is not None and not self._replay_mode:
            self.has_vote_hook(vote)

        if vote.type == PREVOTE_TYPE:
            self._on_prevote_added(vote)
        else:
            self._on_precommit_added(vote)

    def _on_prevote_added(self, vote: Vote) -> None:
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round)
        bid, has_maj = prevotes.two_thirds_majority()

        # unlock on newer polka for a different block (state.go:2339)
        if (
            rs.locked_block is not None
            and rs.locked_round < vote.round
            and vote.round <= rs.round
            and has_maj
            and rs.locked_block.hash() != bid.hash
        ):
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            self.event_bus.publish_lock(rs.round_state_event())

        # update valid block (state.go:2357)
        if (
            has_maj
            and not bid.is_nil()
            and rs.valid_round < vote.round
            and vote.round == rs.round
        ):
            if rs.proposal_block is not None and rs.proposal_block.hash() == bid.hash:
                rs.valid_round = vote.round
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts
            else:
                rs.proposal_block = None
                if rs.proposal_block_parts is None or rs.proposal_block_parts.header != bid.part_set_header:
                    rs.proposal_block_parts = PartSet(bid.part_set_header)
            self.event_bus.publish_valid_block(rs.round_state_event())

        if rs.round < vote.round and prevotes.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
        elif rs.round == vote.round and rs.step >= STEP_PREVOTE:
            if has_maj and (self._is_proposal_complete() or bid.is_nil()):
                self._enter_precommit(rs.height, vote.round)
            elif prevotes.has_two_thirds_any() and rs.step == STEP_PREVOTE:
                self._enter_prevote_wait(rs.height, vote.round)
        elif rs.proposal is not None and 0 <= rs.proposal.pol_round == vote.round:
            if self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)

    def _enter_prevote_wait(self, height: int, round: int) -> None:
        rs = self.rs
        if rs.height != height or round < rs.round or (
            rs.round == round and rs.step >= STEP_PREVOTE_WAIT
        ):
            return
        self._update_round_step(round, STEP_PREVOTE_WAIT)
        self._ticker.schedule(
            TimeoutInfo(self.config.prevote_timeout(round), height, round, STEP_PREVOTE_WAIT)
        )

    def _on_precommit_added(self, vote: Vote) -> None:
        rs = self.rs
        precommits = rs.votes.precommits(vote.round)
        bid, has_maj = precommits.two_thirds_majority()
        if has_maj:
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit(rs.height, vote.round)
            if not bid.is_nil():
                self._enter_commit(rs.height, vote.round)
                if precommits.has_all():
                    self._enter_new_round(rs.height, 0)
            else:
                # nil majority: wait out stragglers then next round
                self._enter_precommit_wait(rs.height, vote.round)
        elif rs.round <= vote.round and precommits.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit_wait(rs.height, vote.round)

    def _enter_precommit_wait(self, height: int, round: int) -> None:
        rs = self.rs
        if rs.height != height or round < rs.round or (
            round == rs.round and rs.triggered_timeout_precommit
        ):
            return
        rs.triggered_timeout_precommit = True
        self._ticker.schedule(
            TimeoutInfo(
                self.config.precommit_timeout(round), height, round, STEP_PRECOMMIT_WAIT
            )
        )

    # ------------------------------------------------------------- signing

    def _vote_time_ns(self) -> int:
        """Monotonic vote timestamps for BFT time (state.go voteTime)."""
        now = time.time_ns()
        minimum = self.state.last_block_time.unix_ns() + 1_000_000
        return max(now, minimum)

    def _sign_vote(self, vote_type: int, block_hash: bytes, psh) -> Vote | None:
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            return None
        addr = self.priv_validator_pub_key.address()
        idx, val = self.rs.validators.get_by_address(addr)
        if val is None:
            return None
        rs = self.rs
        block_id = (
            BlockID(hash=block_hash, part_set_header=psh)
            if block_hash
            else BlockID()
        )
        vote = Vote(
            type=vote_type,
            height=rs.height,
            round=rs.round,
            block_id=block_id,
            timestamp=Timestamp.from_unix_ns(self._vote_time_ns()),
            validator_address=addr,
            validator_index=idx,
        )
        ext_enabled = self.state.consensus_params.feature.vote_extensions_enabled(
            rs.height
        )
        if (
            vote_type == PRECOMMIT_TYPE
            and block_hash
            and ext_enabled
        ):
            vote.extension = self.block_exec.extend_vote(
                vote, rs.proposal_block, self.state
            )
        try:
            self.priv_validator.sign_vote(
                self.state.chain_id, vote, sign_extension=ext_enabled
            )
        except Exception as e:  # noqa: BLE001
            if not self._replay_mode:
                self.logger.error(f"failed signing vote: {e}")
            return None
        return vote

    def _sign_add_vote(self, vote_type: int, block_hash: bytes, psh) -> None:
        vote = self._sign_vote(vote_type, block_hash, psh)
        if vote is not None:
            self._maybe_double_sign(vote)
            self._internal_msg(MsgInfo(VoteMessage(vote), "", time.time_ns()))

    def _maybe_double_sign(self, vote: Vote) -> None:
        """Chaos seam (utils/fail, fault ``double_sign``): alongside a
        signed non-nil prevote, BROADCAST a conflicting vote for a
        fabricated block at the same height/round — byzantine
        equivocation, injected.  Broadcast-only: the equivocator does
        not process its own conflicting vote (its honest vote is the
        one in its WAL); honest peers' vote sets raise
        ErrVoteConflictingVotes, feed the evidence pool, and the
        DuplicateVoteEvidence lands in a later block.  The conflicting
        vote is signed by the raw key, deliberately bypassing FilePV's
        last-sign-state guard — bypassing that guard is what makes the
        node byzantine."""
        from ..utils import fail

        if (
            vote.type != PREVOTE_TYPE
            or not vote.block_id.hash
            or self._replay_mode
            or self.broadcast_hook is None
        ):
            return
        key = getattr(self.priv_validator, "key", None)
        if key is None:
            return  # remote signers can't be coaxed into equivocating
        if fail.consume("double_sign") is None:
            return
        conflicting = Vote(
            type=vote.type,
            height=vote.height,
            round=vote.round,
            block_id=BlockID(
                hash=bytes(b ^ 0xFF for b in vote.block_id.hash),
                part_set_header=vote.block_id.part_set_header,
            ),
            timestamp=vote.timestamp,
            validator_address=vote.validator_address,
            validator_index=vote.validator_index,
        )
        conflicting.signature = key.priv_key.sign(
            conflicting.sign_bytes(self.state.chain_id)
        )
        self.logger.error(
            "CHAOS: broadcasting conflicting prevote (injected "
            f"double_sign) at {vote.height}/{vote.round}"
        )
        _flightrec().record(
            "chaos_double_sign", height=vote.height, round=vote.round
        )
        self.broadcast_hook(VoteMessage(conflicting, bypass_gossip_dedup=True))

    def _internal_msg(self, mi: MsgInfo) -> None:
        """Own proposals/votes/parts: WAL-log (fsync for votes) then
        handle inline — the same serialization point as peer inputs since
        we already hold the state lock."""
        self._wal_write_msg(mi)
        self._handle_msg(mi)
        if self.broadcast_hook is not None and not self._replay_mode:
            self.broadcast_hook(mi.msg)
