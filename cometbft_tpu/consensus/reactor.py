"""Consensus reactor: gossips proposals, block parts, and votes between
the local state machine and peers (reference: internal/consensus/reactor.go).

Four p2p streams (reactor.go:156): State (round steps / HasVote /
NewValidBlock), Data (proposals + block parts), Vote, VoteSetBits.
Per peer: a PeerState mirror of the remote round state and two gossip
threads (data + votes, reactor.go:594,654) that push whatever the peer
is missing — including catchup block parts for peers on old heights —
plus a Maj23 query loop (reactor.go:720) that periodically advertises the
blocks we hold 2/3 majorities for so peers reply with their vote bits.
"""

from __future__ import annotations

import threading
import time

from ..p2p.conn.connection import StreamDescriptor
from ..p2p.reactor import Reactor
from ..types.block import BlockID
from ..types.msg_validation import validate_consensus_message
from ..types.part_set import Part, PartSet
from ..types.proposal import Proposal
from ..types.vote import Vote
from ..utils.log import get_logger
from ..wire import consensus_pb as pb
from ..wire.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from .state import (
    BlockPartMessage,
    ConsensusState,
    ProposalMessage,
    VoteMessage,
)
from .types import STEP_COMMIT, STEP_NEW_HEIGHT

STATE_STREAM = 0x20
DATA_STREAM = 0x21
VOTE_STREAM = 0x22
VOTE_SET_BITS_STREAM = 0x23


class PeerState:
    """What we know about a peer's round state (reactor.go:1110)."""

    def __init__(self, peer):
        self.peer = peer
        self.mtx = threading.RLock()
        self.height = 0
        self.round = -1
        self.step = STEP_NEW_HEIGHT
        self.start_time_ns = 0
        self.proposal = False
        self.proposal_block_psh = None  # PartSetHeader
        self.proposal_block_parts: list[bool] = []
        self.proposal_pol_round = -1
        # (height, round, type) -> set of validator indexes the peer has
        self.votes_seen: dict[tuple[int, int, int], set[int]] = {}
        self.catchup_commit_round = -1

    def apply_new_round_step(self, msg: pb.NewRoundStep) -> None:
        with self.mtx:
            new_height = msg.height != self.height
            new_round = new_height or msg.round != self.round
            self.height = msg.height
            self.round = msg.round
            self.step = msg.step
            if new_round:
                self.proposal = False
                self.proposal_block_psh = None
                self.proposal_block_parts = []
                self.proposal_pol_round = -1
            if new_height:
                self.votes_seen = {
                    k: v for k, v in self.votes_seen.items() if k[0] >= msg.height - 1
                }

    def apply_new_valid_block(self, msg: pb.NewValidBlock) -> None:
        with self.mtx:
            if msg.height != self.height:
                return
            if msg.round != self.round and not msg.is_commit:
                return
            from ..types.block import PartSetHeader

            self.proposal_block_psh = PartSetHeader.from_proto(
                msg.block_part_set_header
            )
            self.proposal_block_parts = (
                msg.block_parts.to_bools() if msg.block_parts else []
            )

    def set_has_proposal(self, proposal: Proposal) -> None:
        with self.mtx:
            if proposal.height != self.height or proposal.round != self.round:
                return
            if self.proposal:
                return
            self.proposal = True
            self.proposal_block_psh = proposal.block_id.part_set_header
            self.proposal_block_parts = [False] * proposal.block_id.part_set_header.total
            self.proposal_pol_round = proposal.pol_round

    def set_has_block_part(self, height: int, round: int, index: int) -> None:
        with self.mtx:
            if height != self.height:
                return
            if 0 <= index < len(self.proposal_block_parts):
                self.proposal_block_parts[index] = True

    def set_has_vote(self, height: int, round: int, vtype: int, index: int) -> None:
        with self.mtx:
            self.votes_seen.setdefault((height, round, vtype), set()).add(index)

    def has_vote(self, vote: Vote) -> bool:
        with self.mtx:
            return vote.validator_index in self.votes_seen.get(
                (vote.height, vote.round, vote.type), set()
            )

    def missing_part_index(self, our_parts: PartSet) -> int | None:
        """First part we have that the peer seems to lack."""
        with self.mtx:
            if self.proposal_block_psh is None:
                return None
            if our_parts.header != self.proposal_block_psh:
                return None
            for i in range(our_parts.header.total):
                have = our_parts.get_part(i) is not None
                peer_has = (
                    i < len(self.proposal_block_parts) and self.proposal_block_parts[i]
                )
                if have and not peer_has:
                    return i
            return None


class ConsensusReactor(Reactor):
    def __init__(self, cs: ConsensusState, wait_sync: bool = False):
        super().__init__("ConsensusReactor")
        self.cs = cs
        self.wait_sync = wait_sync  # blocksync still running
        self._switch_mtx = threading.Lock()  # guards the one-shot handoff
        self.logger = get_logger("cs-reactor")
        # the state machine tells us what to flood
        cs.broadcast_hook = self._on_internal_msg
        cs.on_new_round_step = self._on_new_round_step
        cs.has_vote_hook = self._broadcast_has_vote
        cs.new_valid_block_hook = self._broadcast_new_valid_block

    # ------------------------------------------------------------- config

    def stream_descriptors(self) -> list[StreamDescriptor]:
        return [
            StreamDescriptor(id=STATE_STREAM, priority=6, send_queue_capacity=100),
            StreamDescriptor(id=DATA_STREAM, priority=10, send_queue_capacity=100),
            StreamDescriptor(id=VOTE_STREAM, priority=7, send_queue_capacity=100),
            StreamDescriptor(id=VOTE_SET_BITS_STREAM, priority=1, send_queue_capacity=20),
        ]

    def on_start(self) -> None:
        if not self.wait_sync and not self.cs.is_running():
            self.cs.start()

    def on_stop(self) -> None:
        if self.cs.is_running():
            self.cs.stop()

    def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        """Blocksync → consensus handoff (reactor.go:117).

        Idempotent and locked: a duplicate handoff (pool double-signal)
        must NOT re-run update_to_state on a running state machine — the
        rs swap staleness-drops every scheduled timeout while the failed
        re-start() schedules nothing new, wedging the node at the handoff
        height with an empty queue and no pending timer (the post-restart
        stall chased across rounds 3-4)."""
        with self._switch_mtx:
            if not self.wait_sync:
                self.logger.error(
                    "switch_to_consensus called again; ignoring duplicate"
                )
                return
            self.cs.update_to_state(state)
            self.wait_sync = False
            self.cs.start()
        # Tell every peer where we are NOW that we can accept their
        # catchup traffic (announcements were suppressed while syncing;
        # reference SwitchToConsensus reaches peers via the NewRoundStep
        # the restarted state machine emits — ours may have been replayed
        # past that emission, so announce explicitly)
        self._on_new_round_step(self.cs.get_round_state())

    # ------------------------------------------------------------- peers

    def init_peer(self, peer) -> None:
        # per-CONNECTION state, stored on the peer object itself: an id-keyed
        # dict races on reconnect (the old connection's remove_peer pops the
        # new connection's state, after which every message from that peer is
        # silently dropped — observed as a permanent catchup stall)
        peer.set("consensus_peer_state", PeerState(peer))

    def add_peer(self, peer) -> None:
        ps = peer.get("consensus_peer_state")
        if ps is None:
            return
        if not peer.has_channel(STATE_STREAM):
            return  # peer runs no consensus reactor: skip the gossip threads
        # Announce our round state so the peer can route to us — but NEVER
        # while block/state sync is running (reactor.go:193 AddPeer gates
        # on !conR.WaitSync()).  While syncing, receive() drops vote/data
        # traffic; announcing a consensus height in that window makes
        # peers serve catchup votes into the void and mark them sent in
        # their per-peer votes_seen, which is only pruned when OUR height
        # advances — so after the handoff nobody ever resends them and
        # the node wedges at its handoff height (the perturbed-soak
        # post-kill stall, root-caused round 5).
        if not self.wait_sync:
            self._send_round_step(peer)
        short = peer.id[:8]
        threading.Thread(
            target=self._gossip_data_routine, args=(peer, ps), daemon=True,
            name=f"cs-gossip-data-{short}",
        ).start()
        threading.Thread(
            target=self._gossip_votes_routine, args=(peer, ps), daemon=True,
            name=f"cs-gossip-votes-{short}",
        ).start()
        threading.Thread(
            target=self._query_maj23_routine, args=(peer, ps), daemon=True,
            name=f"cs-maj23-{short}",
        ).start()

    def remove_peer(self, peer, reason: str = "") -> None:
        pass  # state lives on the peer object; it dies with the connection

    # ----------------------------------------------------------- receive

    def receive(self, stream_id: int, peer, msg_bytes: bytes) -> None:
        # While blocksync is still running the consensus state machine is
        # stopped: drop data/vote traffic before decoding, keeping only
        # state-stream bookkeeping (reference: reactor.go:243-255 gates every
        # non-state channel on conR.WaitSync()).
        if self.wait_sync and stream_id != STATE_STREAM:
            return
        msg = pb.ConsensusMessage.decode(msg_bytes)
        # validate-before-use: bounds-check every peer-supplied field
        # (heights, rounds, bit-array and part-set sizes) before any arm
        # touches PeerState or the state machine; a raise here reaches
        # the switch's receive wrapper, which disconnects the peer
        validate_consensus_message(msg)
        which = msg.which()
        ps: PeerState = peer.get("consensus_peer_state")
        if ps is None:
            return
        if which == "new_round_step":
            ps.apply_new_round_step(msg.new_round_step)
        elif which == "new_valid_block":
            ps.apply_new_valid_block(msg.new_valid_block)
        elif which == "has_vote":
            hv = msg.has_vote
            ps.set_has_vote(hv.height, hv.round, hv.type, hv.index)
        elif which == "has_proposal_block_part":
            hp = msg.has_proposal_block_part
            ps.set_has_block_part(hp.height, hp.round, hp.index)
        elif which == "proposal":
            proposal = Proposal.from_proto(msg.proposal.proposal)
            proposal.validate_basic()
            ps.set_has_proposal(proposal)
            self.cs.set_proposal(proposal, peer.id)
        elif which == "block_part":
            bp = msg.block_part
            part = Part.from_proto(bp.part)
            part.validate_basic()
            ps.set_has_block_part(bp.height, bp.round, part.index)
            self.cs.add_proposal_block_part(bp.height, bp.round, part, peer.id)
        elif which == "vote":
            vote = Vote.from_proto(msg.vote.vote)
            vote.validate_basic()
            ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
            self.cs.add_vote(vote, peer.id)
        elif which == "vote_set_maj23":
            m = msg.vote_set_maj23
            rs = self.cs.get_round_state()
            if rs.height == m.height and rs.votes is not None:
                rs.votes.set_peer_maj23(
                    m.round, m.type, peer.id, BlockID.from_proto(m.block_id)
                )
                # respond with our bit array for that (round, type, blockID)
                vs = (
                    rs.votes.prevotes(m.round)
                    if m.type == PREVOTE_TYPE
                    else rs.votes.precommits(m.round)
                )
                if vs is not None:
                    bits = vs.bit_array_by_block_id(BlockID.from_proto(m.block_id))
                    if bits is not None:
                        reply = pb.ConsensusMessage(
                            vote_set_bits=pb.VoteSetBits(
                                height=m.height,
                                round=m.round,
                                type=m.type,
                                block_id=m.block_id,
                                votes=pb.BitArrayProto.from_bools(bits),
                            )
                        )
                        peer.try_send(VOTE_SET_BITS_STREAM, reply.encode())
        elif which == "vote_set_bits":
            # the peer's answer to our VoteSetMaj23 query: mark every vote
            # it reports holding so the gossip routines stop re-sending
            # them and concentrate on the gaps (reactor.go
            # ApplyVoteSetBitsMessage)
            vb = msg.vote_set_bits
            for i, has in enumerate(vb.votes.to_bools() if vb.votes else []):
                if has:
                    ps.set_has_vote(vb.height, vb.round, vb.type, i)

    # --------------------------------------------- own-state broadcasting

    def _on_internal_msg(self, msg) -> None:
        """Our own proposals/parts/votes flood to every peer, skipping
        peers we know already have them."""
        if self.switch is None:
            return
        if isinstance(msg, ProposalMessage):
            wire = pb.ConsensusMessage(
                proposal=pb.ProposalMsg(proposal=msg.proposal.to_proto())
            ).encode()
            for peer in self.switch.peers.list():
                ps = peer.get("consensus_peer_state")
                if ps is not None:
                    ps.set_has_proposal(msg.proposal)
                peer.try_send(DATA_STREAM, wire)
        elif isinstance(msg, BlockPartMessage):
            wire = pb.ConsensusMessage(
                block_part=pb.BlockPartMsg(
                    height=msg.height, round=msg.round, part=msg.part.to_proto()
                )
            ).encode()
            for peer in self.switch.peers.list():
                ps = peer.get("consensus_peer_state")
                if ps is not None:
                    ps.set_has_block_part(msg.height, msg.round, msg.part.index)
                peer.try_send(DATA_STREAM, wire)
        elif isinstance(msg, VoteMessage):
            self._broadcast_vote(
                msg.vote, bypass_dedup=msg.bypass_gossip_dedup
            )

    def _broadcast_vote(self, vote: Vote, bypass_dedup: bool = False) -> None:
        wire = pb.ConsensusMessage(vote=pb.VoteMsg(vote=vote.to_proto())).encode()
        if bypass_dedup:
            # chaos double_sign injection: push to every peer without
            # touching has-vote state, so the honest vote that follows
            # (same validator index) still gossips normally and every
            # peer's vote set receives the CONFLICTING PAIR
            for peer in self.switch.peers.list():
                peer.try_send(VOTE_STREAM, wire)
            return
        for peer in self.switch.peers.list():
            ps = peer.get("consensus_peer_state")
            if ps is not None and ps.has_vote(vote):
                continue
            # Mark as held only if the peer is AT this height — a peer on
            # another height drops the vote, and marking it would stop the
            # catchup gossip from ever re-sending it (the reference's
            # PeerState.SetHasVote is a no-op for heights the peer isn't
            # tracking, reactor.go:1287 getVoteBitArray).
            if (
                peer.try_send(VOTE_STREAM, wire)
                and ps is not None
                and vote.height == ps.height
            ):
                ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)

    def _broadcast_has_vote(self, vote: Vote) -> None:
        """Tell peers we hold this vote so they skip re-sending it
        (reactor.go broadcastHasVoteMessage)."""
        if self.switch is None:
            return
        wire = pb.ConsensusMessage(
            has_vote=pb.HasVote(
                height=vote.height,
                round=vote.round,
                type=vote.type,
                index=vote.validator_index,
            )
        ).encode()
        self.switch.broadcast(STATE_STREAM, wire)

    def _broadcast_new_valid_block(self, rs, is_commit: bool) -> None:
        """Advertise the part-set header + which parts we hold for the block
        being committed/validated, so peers reset their sent-parts view and
        re-send what we lack (reactor.go NewValidBlockMessage)."""
        if self.switch is None or rs.proposal_block_parts is None:
            return
        wire = pb.ConsensusMessage(
            new_valid_block=pb.NewValidBlock(
                height=rs.height,
                round=rs.round,
                block_part_set_header=rs.proposal_block_parts.header.to_proto(),
                block_parts=pb.BitArrayProto.from_bools(
                    rs.proposal_block_parts.bit_array()
                ),
                is_commit=is_commit,
            )
        ).encode()
        self.switch.broadcast(STATE_STREAM, wire)

    def _on_new_round_step(self, rs) -> None:
        if self.switch is None or self.wait_sync:
            # syncing: we drop the vote/data traffic an announcement
            # would draw (see add_peer) — stay silent until the handoff
            return
        wire = self._round_step_msg(rs)
        self.switch.broadcast(STATE_STREAM, wire)

    def _round_step_msg(self, rs) -> bytes:
        return pb.ConsensusMessage(
            new_round_step=pb.NewRoundStep(
                height=rs.height,
                round=rs.round,
                step=rs.step,
                seconds_since_start_time=max(
                    0, int((time.time_ns() - rs.start_time_ns) / 1e9)
                ),
                last_commit_round=rs.last_commit.round if rs.last_commit else -1,
            )
        ).encode()

    def _send_round_step(self, peer) -> None:
        peer.try_send(STATE_STREAM, self._round_step_msg(self.cs.get_round_state()))

    # ------------------------------------------------------------ gossip

    def _gossip_data_routine(self, peer, ps: PeerState) -> None:
        """Push proposal parts / catchup parts the peer lacks
        (reactor.go:594)."""
        sleep = self.cs.config.peer_gossip_sleep_duration
        while peer.is_running() and self.is_running():
            try:
                rs = self.cs.get_round_state()
                # catchup: peer on an older height -> send committed parts
                if 0 < ps.height < rs.height:
                    self._gossip_catchup_part(peer, ps)
                    time.sleep(sleep)
                    continue
                if ps.height == rs.height and rs.proposal_block_parts is not None:
                    idx = ps.missing_part_index(rs.proposal_block_parts)
                    if idx is not None:
                        part = rs.proposal_block_parts.get_part(idx)
                        msg = pb.ConsensusMessage(
                            block_part=pb.BlockPartMsg(
                                height=rs.height, round=rs.round, part=part.to_proto()
                            )
                        )
                        if peer.try_send(DATA_STREAM, msg.encode()):
                            ps.set_has_block_part(rs.height, rs.round, idx)
                        continue
                    # peer lacks the proposal itself
                    if rs.proposal is not None and not ps.proposal:
                        msg = pb.ConsensusMessage(
                            proposal=pb.ProposalMsg(proposal=rs.proposal.to_proto())
                        )
                        if peer.try_send(DATA_STREAM, msg.encode()):
                            ps.set_has_proposal(rs.proposal)
                        continue
                time.sleep(sleep)
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"gossip data error: {e}")
                time.sleep(sleep)

    def _gossip_catchup_part(self, peer, ps: PeerState) -> None:
        """Serve block parts for the height the peer is on
        (reactor.go gossipDataForCatchup)."""
        meta = self.cs.block_store.load_block_meta(ps.height)
        if meta is None:
            return
        from ..types.block import PartSetHeader

        psh = PartSetHeader.from_proto(meta.block_id.part_set_header)
        with ps.mtx:
            if ps.proposal_block_psh is None or ps.proposal_block_psh != psh:
                ps.proposal_block_psh = psh
                ps.proposal_block_parts = [False] * psh.total
            want = next(
                (
                    i
                    for i in range(psh.total)
                    if i >= len(ps.proposal_block_parts)
                    or not ps.proposal_block_parts[i]
                ),
                None,
            )
        if want is None:
            return
        part = self.cs.block_store.load_block_part(ps.height, want)
        if part is None:
            return
        msg = pb.ConsensusMessage(
            block_part=pb.BlockPartMsg(
                height=ps.height, round=ps.round, part=part.to_proto()
            )
        )
        if peer.try_send(DATA_STREAM, msg.encode()):
            ps.set_has_block_part(ps.height, ps.round, want)

    def _gossip_votes_routine(self, peer, ps: PeerState) -> None:
        """Push votes the peer is missing (reactor.go:654)."""
        sleep = self.cs.config.peer_gossip_sleep_duration
        while peer.is_running() and self.is_running():
            try:
                rs = self.cs.get_round_state()
                sent = False
                if (
                    ps.height == rs.height
                    and ps.step == STEP_NEW_HEIGHT
                    and rs.last_commit is not None
                ):
                    # peer is waiting out commit-timeout for the block it
                    # just committed: feed it any last-commit precommits it
                    # is missing (reactor.go gossipVotesForHeight, the
                    # RoundStepNewHeight branch)
                    sent = self._pick_send_vote(peer, ps, rs.last_commit)
                if ps.height == rs.height and rs.votes is not None:
                    for vtype, vs in (
                        (PREVOTE_TYPE, rs.votes.prevotes(ps.round if ps.round >= 0 else rs.round)),
                        (PRECOMMIT_TYPE, rs.votes.precommits(ps.round if ps.round >= 0 else rs.round)),
                    ):
                        if vs is None:
                            continue
                        sent = self._pick_send_vote(peer, ps, vs) or sent
                    # current-round sets too if the peer is on an older round
                    if ps.round != rs.round:
                        for vs in (rs.votes.prevotes(rs.round), rs.votes.precommits(rs.round)):
                            if vs is not None:
                                sent = self._pick_send_vote(peer, ps, vs) or sent
                elif ps.height + 1 == rs.height and rs.last_commit is not None:
                    # peer finishing the previous height: feed last commit
                    sent = self._pick_send_vote(peer, ps, rs.last_commit)
                elif 0 < ps.height < rs.height - 1:
                    # deep catchup: send the stored commit as precommits
                    sent = self._send_stored_commit_vote(peer, ps)
                if not sent:
                    time.sleep(sleep)
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"gossip votes error: {e}")
                time.sleep(sleep)

    def _query_maj23_routine(self, peer, ps: PeerState) -> None:
        """Periodically tell the peer which blocks we have 2/3 majorities
        for, so it replies with its vote bit-arrays and the vote gossip can
        fill in anything we're missing (reactor.go:720 queryMaj23Routine).

        Cycles through prevotes / precommits / POL-prevotes at the current
        height, and the stored commit when the peer trails us."""
        sleep = self.cs.config.peer_query_maj23_sleep_duration
        ticks = 0
        while peer.is_running() and self.is_running():
            try:
                rs = self.cs.get_round_state()
                # Re-announce our round state: the one-shot send in
                # add_peer can race connection setup and drop, and a node
                # parked in the commit step never re-broadcasts — leaving
                # every peer thinking we're at height 0 and never serving
                # catchup votes/parts (observed as a permanent post-restart
                # stall in the perturbed e2e net).  Cheap self-healing:
                # resend whenever the peer may not know us, and every few
                # ticks regardless.
                ticks += 1
                if not self.wait_sync and (ps.height == 0 or ticks % 5 == 0):
                    self._send_round_step(peer)
                if rs.votes is not None and ps.height == rs.height:
                    # query for the PEER's round (reactor.go:720 uses
                    # prs.Round): a peer stuck in an earlier round needs
                    # hints for that round, not ours
                    qround = ps.round if ps.round >= 0 else rs.round
                    for vtype, vs in (
                        (PREVOTE_TYPE, rs.votes.prevotes(qround)),
                        (PRECOMMIT_TYPE, rs.votes.precommits(qround)),
                    ):
                        if vs is None:
                            continue
                        maj, ok = vs.two_thirds_majority()
                        if ok and maj is not None:
                            self._send_maj23(peer, rs.height, qround, vtype, maj)
                    pol_round = (
                        rs.proposal.pol_round if rs.proposal is not None else -1
                    )
                    if pol_round >= 0:
                        vs = rs.votes.prevotes(pol_round)
                        if vs is not None:
                            maj, ok = vs.two_thirds_majority()
                            if ok and maj is not None:
                                self._send_maj23(
                                    peer, rs.height, pol_round, PREVOTE_TYPE, maj
                                )
                # catchup: peer on a height we already committed
                if 0 < ps.height < rs.height:
                    commit = self.cs.block_store.load_block_commit(ps.height)
                    if commit is not None:
                        self._send_maj23(
                            peer,
                            ps.height,
                            commit.round,
                            PRECOMMIT_TYPE,
                            commit.block_id,
                        )
                time.sleep(sleep)
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"query maj23 error: {e}")
                time.sleep(sleep)

    def _send_maj23(
        self, peer, height: int, round: int, vtype: int, block_id: BlockID
    ) -> None:
        peer.try_send(
            STATE_STREAM,
            pb.ConsensusMessage(
                vote_set_maj23=pb.VoteSetMaj23(
                    height=height,
                    round=round,
                    type=vtype,
                    block_id=block_id.to_proto(),
                )
            ).encode(),
        )

    def _pick_send_vote(self, peer, ps: PeerState, vote_set) -> bool:
        for i in range(vote_set.size()):
            vote = vote_set.get_by_index(i)
            if vote is None or ps.has_vote(vote):
                continue
            wire = pb.ConsensusMessage(vote=pb.VoteMsg(vote=vote.to_proto()))
            if peer.try_send(VOTE_STREAM, wire.encode()):
                ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
                return True
            return False
        return False

    def _send_stored_commit_vote(self, peer, ps: PeerState) -> bool:
        commit = self.cs.block_store.load_block_commit(ps.height)
        if commit is None:
            return False
        rs_seen = ps.votes_seen.setdefault(
            (ps.height, commit.round, PRECOMMIT_TYPE), set()
        )
        for i, cs_sig in enumerate(commit.signatures):
            if i in rs_seen or not cs_sig.for_block():
                continue
            vote = Vote(
                type=PRECOMMIT_TYPE,
                height=commit.height,
                round=commit.round,
                block_id=commit.block_id,
                timestamp=cs_sig.timestamp,
                validator_address=cs_sig.validator_address,
                validator_index=i,
                signature=cs_sig.signature,
            )
            wire = pb.ConsensusMessage(vote=pb.VoteMsg(vote=vote.to_proto()))
            if peer.try_send(VOTE_STREAM, wire.encode()):
                rs_seen.add(i)
                return True
            return False
        return False
