"""Consensus round state + height vote set (reference:
internal/consensus/types/round_state.go, height_vote_set.go).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from ..types.block import Block, BlockID
from ..types.part_set import PartSet
from ..types.proposal import Proposal
from ..types.validators import ValidatorSet
from ..types.vote import Vote, VoteError
from ..types.vote_set import VoteSet
from ..wire.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE, Timestamp

# RoundStepType (round_state.go:12-24)
STEP_NEW_HEIGHT = 1
STEP_NEW_ROUND = 2
STEP_PROPOSE = 3
STEP_PREVOTE = 4
STEP_PREVOTE_WAIT = 5
STEP_PRECOMMIT = 6
STEP_PRECOMMIT_WAIT = 7
STEP_COMMIT = 8

STEP_NAMES = {
    STEP_NEW_HEIGHT: "NewHeight",
    STEP_NEW_ROUND: "NewRound",
    STEP_PROPOSE: "Propose",
    STEP_PREVOTE: "Prevote",
    STEP_PREVOTE_WAIT: "PrevoteWait",
    STEP_PRECOMMIT: "Precommit",
    STEP_PRECOMMIT_WAIT: "PrecommitWait",
    STEP_COMMIT: "Commit",
}


@dataclass
class RoundState:
    """Everything the state machine knows about the current height/round
    (round_state.go:27)."""

    height: int = 0
    round: int = 0
    step: int = STEP_NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0
    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_receive_time_ns: int = 0
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None
    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None
    votes: Optional["HeightVoteSet"] = None
    commit_round: int = -1
    last_commit: VoteSet | None = None
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def step_name(self) -> str:
        return STEP_NAMES.get(self.step, f"Unknown({self.step})")

    def round_state_event(self) -> dict:
        return {
            "height": self.height,
            "round": self.round,
            "step": self.step_name(),
        }


class RoundVoteSet:
    __slots__ = ("prevotes", "precommits")

    def __init__(self, prevotes: VoteSet, precommits: VoteSet):
        self.prevotes = prevotes
        self.precommits = precommits


class HeightVoteSet:
    """Keeps prevote/precommit VoteSets for all rounds of one height;
    peers may make us create one catchup round each
    (height_vote_set.go:24-41)."""

    def __init__(
        self,
        chain_id: str,
        height: int,
        val_set: ValidatorSet,
        extensions_enabled: bool = False,
    ):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.extensions_enabled = extensions_enabled
        self.round = 0
        self.round_vote_sets: dict[int, RoundVoteSet] = {}
        self.peer_catchup_rounds: dict[str, list[int]] = {}
        self._mtx = threading.RLock()
        self._add_round(0)

    def _add_round(self, round: int) -> None:
        if round in self.round_vote_sets:
            raise ValueError(f"round {round} already exists")
        self.round_vote_sets[round] = RoundVoteSet(
            prevotes=VoteSet(
                self.chain_id, self.height, round, PREVOTE_TYPE, self.val_set
            ),
            precommits=VoteSet(
                self.chain_id,
                self.height,
                round,
                PRECOMMIT_TYPE,
                self.val_set,
                extensions_enabled=self.extensions_enabled,
            ),
        )

    def set_round(self, round: int) -> None:
        """Create vote sets up to round+1 (height_vote_set.go SetRound)."""
        with self._mtx:
            new_round = self.round - 1 if self.round > 0 else 0
            for r in range(new_round, round + 2):
                if r not in self.round_vote_sets:
                    self._add_round(r)
            self.round = round

    def add_vote(self, vote: Vote, peer_id: str) -> bool:
        """(height_vote_set.go AddVote) — unwanted rounds are limited to
        one peer-triggered catchup round per peer."""
        with self._mtx:
            if not vote.type in (PREVOTE_TYPE, PRECOMMIT_TYPE):
                raise VoteError(f"invalid vote type {vote.type}")
            rvs = self.round_vote_sets.get(vote.round)
            if rvs is None:
                rounds = self.peer_catchup_rounds.setdefault(peer_id, [])
                if len(rounds) < 2:
                    self._add_round(vote.round)
                    rvs = self.round_vote_sets[vote.round]
                    rounds.append(vote.round)
                else:
                    raise VoteError(
                        "peer has sent a vote that does not match our round "
                        "for more than one round"
                    )
            vs = rvs.prevotes if vote.type == PREVOTE_TYPE else rvs.precommits
            return vs.add_vote(vote)

    def prevotes(self, round: int) -> VoteSet | None:
        with self._mtx:
            rvs = self.round_vote_sets.get(round)
            return rvs.prevotes if rvs else None

    def precommits(self, round: int) -> VoteSet | None:
        with self._mtx:
            rvs = self.round_vote_sets.get(round)
            return rvs.precommits if rvs else None

    def pol_info(self) -> tuple[int, BlockID | None]:
        """Last round with a prevote POL (+2/3 for some block)
        (height_vote_set.go POLInfo)."""
        with self._mtx:
            for r in range(self.round, -1, -1):
                rvs = self.round_vote_sets.get(r)
                if rvs is None:
                    continue
                bid, ok = rvs.prevotes.two_thirds_majority()
                if ok:
                    return r, bid
            return -1, None

    def set_peer_maj23(
        self, round: int, vote_type: int, peer_id: str, block_id: BlockID
    ) -> None:
        with self._mtx:
            if round not in self.round_vote_sets:
                return
            rvs = self.round_vote_sets[round]
            vs = rvs.prevotes if vote_type == PREVOTE_TYPE else rvs.precommits
            vs.set_peer_maj23(peer_id, block_id)
