"""Consensus: the Tendermint BFT state machine and its services
(reference: internal/consensus/).
"""

from .wal import WAL, NilWAL, WALSearchOptions

__all__ = ["WAL", "NilWAL", "WALSearchOptions"]
