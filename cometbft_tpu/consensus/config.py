"""Consensus timing configuration (reference: config/config.go
ConsensusConfig; durations in seconds).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ConsensusConfig:
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    peer_gossip_sleep_duration: float = 0.1
    peer_query_maj23_sleep_duration: float = 2.0
    wal_path: str = "data/cs.wal/wal"

    def propose_timeout(self, round: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round

    def prevote_timeout(self, round: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round

    def precommit_timeout(self, round: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round


def test_consensus_config() -> ConsensusConfig:
    """Fast timeouts for in-process tests (config.go TestConsensusConfig)."""
    return ConsensusConfig(
        timeout_propose=0.8,
        timeout_propose_delta=0.2,
        timeout_prevote=0.4,
        timeout_prevote_delta=0.2,
        timeout_precommit=0.4,
        timeout_precommit_delta=0.2,
        peer_gossip_sleep_duration=0.01,
        peer_query_maj23_sleep_duration=0.25,
    )


# not a pytest case, despite the reference-matching name
test_consensus_config.__test__ = False
