"""Write-ahead log of every consensus input (reference:
internal/consensus/wal.go, libs/autofile).

Record framing (wal.go WALEncoder): crc32(4, big-endian) + length(4,
big-endian) + proto(TimedWALMessage).  Files roll at max_file_size like
the reference's autofile.Group (head + .000, .001, ... chunks);
SearchForEndHeight scans for the EndHeight marker so replay can resume
mid-stream after a crash.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib

from ..utils.log import get_logger
from ..utils.service import Service
from ..wire import wal_pb
from ..wire.canonical import Timestamp

MAX_WAL_MSG_SIZE_BYTES = 1024 * 1024 * 2  # wal.go maxMsgSizeBytes
DEFAULT_GROUP_FILE_SIZE = 10 * 1024 * 1024


class WALError(Exception):
    pass


class CorruptWALError(WALError):
    pass


def encode_record(msg: wal_pb.TimedWALMessageProto) -> bytes:
    data = msg.encode()
    if len(data) > MAX_WAL_MSG_SIZE_BYTES:
        raise WALError(f"WAL record too big: {len(data)}")
    crc = zlib.crc32(data) & 0xFFFFFFFF
    return struct.pack(">II", crc, len(data)) + data


def decode_records(buf: bytes):
    """Yield TimedWALMessageProto records; raises CorruptWALError on a
    mangled record (truncated tail is reported as corruption too — the
    caller decides whether to repair)."""
    pos = 0
    n = len(buf)
    while pos < n:
        if n - pos < 8:
            raise CorruptWALError("truncated record header")
        crc, length = struct.unpack_from(">II", buf, pos)
        pos += 8
        if length > MAX_WAL_MSG_SIZE_BYTES:
            raise CorruptWALError(f"record length {length} exceeds max")
        if n - pos < length:
            raise CorruptWALError("truncated record body")
        data = buf[pos : pos + length]
        pos += length
        if zlib.crc32(data) & 0xFFFFFFFF != crc:
            raise CorruptWALError("CRC mismatch")
        try:
            yield wal_pb.TimedWALMessageProto.decode(data)
        except ValueError as e:
            raise CorruptWALError(f"undecodable record: {e}")


class WALSearchOptions:
    def __init__(self, ignore_data_corruption_errors: bool = False):
        self.ignore_data_corruption_errors = ignore_data_corruption_errors


class WAL(Service):
    """File-group-backed WAL (wal.go baseWAL)."""

    def __init__(self, path: str, max_file_size: int = DEFAULT_GROUP_FILE_SIZE):
        super().__init__("WAL")
        self.head_path = path
        self.max_file_size = max_file_size
        self._f = None
        self._mtx = threading.Lock()
        self.logger = get_logger("wal")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    # ------------------------------------------------------------ rolling

    def _chunk_paths(self) -> list[str]:
        """Rolled chunks in order, oldest first, head last."""
        d = os.path.dirname(self.head_path) or "."
        base = os.path.basename(self.head_path)
        chunks = sorted(
            (f for f in os.listdir(d)
             if f.startswith(base + ".") and f.split(".")[-1].isdigit()),
            key=lambda f: int(f.split(".")[-1]),
        )
        out = [os.path.join(d, c) for c in chunks]
        if os.path.exists(self.head_path):
            out.append(self.head_path)
        return out

    def _maybe_roll(self) -> None:
        if self._f.tell() < self.max_file_size:
            return
        # the rolled chunk must be durable before it is renamed — records in
        # it may already have been promised by write_sync
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        d = os.path.dirname(self.head_path) or "."
        base = os.path.basename(self.head_path)
        existing = [
            int(f.split(".")[-1])
            for f in os.listdir(d)
            if f.startswith(base + ".") and f.split(".")[-1].isdigit()
        ]
        idx = max(existing) + 1 if existing else 0
        os.replace(self.head_path, f"{self.head_path}.{idx:03d}")
        dfd = os.open(os.path.dirname(self.head_path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._f = open(self.head_path, "ab")

    # ---------------------------------------------------------- lifecycle

    def on_start(self) -> None:
        self._f = open(self.head_path, "ab")
        # reference writes EndHeight{0} on a fresh WAL (wal.go OnStart)
        if self._f.tell() == 0 and not self._chunk_paths()[:-1]:
            self.write_sync(wal_pb.WALMessageProto(end_height=wal_pb.EndHeightProto(height=0)))

    def on_stop(self) -> None:
        with self._mtx:
            if self._f:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()
                self._f = None

    # ------------------------------------------------------------ writing

    def write(self, msg: wal_pb.WALMessageProto) -> None:
        if self._f is None:
            return
        rec = wal_pb.TimedWALMessageProto(
            time=Timestamp.from_unix_ns(time.time_ns()), msg=msg
        )
        with self._mtx:
            self._f.write(encode_record(rec))
            self._maybe_roll()

    def write_sync(self, msg: wal_pb.WALMessageProto) -> None:
        """Write + fsync — used at signing points and EndHeight
        (wal.go WriteSync)."""
        if self._f is None:
            return
        self.write(msg)
        with self._mtx:
            self._f.flush()
            os.fsync(self._f.fileno())

    def flush_and_sync(self) -> None:
        with self._mtx:
            if self._f:
                self._f.flush()
                os.fsync(self._f.fileno())

    # ------------------------------------------------------------ reading

    def iter_records(self, options: WALSearchOptions | None = None):
        """All records across chunks, oldest first."""
        options = options or WALSearchOptions()
        for path in self._chunk_paths():
            with open(path, "rb") as f:
                buf = f.read()
            try:
                yield from decode_records(buf)
            except CorruptWALError as e:
                if options.ignore_data_corruption_errors:
                    self.logger.error(f"skipping corrupt WAL tail in {path}: {e}")
                    continue
                raise

    def search_for_end_height(
        self, height: int, options: WALSearchOptions | None = None
    ):
        """Records following EndHeight{height}, or None if the marker is
        absent (wal.go:59-69 SearchForEndHeight)."""
        found = False
        out = []
        try:
            for rec in self.iter_records(options):
                m = rec.msg
                if m is not None and m.which() == "end_height":
                    if m.end_height.height == height:
                        found = True
                        out = []
                        continue
                if found:
                    out.append(rec)
        except CorruptWALError:
            if not (options and options.ignore_data_corruption_errors):
                raise
        return out if found else None

    def truncate_corrupt_tail(self) -> int:
        """Repair a torn final write by truncating the head file at the
        last valid record (what the reference's replay 'repair' flow does).
        Returns bytes dropped."""
        if not os.path.exists(self.head_path):
            return 0
        with open(self.head_path, "rb") as f:
            buf = f.read()
        good = 0
        pos = 0
        n = len(buf)
        while pos + 8 <= n:
            crc, length = struct.unpack_from(">II", buf, pos)
            if length > MAX_WAL_MSG_SIZE_BYTES or pos + 8 + length > n:
                break
            data = buf[pos + 8 : pos + 8 + length]
            if zlib.crc32(data) & 0xFFFFFFFF != crc:
                break
            pos += 8 + length
            good = pos
        dropped = n - good
        if dropped:
            with self._mtx:
                reopen = self._f is not None
                if reopen:
                    self._f.close()
                with open(self.head_path, "ab") as f:
                    f.truncate(good)
                if reopen:
                    self._f = open(self.head_path, "ab")
        return dropped


class NilWAL:
    """No-op WAL (wal.go nilWAL)."""

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def is_running(self) -> bool:
        return True

    def write(self, msg) -> None:
        pass

    def write_sync(self, msg) -> None:
        pass

    def flush_and_sync(self) -> None:
        pass

    def iter_records(self, options=None):
        return iter(())

    def search_for_end_height(self, height, options=None):
        return None
