"""Handshaker: reconcile app height with chain height on boot.

On start the node asks the application where it is (ABCI Info) and replays
whatever the app is missing from the block store — or runs InitChain if
the app is at genesis — asserting app-hash equality at every step, so a
node whose application restarted behind the chain (or whose own state
lagged the store after a crash) rejoins cleanly.  Reference:
internal/consensus/replay.go:244 (Handshake), :284 (ReplayBlocks),
:516 (replayBlock), :535-551 (app-hash assertions); exercised by the
reference's replay_test.go crash-at-every-WAL-write suite.

Crash cases covered (replay.go:373-420 case analysis):
  store == state:  app behind  -> replay app-only (no state mutation)
                   app == store -> nothing to do
  store == state+1 (crashed between SaveBlock and state save):
                   app <  state -> replay app-only, then final block
                                   through the real executor
                   app == state -> final block through the real executor
                   app == store -> app ran Commit but state wasn't saved:
                                   re-derive state from the stored
                                   FinalizeBlockResponse (mock app)
"""

from __future__ import annotations

from ..crypto import merkle
from ..mempool.nop import NopMempool
from ..state.execution import (
    BlockExecutor,
    build_last_commit_info,
    validate_validator_updates,
)
from ..types.validators import ValidatorSet
from ..utils.log import get_logger
from ..wire import abci_pb as abci


class HandshakeError(Exception):
    pass


class AppBlockHeightTooLowError(HandshakeError):
    """App height below the truncated store base (state.go ErrAppBlockHeightTooLow)."""

    def __init__(self, app_height: int, store_base: int):
        super().__init__(
            f"app block height {app_height} is below the block store base "
            f"{store_base}; the node cannot replay the missing blocks"
        )


class AppBlockHeightTooHighError(HandshakeError):
    def __init__(self, store_height: int, app_height: int):
        super().__init__(
            f"app block height {app_height} is ahead of the block store "
            f"height {store_height}; the app must never outrun the chain"
        )


class AppHashMismatchError(HandshakeError):
    def __init__(self, got: bytes, want: bytes, where: str):
        super().__init__(
            f"app hash after replay does not match {where}: got {got.hex()}, "
            f"expected {want.hex()} — was the chain reset without resetting "
            f"the application's data?"
        )


class _SavedResponseApp:
    """Stand-in consensus connection replaying a stored
    FinalizeBlockResponse (replay.go newMockProxyApp): used when the app
    already ran Commit for the last block but our state save was lost."""

    def __init__(self, resp: abci.FinalizeBlockResponse):
        self._resp = resp

    def finalize_block(self, req) -> abci.FinalizeBlockResponse:
        return self._resp

    def commit(self, req=None) -> abci.CommitResponse:
        return abci.CommitResponse()


class Handshaker:
    def __init__(
        self,
        state_store,
        initial_state,
        block_store,
        genesis,
        event_bus=None,
    ):
        self.state_store = state_store
        self.initial_state = initial_state
        self.block_store = block_store
        self.genesis = genesis
        self.event_bus = event_bus
        self.logger = get_logger("handshaker")
        self.n_blocks = 0  # blocks replayed, for tests/metrics

    # ------------------------------------------------------------ entry

    def handshake(self, app_conns) -> None:
        """replay.go:244 — Info on the query connection, then replay."""
        res = app_conns.query.info(abci.InfoRequest())
        app_height = res.last_block_height
        if app_height < 0:
            raise HandshakeError(f"app reported negative height {app_height}")
        self.logger.info(
            f"ABCI handshake: app height={app_height} "
            f"hash={res.last_block_app_hash.hex()[:16]}"
        )
        if self.initial_state.last_block_height == 0:
            self.initial_state.app_version = res.app_version
        self.replay_blocks(
            self.initial_state, res.last_block_app_hash, app_height, app_conns
        )
        self.logger.info("ABCI handshake complete: engine and app are synced")

    # ----------------------------------------------------------- replay

    def replay_blocks(
        self, state, app_hash: bytes, app_height: int, app_conns
    ) -> bytes:
        """replay.go:284 — the height-triangle case analysis."""
        store_base = self.block_store.base
        store_height = self.block_store.height
        state_height = state.last_block_height
        self.logger.info(
            f"replay: app={app_height} store={store_height} state={state_height}"
        )

        if app_height == 0:
            app_hash = self._init_chain(state, app_conns)
            state_height = state.last_block_height

        if store_height == 0:
            self._assert_state_hash(app_hash, state)
            return app_hash
        if app_height == 0 and state.initial_height < store_base:
            raise AppBlockHeightTooLowError(app_height, store_base)
        if 0 < app_height < store_base - 1:
            # can be exactly 1 behind the base: we replay the next block
            raise AppBlockHeightTooLowError(app_height, store_base)
        if store_height < app_height:
            raise AppBlockHeightTooHighError(store_height, app_height)
        if store_height < state_height:
            raise HandshakeError(
                f"state height {state_height} ahead of store height "
                f"{store_height}: corrupted stores"
            )
        if store_height > state_height + 1:
            raise HandshakeError(
                f"store height {store_height} more than one ahead of state "
                f"height {state_height}: corrupted stores"
            )

        if store_height == state_height:
            if app_height < store_height:
                return self._replay(state, app_conns, app_height, store_height, False)
            self._assert_state_hash(app_hash, state)
            return app_hash

        # store == state + 1: crashed after SaveBlock, before the state save
        if app_height < state_height:
            return self._replay(state, app_conns, app_height, store_height, True)
        if app_height == state_height:
            # neither we nor the app ran the final block
            state = self._replay_final_block(state, store_height, app_conns.consensus)
            return state.app_hash
        # app_height == store_height: the app ran Commit but our state save
        # was lost — re-derive the state transition from the stored response
        resp = self.state_store.load_finalize_block_response(store_height)
        if resp is None:
            raise HandshakeError(
                f"no stored FinalizeBlockResponse for height {store_height}"
            )
        if not resp.app_hash:
            resp.app_hash = app_hash
        state = self._replay_final_block(
            state, store_height, _SavedResponseApp(resp)
        )
        return state.app_hash

    # --------------------------------------------------------- internals

    def _init_chain(self, state, app_conns) -> bytes:
        """replay.go:305-360 — genesis InitChain + state seeding."""
        g = self.genesis
        req = abci.InitChainRequest(
            time=g.genesis_time,
            chain_id=g.chain_id,
            consensus_params=g.consensus_params.to_proto(),
            validators=[
                abci.ValidatorUpdate(
                    power=v.power,
                    pub_key_type=v.pub_key_type,
                    pub_key_bytes=v.pub_key_bytes,
                )
                for v in g.validators
            ],
            app_state_bytes=g.app_state,
            initial_height=g.initial_height,
        )
        res = app_conns.consensus.init_chain(req)
        app_hash = res.app_hash

        if state.last_block_height == 0:
            if res.app_hash:
                state.app_hash = res.app_hash
            if res.validators:
                vals = validate_validator_updates(
                    res.validators, state.consensus_params
                )
                state.validators = ValidatorSet(vals)
                nxt = ValidatorSet(vals)
                nxt.increment_proposer_priority(1)
                state.next_validators = nxt
            elif not g.validators:
                raise HandshakeError(
                    "validator set is empty in genesis and still empty "
                    "after InitChain"
                )
            if res.consensus_params is not None:
                state.consensus_params = state.consensus_params.update(
                    res.consensus_params
                )
                state.app_version = state.consensus_params.version.app
            state.last_results_hash = merkle.hash_from_byte_slices([])
            self.state_store.save(state)
        return app_hash

    def _replay(
        self, state, app_conns, app_height: int, store_height: int, mutate_state: bool
    ) -> bytes:
        """replay.go:452 replayBlocks — feed stored blocks app-only; when
        mutate_state, the last block goes through the real executor so the
        engine state advances with it."""
        app_hash = b""
        final = store_height - 1 if mutate_state else store_height
        first = app_height + 1
        if first == 1:
            first = state.initial_height
        for h in range(first, final + 1):
            block = self.block_store.load_block(h)
            if block is None:
                raise HandshakeError(f"block {h} missing from store during replay")
            if app_hash and block.header.app_hash != app_hash:
                raise AppHashMismatchError(
                    app_hash, block.header.app_hash, f"block {h} header"
                )
            self.logger.info(f"replaying block {h} into the app")
            app_hash = self._exec_commit_block(app_conns.consensus, block, store_height)
            self.n_blocks += 1
        if mutate_state:
            state = self._replay_final_block(
                state, store_height, app_conns.consensus
            )
            app_hash = state.app_hash
        self._assert_state_hash(app_hash, state)
        return app_hash

    def _exec_commit_block(self, consensus_conn, block, store_height: int) -> bytes:
        """state.ExecCommitBlock: FinalizeBlock + Commit with no engine
        state mutation (the state snapshots for these heights are already
        persisted or never needed)."""
        h = block.header.height
        vals = self.state_store.load_validators(h - 1) if h > 1 else None
        commit_info = (
            build_last_commit_info(block, vals, self.initial_state.initial_height)
            if vals is not None
            else abci.CommitInfo()
        )
        resp = consensus_conn.finalize_block(
            abci.FinalizeBlockRequest(
                txs=block.data.txs,
                decided_last_commit=commit_info,
                misbehavior=[],
                hash=block.hash(),
                height=h,
                time=block.header.time,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=block.header.proposer_address,
                syncing_to_height=store_height,
            )
        )
        if len(resp.tx_results) != len(block.data.txs):
            raise HandshakeError(
                f"replay height {h}: app returned {len(resp.tx_results)} tx "
                f"results for {len(block.data.txs)} txs"
            )
        consensus_conn.commit()
        return resp.app_hash

    def _replay_final_block(self, state, height: int, consensus_conn):
        """replay.go:516 replayBlock — the last block runs through a real
        BlockExecutor (nop mempool/evidence) so the state transition is
        recomputed and saved."""
        block = self.block_store.load_block(height)
        meta = self.block_store.load_block_meta(height)
        if block is None or meta is None:
            raise HandshakeError(f"final block {height} missing from store")
        from ..types.block import BlockID

        executor = BlockExecutor(
            self.state_store,
            consensus_conn,
            NopMempool(),
            block_store=self.block_store,
            event_bus=self.event_bus,
        )
        new_state = executor.apply_block(
            state, BlockID.from_proto(meta.block_id), block, height
        )
        self.n_blocks += 1
        # propagate: callers hold a reference to the original state object
        for f in new_state.__dataclass_fields__:
            setattr(state, f, getattr(new_state, f))
        return state

    def _assert_state_hash(self, app_hash: bytes, state) -> None:
        if app_hash != state.app_hash:
            raise AppHashMismatchError(app_hash, state.app_hash, "engine state")
