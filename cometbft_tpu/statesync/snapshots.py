"""Snapshot pool: candidate snapshots advertised by peers
(reference: statesync/snapshots.go).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""
    trusted_app_hash: bytes = b""  # filled by the syncer after light verify

    def key(self) -> tuple:
        return (self.height, self.format, self.hash)


class SnapshotPool:
    """Tracks snapshots and which peers can serve them; Best() prefers
    the newest height, then the highest format (snapshots.go Best)."""

    def __init__(self):
        self._mtx = threading.Lock()
        self._snapshots: dict[tuple, Snapshot] = {}
        self._peers: dict[tuple, set[str]] = {}
        self._rejected: set[tuple] = set()
        self._rejected_formats: set[int] = set()
        self._rejected_peers: set[str] = set()

    def add(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Returns True if this snapshot is new to the pool."""
        k = snapshot.key()
        with self._mtx:
            if (
                k in self._rejected
                or snapshot.format in self._rejected_formats
                or peer_id in self._rejected_peers
            ):
                return False
            new = k not in self._snapshots
            self._snapshots.setdefault(k, snapshot)
            self._peers.setdefault(k, set()).add(peer_id)
            return new

    def best(self) -> Snapshot | None:
        with self._mtx:
            candidates = sorted(
                self._snapshots.values(),
                key=lambda s: (s.height, s.format),
                reverse=True,
            )
            return candidates[0] if candidates else None

    def peers_of(self, snapshot: Snapshot) -> list[str]:
        with self._mtx:
            return list(self._peers.get(snapshot.key(), ()))

    def reject(self, snapshot: Snapshot) -> None:
        with self._mtx:
            k = snapshot.key()
            self._rejected.add(k)
            self._snapshots.pop(k, None)
            self._peers.pop(k, None)

    def reject_format(self, fmt: int) -> None:
        with self._mtx:
            self._rejected_formats.add(fmt)
            for k in [k for k in self._snapshots if k[1] == fmt]:
                self._snapshots.pop(k, None)
                self._peers.pop(k, None)

    def reject_peer(self, peer_id: str) -> None:
        with self._mtx:
            self._rejected_peers.add(peer_id)
            for k, peers in list(self._peers.items()):
                peers.discard(peer_id)
                if not peers:
                    self._snapshots.pop(k, None)
                    self._peers.pop(k, None)

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            for k, peers in list(self._peers.items()):
                peers.discard(peer_id)
                # snapshots with no remaining peers are unusable
                if not peers:
                    self._snapshots.pop(k, None)
                    self._peers.pop(k, None)
