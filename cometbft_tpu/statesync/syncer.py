"""Statesync syncer: restore app state from a peer snapshot
(reference: statesync/syncer.go).

Flow (syncer.go:144 SyncAny / :236 Sync): pick the best advertised
snapshot → light-verify its app hash → OfferSnapshot to the app → fetch
chunks from peers (the reactor feeds add_chunk) while applying them in
order → verify the app's restored hash/height via Info → hand back the
light-verified State + Commit for the stores.
"""

from __future__ import annotations

import threading
import time

from ..utils.log import get_logger
from ..wire import abci_pb as abci
from .chunks import Chunk, ChunkQueue
from .snapshots import Snapshot, SnapshotPool


class StatesyncError(Exception):
    pass


class ErrNoSnapshots(StatesyncError):
    pass


class ErrAbort(StatesyncError):
    pass


class ErrRejectSnapshot(StatesyncError):
    pass


class ErrRejectFormat(StatesyncError):
    pass


class ErrRejectSender(StatesyncError):
    pass


class ErrRetrySnapshot(StatesyncError):
    pass


class ErrChunkTimeout(StatesyncError):
    pass


CHUNK_TIMEOUT = 30.0
CHUNK_FETCHERS = 4


class Syncer:
    def __init__(
        self,
        state_provider,
        snapshot_conn,  # abci client, snapshot connection
        query_conn,  # abci client, query connection (Info)
        request_chunk,  # callable(peer_id, snapshot, index)
        chunk_fetchers: int = CHUNK_FETCHERS,
        chunk_timeout: float = CHUNK_TIMEOUT,
    ):
        self.state_provider = state_provider
        self.snapshot_conn = snapshot_conn
        self.query_conn = query_conn
        self.request_chunk = request_chunk
        self.chunk_fetchers = chunk_fetchers
        self.chunk_timeout = chunk_timeout
        self.snapshots = SnapshotPool()
        self.logger = get_logger("statesync")
        self._mtx = threading.Lock()
        self._chunks: ChunkQueue | None = None

    # ---------------------------------------------------------- pool feeds

    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Reactor feed: a peer advertised a snapshot (syncer.go:108).
        Light-verify the app hash up front so garbage never enters the
        pool."""
        try:
            snapshot.trusted_app_hash = self.state_provider.app_hash(
                snapshot.height
            )
        except Exception as e:  # noqa: BLE001
            self.logger.info(
                f"failed to verify app hash for snapshot at height "
                f"{snapshot.height}: {e}"
            )
            return False
        added = self.snapshots.add(peer_id, snapshot)
        if added:
            self.logger.info(
                f"discovered new snapshot height={snapshot.height} "
                f"format={snapshot.format} hash={snapshot.hash.hex()[:12]}"
            )
        return added

    def add_chunk(self, chunk: Chunk) -> bool:
        with self._mtx:
            q = self._chunks
        if q is None:
            return False
        return q.add(chunk)

    # ------------------------------------------------------------- syncing

    def sync_any(
        self,
        discovery_time: float,
        max_discovery_time: float,
        retry_hook=None,
    ):
        """syncer.go:144 — wait for snapshots, then drive Sync with
        rejection/retry handling.  Returns (state, commit)."""
        start = time.monotonic()
        time.sleep(discovery_time)
        snapshot, chunks = None, None
        while True:
            if snapshot is None:
                snapshot = self.snapshots.best()
                chunks = None
            if snapshot is None:
                if (
                    max_discovery_time > 0
                    and time.monotonic() - start >= max_discovery_time
                ):
                    raise ErrNoSnapshots("no viable snapshots discovered")
                if retry_hook:
                    retry_hook()
                time.sleep(discovery_time)
                continue
            if chunks is None:
                chunks = ChunkQueue(snapshot)
            try:
                return self.sync(snapshot, chunks)
            except ErrRetrySnapshot:
                chunks.retry_all()
                self.logger.info(f"retrying snapshot {snapshot.height}")
                continue
            except ErrChunkTimeout:
                self.snapshots.reject(snapshot)
                self.logger.error(
                    f"timed out fetching chunks; rejected snapshot "
                    f"{snapshot.height}"
                )
            except ErrRejectSnapshot:
                self.snapshots.reject(snapshot)
                self.logger.info(f"snapshot {snapshot.height} rejected")
            except ErrRejectFormat:
                self.snapshots.reject_format(snapshot.format)
                self.logger.info(f"snapshot format {snapshot.format} rejected")
            except ErrRejectSender:
                self.logger.info("snapshot senders rejected")
                for peer in self.snapshots.peers_of(snapshot):
                    self.snapshots.reject_peer(peer)
            finally:
                if chunks is not None and (snapshot is None or chunks.done()):
                    pass
            snapshot, chunks = None, None

    def sync(self, snapshot: Snapshot, chunks: ChunkQueue):
        """syncer.go:236 — one restoration attempt."""
        with self._mtx:
            if self._chunks is not None:
                raise StatesyncError("a state sync is already in progress")
            self._chunks = chunks
        stop_fetch = threading.Event()
        try:
            if not snapshot.trusted_app_hash:
                snapshot.trusted_app_hash = self.state_provider.app_hash(
                    snapshot.height
                )

            self._offer_snapshot(snapshot)

            for i in range(self.chunk_fetchers):
                threading.Thread(
                    target=self._fetch_chunks,
                    args=(snapshot, chunks, stop_fetch),
                    daemon=True,
                    name=f"statesync-fetch-{i}",
                ).start()

            # optimistically build the post-snapshot state so light-client
            # failures surface before the expensive restore
            state = self.state_provider.state(snapshot.height)
            commit = self.state_provider.commit(snapshot.height)

            self._apply_chunks(snapshot, chunks)
            self._verify_app(snapshot, state.app_version)
            self.logger.info(
                f"snapshot restored height={snapshot.height} "
                f"hash={snapshot.hash.hex()[:12]}"
            )
            return state, commit
        finally:
            stop_fetch.set()
            chunks.close()
            with self._mtx:
                self._chunks = None

    # ------------------------------------------------------------ internals

    def _offer_snapshot(self, snapshot: Snapshot) -> None:
        """syncer.go:317."""
        resp = self.snapshot_conn.offer_snapshot(
            abci.OfferSnapshotRequest(
                snapshot=abci.Snapshot(
                    height=snapshot.height,
                    format=snapshot.format,
                    chunks=snapshot.chunks,
                    hash=snapshot.hash,
                    metadata=snapshot.metadata,
                ),
                app_hash=snapshot.trusted_app_hash,
            )
        )
        r = resp.result
        if r == abci.OFFER_SNAPSHOT_RESULT_ACCEPT:
            return
        if r == abci.OFFER_SNAPSHOT_RESULT_ABORT:
            raise ErrAbort("app aborted the snapshot offer")
        if r == abci.OFFER_SNAPSHOT_RESULT_REJECT:
            raise ErrRejectSnapshot("app rejected the snapshot")
        if r == abci.OFFER_SNAPSHOT_RESULT_REJECT_FORMAT:
            raise ErrRejectFormat("app rejected the snapshot format")
        if r == abci.OFFER_SNAPSHOT_RESULT_REJECT_SENDER:
            raise ErrRejectSender("app rejected the snapshot senders")
        raise StatesyncError(f"unknown OfferSnapshot result {r}")

    def _fetch_chunks(self, snapshot, chunks, stop: threading.Event) -> None:
        """syncer.go:410 — request allocations until the queue is done."""
        while not stop.is_set() and not chunks.done():
            index = chunks.allocate()
            if index is None:
                time.sleep(0.05)
                continue
            peers = self.snapshots.peers_of(snapshot)
            if not peers:
                chunks.retry(index)
                time.sleep(0.2)
                continue
            peer = peers[index % len(peers)]
            try:
                self.request_chunk(peer, snapshot, index)
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"chunk request to {peer} failed: {e}")
                chunks.retry(index)
                time.sleep(0.2)

    def _apply_chunks(self, snapshot, chunks: ChunkQueue) -> None:
        """syncer.go:353."""
        while True:
            chunk = chunks.next(timeout=self.chunk_timeout)
            if chunk is None:
                if chunks.done():
                    return
                raise ErrChunkTimeout("timed out waiting for a chunk")
            resp = self.snapshot_conn.apply_snapshot_chunk(
                abci.ApplySnapshotChunkRequest(
                    index=chunk.index, chunk=chunk.chunk, sender=chunk.sender
                )
            )
            for index in resp.refetch_chunks or []:
                chunks.discard(index)
            for sender in resp.reject_senders or []:
                if sender:
                    self.snapshots.reject_peer(sender)
                    chunks.discard_sender(sender)
            r = resp.result
            if r == abci.APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT:
                continue
            if r == abci.APPLY_SNAPSHOT_CHUNK_RESULT_ABORT:
                raise ErrAbort("app aborted chunk application")
            if r == abci.APPLY_SNAPSHOT_CHUNK_RESULT_RETRY:
                chunks.retry(chunk.index)
            elif r == abci.APPLY_SNAPSHOT_CHUNK_RESULT_RETRY_SNAPSHOT:
                raise ErrRetrySnapshot("app asked to retry the snapshot")
            elif r == abci.APPLY_SNAPSHOT_CHUNK_RESULT_REJECT_SNAPSHOT:
                raise ErrRejectSnapshot("app rejected the snapshot mid-restore")
            else:
                raise StatesyncError(f"unknown ApplySnapshotChunk result {r}")

    def _verify_app(self, snapshot: Snapshot, app_version: int) -> None:
        """syncer.go verifyApp: the restored app must report the snapshot
        height and the light-verified hash."""
        resp = self.query_conn.info(abci.InfoRequest())
        if resp.last_block_app_hash != snapshot.trusted_app_hash:
            raise StatesyncError(
                f"restored app hash {resp.last_block_app_hash.hex()} does "
                f"not match trusted hash {snapshot.trusted_app_hash.hex()}"
            )
        if resp.last_block_height != snapshot.height:
            raise StatesyncError(
                f"restored app height {resp.last_block_height} does not "
                f"match snapshot height {snapshot.height}"
            )
        if app_version and resp.app_version != app_version:
            raise StatesyncError(
                f"restored app version {resp.app_version} does not match "
                f"state app version {app_version}"
            )
