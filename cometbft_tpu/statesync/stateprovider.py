"""State provider backed by the light client
(reference: statesync/stateprovider.go:39-91).

Everything a freshly statesynced node trusts — the app hash it restores
against, the Commit it stores, the State it boots from — is verified
through light-client bisection from a social-consensus root of trust.
"""

from __future__ import annotations

import threading

from ..light import Client, LightStore, TrustOptions
from ..state.state import State
from ..store.db import MemDB
from ..utils.log import get_logger


class StateProviderError(Exception):
    pass


class LightClientStateProvider:
    """app_hash / commit / state for a snapshot height, all light-verified.

    params_source must expose consensus_params(height) -> ConsensusParams;
    the result is checked against the verified header's consensus_hash, so
    a lying source cannot smuggle parameters in (the reference reaches the
    same guarantee via its verifying RPC proxy, lightrpc.Client)."""

    def __init__(
        self,
        chain_id: str,
        initial_height: int,
        primary,
        witnesses: list,
        trust_options: TrustOptions,
        params_source=None,
        now_fn=None,
    ):
        self.chain_id = chain_id
        self.initial_height = initial_height or 1
        self.params_source = params_source or primary
        self.logger = get_logger("stateprovider")
        self._mtx = threading.Lock()  # light.Client is not concurrency-safe
        self.lc = Client(
            chain_id,
            trust_options,
            primary,
            witnesses,
            LightStore(MemDB()),
            now_fn=now_fn,
        )

    def app_hash(self, height: int) -> bytes:
        """The app hash FOR height lives in header height+1; also probe
        height+2 up front so State() can't fail later
        (stateprovider.go:118-135)."""
        with self._mtx:
            header = self.lc.verify_light_block_at_height(height + 1)
            self.lc.verify_light_block_at_height(height + 2)
            return header.signed_header.header.app_hash

    def commit(self, height: int):
        with self._mtx:
            lb = self.lc.verify_light_block_at_height(height)
            return lb.signed_header.commit

    def state(self, height: int) -> State:
        """stateprovider.go:151 — assemble the post-snapshot State from
        the blocks at height, height+1 and height+2."""
        with self._mtx:
            last_lb = self.lc.verify_light_block_at_height(height)
            cur_lb = self.lc.verify_light_block_at_height(height + 1)
            next_lb = self.lc.verify_light_block_at_height(height + 2)

        params = self.params_source.consensus_params(height + 1)
        if params is None:
            raise StateProviderError(
                f"no consensus params available for height {height + 1}"
            )
        if params.hash() != cur_lb.signed_header.header.consensus_hash:
            raise StateProviderError(
                "consensus params do not match the verified header's "
                "consensus hash"
            )

        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=last_lb.height,
            last_block_id=last_lb.signed_header.commit.block_id,
            last_block_time=last_lb.signed_header.header.time,
            next_validators=next_lb.validator_set.copy(),
            validators=cur_lb.validator_set.copy(),
            last_validators=last_lb.validator_set.copy(),
            last_height_validators_changed=next_lb.height,
            consensus_params=params,
            last_height_consensus_params_changed=cur_lb.height,
            last_results_hash=cur_lb.signed_header.header.last_results_hash,
            app_hash=cur_lb.signed_header.header.app_hash,
            app_version=params.version.app,
        )
