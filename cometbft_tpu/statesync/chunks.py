"""Chunk queue for one snapshot restoration
(reference: statesync/chunks.go, redesigned in-memory: chunks are small
relative to host RAM and a condition variable replaces the on-disk spool
+ channel plumbing).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

PENDING, REQUESTED, RECEIVED, DONE = range(4)


@dataclass
class Chunk:
    height: int
    format: int
    index: int
    chunk: bytes
    sender: str


class ChunkQueue:
    def __init__(self, snapshot):
        self.snapshot = snapshot
        self._mtx = threading.Condition()
        self._status = [PENDING] * snapshot.chunks
        self._chunks: dict[int, Chunk] = {}
        self._closed = False

    # ------------------------------------------------------------ fetchers

    def allocate(self) -> int | None:
        """Next chunk index needing a request; None when all are in
        flight or done (chunks.go Allocate)."""
        with self._mtx:
            for i, st in enumerate(self._status):
                if st == PENDING:
                    self._status[i] = REQUESTED
                    return i
            return None

    def add(self, chunk: Chunk) -> bool:
        """A chunk arrived from a peer (chunks.go Add)."""
        with self._mtx:
            if self._closed or not (0 <= chunk.index < len(self._status)):
                return False
            if self._status[chunk.index] in (RECEIVED, DONE):
                return False
            self._chunks[chunk.index] = chunk
            self._status[chunk.index] = RECEIVED
            self._mtx.notify_all()
            return True

    # ------------------------------------------------------------- applier

    def next(self, timeout: float | None = None) -> Chunk | None:
        """Lowest-index received-but-unapplied chunk, blocking until it
        arrives; None when every chunk is DONE or the queue closed."""
        with self._mtx:
            while True:
                if self._closed:
                    return None
                if all(st == DONE for st in self._status):
                    return None
                want = next(
                    (i for i, st in enumerate(self._status) if st != DONE),
                    None,
                )
                if want is not None and self._status[want] == RECEIVED:
                    self._status[want] = DONE
                    return self._chunks[want]
                if not self._mtx.wait(timeout):
                    return None  # timed out

    def retry(self, index: int) -> None:
        with self._mtx:
            if 0 <= index < len(self._status):
                self._status[index] = PENDING
                self._chunks.pop(index, None)
                self._mtx.notify_all()

    def retry_all(self) -> None:
        with self._mtx:
            self._status = [PENDING] * len(self._status)
            self._chunks.clear()
            self._mtx.notify_all()

    def discard(self, index: int) -> None:
        self.retry(index)

    def discard_sender(self, peer_id: str) -> None:
        """Drop unapplied chunks from a rejected sender (chunks.go
        DiscardSender)."""
        with self._mtx:
            for i, ch in list(self._chunks.items()):
                if ch.sender == peer_id and self._status[i] == RECEIVED:
                    self._status[i] = PENDING
                    self._chunks.pop(i)
            self._mtx.notify_all()

    def close(self) -> None:
        with self._mtx:
            self._closed = True
            self._mtx.notify_all()

    def done(self) -> bool:
        with self._mtx:
            return all(st == DONE for st in self._status)
