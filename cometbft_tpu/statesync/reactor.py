"""Statesync reactor: snapshot/chunk exchange + the sync driver
(reference: statesync/reactor.go; streams 0x60/0x61).

Serving side: answers SnapshotsRequest from the app's ListSnapshots and
ChunkRequest from LoadSnapshotChunk — any caught-up node is a snapshot
server with no extra state.

Syncing side: run() discovers snapshots from peers, drives the Syncer,
then bootstraps the stores (state + seen commit) and hands off to
blocksync (switch_to_block_sync), which later hands off to consensus —
the full cold-start pipeline.
"""

from __future__ import annotations

import threading

from ..p2p.conn.connection import StreamDescriptor
from ..p2p.reactor import Reactor
from ..types.msg_validation import validate_statesync_message
from ..utils.log import get_logger
from ..wire import abci_pb as abci
from ..wire import statesync_pb as pb
from .chunks import Chunk
from .snapshots import Snapshot
from .syncer import Syncer

SNAPSHOT_STREAM = 0x60
CHUNK_STREAM = 0x61

MAX_SNAPSHOTS_ADVERTISED = 10  # reactor.go recentSnapshots


class StatesyncReactor(Reactor):
    def __init__(
        self,
        snapshot_conn,  # abci snapshot connection (serving + restoring)
        query_conn,  # abci query connection (Info)
        state_provider=None,  # LightClientStateProvider when syncing
        enabled: bool = False,  # are WE state syncing on boot?
    ):
        super().__init__("StatesyncReactor")
        self.snapshot_conn = snapshot_conn
        self.query_conn = query_conn
        self.state_provider = state_provider
        self.enabled = enabled
        self.logger = get_logger("statesync-reactor")
        self.syncer: Syncer | None = None
        self._synced_callbacks = []
        if enabled and state_provider is not None:
            self.syncer = Syncer(
                state_provider,
                snapshot_conn,
                query_conn,
                self._request_chunk,
            )

    def stream_descriptors(self) -> list[StreamDescriptor]:
        return [
            StreamDescriptor(id=SNAPSHOT_STREAM, priority=5, send_queue_capacity=10),
            StreamDescriptor(id=CHUNK_STREAM, priority=3, send_queue_capacity=16),
        ]

    def on_synced(self, cb) -> None:
        """Register a callback fired with (state, commit) after restore."""
        self._synced_callbacks.append(cb)

    # --------------------------------------------------------------- peers

    def add_peer(self, peer) -> None:
        if self.syncer is not None:
            # ask every new peer what snapshots it has (reactor.go AddPeer)
            peer.try_send(
                SNAPSHOT_STREAM,
                pb.StatesyncMessage(snapshots_request=pb.SnapshotsRequest()).encode(),
            )

    def remove_peer(self, peer, reason: str = "") -> None:
        if self.syncer is not None:
            self.syncer.snapshots.remove_peer(peer.id)

    # ------------------------------------------------------------- receive

    def receive(self, stream_id: int, peer, msg_bytes: bytes) -> None:
        msg = pb.StatesyncMessage.decode(msg_bytes)
        # validate-before-use: snapshot/chunk fields size pool entries
        # and the fetch schedule; a raise here disconnects the peer
        validate_statesync_message(msg)
        which = msg.which()
        if which == "snapshots_request":
            self._serve_snapshots(peer)
        elif which == "snapshots_response":
            if self.syncer is not None:
                m = msg.snapshots_response
                self.syncer.add_snapshot(
                    peer.id,
                    Snapshot(
                        height=m.height,
                        format=m.format,
                        chunks=m.chunks,
                        hash=m.hash,
                        metadata=m.metadata,
                    ),
                )
        elif which == "chunk_request":
            self._serve_chunk(peer, msg.chunk_request)
        elif which == "chunk_response":
            m = msg.chunk_response
            if self.syncer is not None and not m.missing:
                self.syncer.add_chunk(
                    Chunk(
                        height=m.height,
                        format=m.format,
                        index=m.index,
                        chunk=m.chunk,
                        sender=peer.id,
                    )
                )

    def _serve_snapshots(self, peer) -> None:
        """reactor.go:123 — advertise our app's newest snapshots."""
        try:
            resp = self.snapshot_conn.list_snapshots(abci.ListSnapshotsRequest())
        except Exception as e:  # noqa: BLE001
            self.logger.error(f"ListSnapshots failed: {e}")
            return
        snaps = sorted(
            resp.snapshots or [], key=lambda s: (s.height, s.format), reverse=True
        )
        for s in snaps[:MAX_SNAPSHOTS_ADVERTISED]:
            peer.try_send(
                SNAPSHOT_STREAM,
                pb.StatesyncMessage(
                    snapshots_response=pb.SnapshotsResponse(
                        height=s.height,
                        format=s.format,
                        chunks=s.chunks,
                        hash=s.hash,
                        metadata=s.metadata,
                    )
                ).encode(),
            )

    def _serve_chunk(self, peer, req: pb.ChunkRequest) -> None:
        """reactor.go:172 — load the chunk from the app and ship it."""
        try:
            resp = self.snapshot_conn.load_snapshot_chunk(
                abci.LoadSnapshotChunkRequest(
                    height=req.height, format=req.format, chunk=req.index
                )
            )
            chunk = resp.chunk
        except Exception as e:  # noqa: BLE001
            self.logger.error(f"LoadSnapshotChunk failed: {e}")
            chunk = b""
        peer.try_send(
            CHUNK_STREAM,
            pb.StatesyncMessage(
                chunk_response=pb.ChunkResponse(
                    height=req.height,
                    format=req.format,
                    index=req.index,
                    chunk=chunk or b"",
                    missing=not chunk,
                )
            ).encode(),
        )

    def _request_chunk(self, peer_id: str, snapshot, index: int) -> None:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is None:
            raise ConnectionError(f"peer {peer_id} gone")
        peer.try_send(
            CHUNK_STREAM,
            pb.StatesyncMessage(
                chunk_request=pb.ChunkRequest(
                    height=snapshot.height, format=snapshot.format, index=index
                )
            ).encode(),
        )

    # ----------------------------------------------------------- sync run

    def run(
        self,
        state_store,
        block_store,
        discovery_time: float = 2.0,
        max_discovery_time: float = 60.0,
    ) -> None:
        """Kick off the background sync (node/setup.go:569 startStateSync):
        restore → bootstrap stores → hand off to blocksync."""
        if self.syncer is None:
            raise RuntimeError("statesync reactor not configured for syncing")
        threading.Thread(
            target=self._sync_routine,
            args=(state_store, block_store, discovery_time, max_discovery_time),
            daemon=True,
            name="statesync-sync",
        ).start()

    def _sync_routine(
        self, state_store, block_store, discovery_time, max_discovery_time
    ) -> None:
        def rediscover():
            if self.switch is not None:
                self.switch.broadcast(
                    SNAPSHOT_STREAM,
                    pb.StatesyncMessage(
                        snapshots_request=pb.SnapshotsRequest()
                    ).encode(),
                )

        try:
            state, commit = self.syncer.sync_any(
                discovery_time, max_discovery_time, retry_hook=rediscover
            )
        except Exception as e:  # noqa: BLE001
            self.logger.error(f"state sync failed: {e}")
            return
        # persist what blocksync + consensus will build on
        state_store.bootstrap(state)
        block_store.save_seen_commit(state.last_block_height, commit)
        if block_store.height < state.last_block_height:
            block_store.base = state.last_block_height + 1
            block_store.height = state.last_block_height
        self.logger.info(
            f"state synced to height {state.last_block_height}; "
            "handing off to blocksync"
        )
        if self.switch is not None:
            bs = self.switch.reactors.get("BLOCKSYNC")
            if bs is not None and hasattr(bs, "switch_to_block_sync"):
                bs.switch_to_block_sync(state)
        for cb in self._synced_callbacks:
            cb(state, commit)
