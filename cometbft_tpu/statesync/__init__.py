"""Statesync: snapshot-based cold start (reference: statesync/)."""

from .chunks import Chunk, ChunkQueue
from .reactor import CHUNK_STREAM, SNAPSHOT_STREAM, StatesyncReactor
from .snapshots import Snapshot, SnapshotPool
from .stateprovider import LightClientStateProvider, StateProviderError
from .syncer import (
    ErrAbort,
    ErrChunkTimeout,
    ErrNoSnapshots,
    ErrRejectFormat,
    ErrRejectSender,
    ErrRejectSnapshot,
    ErrRetrySnapshot,
    StatesyncError,
    Syncer,
)

__all__ = [
    "StatesyncReactor",
    "SNAPSHOT_STREAM",
    "CHUNK_STREAM",
    "Syncer",
    "Snapshot",
    "SnapshotPool",
    "Chunk",
    "ChunkQueue",
    "LightClientStateProvider",
    "StateProviderError",
    "StatesyncError",
    "ErrNoSnapshots",
    "ErrAbort",
    "ErrRejectSnapshot",
    "ErrRejectFormat",
    "ErrRejectSender",
    "ErrRetrySnapshot",
    "ErrChunkTimeout",
]
