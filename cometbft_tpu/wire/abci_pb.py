"""ABCI protobuf messages: the full request/response set plus persistence
types (field layout mirrors proto/cometbft/abci/v1/types.proto of the
reference; oneof Request/Response numbering at types.proto Request/Response
messages — note the reserved 4,7,9,10 / 5,8,10,11 gaps from removed
SetOption/BeginBlock/DeliverTx/EndBlock).
"""

from __future__ import annotations

from .canonical import Timestamp
from .proto import Message, Field
from .types_pb import ConsensusParamsProto, Duration, ProofOps

# CheckTxType (types.proto:82-91)
CHECK_TX_TYPE_UNKNOWN = 0
CHECK_TX_TYPE_RECHECK = 1
CHECK_TX_TYPE_CHECK = 2

# OfferSnapshotResult (types.proto:331-346)
OFFER_SNAPSHOT_RESULT_UNKNOWN = 0
OFFER_SNAPSHOT_RESULT_ACCEPT = 1
OFFER_SNAPSHOT_RESULT_ABORT = 2
OFFER_SNAPSHOT_RESULT_REJECT = 3
OFFER_SNAPSHOT_RESULT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_RESULT_REJECT_SENDER = 5

# ApplySnapshotChunkResult (types.proto:361-377)
APPLY_SNAPSHOT_CHUNK_RESULT_UNKNOWN = 0
APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT = 1
APPLY_SNAPSHOT_CHUNK_RESULT_ABORT = 2
APPLY_SNAPSHOT_CHUNK_RESULT_RETRY = 3
APPLY_SNAPSHOT_CHUNK_RESULT_RETRY_SNAPSHOT = 4
APPLY_SNAPSHOT_CHUNK_RESULT_REJECT_SNAPSHOT = 5

# ProcessProposalStatus / VerifyVoteExtensionStatus (types.proto:390-426)
PROCESS_PROPOSAL_STATUS_UNKNOWN = 0
PROCESS_PROPOSAL_STATUS_ACCEPT = 1
PROCESS_PROPOSAL_STATUS_REJECT = 2
VERIFY_VOTE_EXTENSION_STATUS_UNKNOWN = 0
VERIFY_VOTE_EXTENSION_STATUS_ACCEPT = 1
VERIFY_VOTE_EXTENSION_STATUS_REJECT = 2

# MisbehaviorType (types.proto:562-572)
MISBEHAVIOR_TYPE_UNKNOWN = 0
MISBEHAVIOR_TYPE_DUPLICATE_VOTE = 1
MISBEHAVIOR_TYPE_LIGHT_CLIENT_ATTACK = 2


class EventAttribute(Message):
    FIELDS = [
        Field(1, "key", "string"),
        Field(2, "value", "string"),
        Field(3, "index", "bool"),
    ]


class Event(Message):
    FIELDS = [
        Field(1, "type", "string"),
        Field(2, "attributes", "message", EventAttribute, repeated=True),
    ]


class ExecTxResult(Message):
    FIELDS = [
        Field(1, "code", "varint"),
        Field(2, "data", "bytes"),
        Field(3, "log", "string"),
        Field(4, "info", "string"),
        Field(5, "gas_wanted", "varint"),
        Field(6, "gas_used", "varint"),
        Field(7, "events", "message", Event, repeated=True),
        Field(8, "codespace", "string"),
    ]


class TxResult(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "index", "varint"),
        Field(3, "tx", "bytes"),
        Field(4, "result", "message", ExecTxResult, emit_default=True),
    ]


class ValidatorUpdate(Message):
    FIELDS = [
        Field(2, "power", "varint"),
        Field(3, "pub_key_bytes", "bytes"),
        Field(4, "pub_key_type", "string"),
    ]


class FinalizeBlockResponse(Message):
    FIELDS = [
        Field(1, "events", "message", Event, repeated=True),
        Field(2, "tx_results", "message", ExecTxResult, repeated=True),
        Field(3, "validator_updates", "message", ValidatorUpdate, repeated=True),
        Field(4, "consensus_param_updates", "message", ConsensusParamsProto),
        Field(5, "app_hash", "bytes"),
        Field(6, "next_block_delay", "message", Duration, emit_default=True),
    ]


# ---------------------------------------------------------------- shared


class ValidatorAbci(Message):
    """abci.Validator (types.proto:524-528): 20-byte address + power."""

    FIELDS = [
        Field(1, "address", "bytes"),
        Field(3, "power", "varint"),
    ]


class VoteInfo(Message):
    FIELDS = [
        Field(1, "validator", "message", ValidatorAbci, emit_default=True),
        Field(3, "block_id_flag", "varint"),
    ]


class ExtendedVoteInfo(Message):
    FIELDS = [
        Field(1, "validator", "message", ValidatorAbci, emit_default=True),
        Field(3, "vote_extension", "bytes"),
        Field(4, "extension_signature", "bytes"),
        Field(5, "block_id_flag", "varint"),
    ]


class CommitInfo(Message):
    FIELDS = [
        Field(1, "round", "varint"),
        Field(2, "votes", "message", VoteInfo, repeated=True),
    ]


class ExtendedCommitInfo(Message):
    FIELDS = [
        Field(1, "round", "varint"),
        Field(2, "votes", "message", ExtendedVoteInfo, repeated=True),
    ]


class Misbehavior(Message):
    FIELDS = [
        Field(1, "type", "varint"),
        Field(2, "validator", "message", ValidatorAbci, emit_default=True),
        Field(3, "height", "varint"),
        Field(4, "time", "message", Timestamp, emit_default=True),
        Field(5, "total_voting_power", "varint"),
    ]


class Snapshot(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "format", "varint"),
        Field(3, "chunks", "varint"),
        Field(4, "hash", "bytes"),
        Field(5, "metadata", "bytes"),
    ]


class LanePriorityEntry(Message):
    """map<string,uint32> entry for InfoResponse.lane_priorities."""

    FIELDS = [
        Field(1, "key", "string"),
        Field(2, "value", "varint"),
    ]


# --------------------------------------------------------------- requests


class EchoRequest(Message):
    FIELDS = [Field(1, "message", "string")]


class FlushRequest(Message):
    FIELDS = []


class InfoRequest(Message):
    FIELDS = [
        Field(1, "version", "string"),
        Field(2, "block_version", "varint"),
        Field(3, "p2p_version", "varint"),
        Field(4, "abci_version", "string"),
    ]


class InitChainRequest(Message):
    FIELDS = [
        Field(1, "time", "message", Timestamp, emit_default=True),
        Field(2, "chain_id", "string"),
        Field(3, "consensus_params", "message", ConsensusParamsProto),
        Field(4, "validators", "message", ValidatorUpdate, repeated=True),
        Field(5, "app_state_bytes", "bytes"),
        Field(6, "initial_height", "varint"),
    ]


class QueryRequest(Message):
    FIELDS = [
        Field(1, "data", "bytes"),
        Field(2, "path", "string"),
        Field(3, "height", "varint"),
        Field(4, "prove", "bool"),
    ]


class CheckTxRequest(Message):
    FIELDS = [
        Field(1, "tx", "bytes"),
        Field(3, "type", "varint"),
    ]


class CommitRequest(Message):
    FIELDS = []


class ListSnapshotsRequest(Message):
    FIELDS = []


class OfferSnapshotRequest(Message):
    FIELDS = [
        Field(1, "snapshot", "message", Snapshot),
        Field(2, "app_hash", "bytes"),
    ]


class LoadSnapshotChunkRequest(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "format", "varint"),
        Field(3, "chunk", "varint"),
    ]


class ApplySnapshotChunkRequest(Message):
    FIELDS = [
        Field(1, "index", "varint"),
        Field(2, "chunk", "bytes"),
        Field(3, "sender", "string"),
    ]


class PrepareProposalRequest(Message):
    FIELDS = [
        Field(1, "max_tx_bytes", "varint"),
        Field(2, "txs", "bytes", repeated=True),
        Field(3, "local_last_commit", "message", ExtendedCommitInfo, emit_default=True),
        Field(4, "misbehavior", "message", Misbehavior, repeated=True),
        Field(5, "height", "varint"),
        Field(6, "time", "message", Timestamp, emit_default=True),
        Field(7, "next_validators_hash", "bytes"),
        Field(8, "proposer_address", "bytes"),
    ]


class ProcessProposalRequest(Message):
    FIELDS = [
        Field(1, "txs", "bytes", repeated=True),
        Field(2, "proposed_last_commit", "message", CommitInfo, emit_default=True),
        Field(3, "misbehavior", "message", Misbehavior, repeated=True),
        Field(4, "hash", "bytes"),
        Field(5, "height", "varint"),
        Field(6, "time", "message", Timestamp, emit_default=True),
        Field(7, "next_validators_hash", "bytes"),
        Field(8, "proposer_address", "bytes"),
    ]


class ExtendVoteRequest(Message):
    FIELDS = [
        Field(1, "hash", "bytes"),
        Field(2, "height", "varint"),
        Field(3, "time", "message", Timestamp, emit_default=True),
        Field(4, "txs", "bytes", repeated=True),
        Field(5, "proposed_last_commit", "message", CommitInfo, emit_default=True),
        Field(6, "misbehavior", "message", Misbehavior, repeated=True),
        Field(7, "next_validators_hash", "bytes"),
        Field(8, "proposer_address", "bytes"),
    ]


class VerifyVoteExtensionRequest(Message):
    FIELDS = [
        Field(1, "hash", "bytes"),
        Field(2, "validator_address", "bytes"),
        Field(3, "height", "varint"),
        Field(4, "vote_extension", "bytes"),
    ]


class FinalizeBlockRequest(Message):
    FIELDS = [
        Field(1, "txs", "bytes", repeated=True),
        Field(2, "decided_last_commit", "message", CommitInfo, emit_default=True),
        Field(3, "misbehavior", "message", Misbehavior, repeated=True),
        Field(4, "hash", "bytes"),
        Field(5, "height", "varint"),
        Field(6, "time", "message", Timestamp, emit_default=True),
        Field(7, "next_validators_hash", "bytes"),
        Field(8, "proposer_address", "bytes"),
        Field(9, "syncing_to_height", "varint"),
    ]


# --------------------------------------------------------------- responses


class ExceptionResponse(Message):
    FIELDS = [Field(1, "error", "string")]


class EchoResponse(Message):
    FIELDS = [Field(1, "message", "string")]


class FlushResponse(Message):
    FIELDS = []


class InfoResponse(Message):
    FIELDS = [
        Field(1, "data", "string"),
        Field(2, "version", "string"),
        Field(3, "app_version", "varint"),
        Field(4, "last_block_height", "varint"),
        Field(5, "last_block_app_hash", "bytes"),
        Field(6, "lane_priorities", "message", LanePriorityEntry, repeated=True),
        Field(7, "default_lane", "string"),
    ]

    def lane_priority_map(self) -> dict[str, int]:
        return {e.key: e.value for e in self.lane_priorities}

    def set_lane_priorities(self, m: dict[str, int]) -> None:
        self.lane_priorities = [
            LanePriorityEntry(key=k, value=v) for k, v in sorted(m.items())
        ]


class InitChainResponse(Message):
    FIELDS = [
        Field(1, "consensus_params", "message", ConsensusParamsProto),
        Field(2, "validators", "message", ValidatorUpdate, repeated=True),
        Field(3, "app_hash", "bytes"),
    ]


class QueryResponse(Message):
    FIELDS = [
        Field(1, "code", "varint"),
        Field(3, "log", "string"),
        Field(4, "info", "string"),
        Field(5, "index", "varint"),
        Field(6, "key", "bytes"),
        Field(7, "value", "bytes"),
        Field(8, "proof_ops", "message", ProofOps),
        Field(9, "height", "varint"),
        Field(10, "codespace", "string"),
    ]


class CheckTxResponse(Message):
    FIELDS = [
        Field(1, "code", "varint"),
        Field(2, "data", "bytes"),
        Field(3, "log", "string"),
        Field(4, "info", "string"),
        Field(5, "gas_wanted", "varint"),
        Field(6, "gas_used", "varint"),
        Field(7, "events", "message", Event, repeated=True),
        Field(8, "codespace", "string"),
        Field(12, "lane_id", "string"),
    ]


class CommitResponse(Message):
    FIELDS = [Field(3, "retain_height", "varint")]


class ListSnapshotsResponse(Message):
    FIELDS = [Field(1, "snapshots", "message", Snapshot, repeated=True)]


class OfferSnapshotResponse(Message):
    FIELDS = [Field(1, "result", "varint")]


class LoadSnapshotChunkResponse(Message):
    FIELDS = [Field(1, "chunk", "bytes")]


class ApplySnapshotChunkResponse(Message):
    FIELDS = [
        Field(1, "result", "varint"),
        Field(2, "refetch_chunks", "varint", repeated=True, packed=True),
        Field(3, "reject_senders", "string", repeated=True),
    ]


class PrepareProposalResponse(Message):
    FIELDS = [Field(1, "txs", "bytes", repeated=True)]


class ProcessProposalResponse(Message):
    FIELDS = [Field(1, "status", "varint")]


class ExtendVoteResponse(Message):
    FIELDS = [Field(1, "vote_extension", "bytes")]


class VerifyVoteExtensionResponse(Message):
    FIELDS = [Field(1, "status", "varint")]


# ----------------------------------------------------- oneof socket frames


class Request(Message):
    """oneof wrapper for the socket protocol (types.proto Request; field
    numbers 4,7,9,10 reserved by the removed legacy methods)."""

    FIELDS = [
        Field(1, "echo", "message", EchoRequest),
        Field(2, "flush", "message", FlushRequest),
        Field(3, "info", "message", InfoRequest),
        Field(5, "init_chain", "message", InitChainRequest),
        Field(6, "query", "message", QueryRequest),
        Field(8, "check_tx", "message", CheckTxRequest),
        Field(11, "commit", "message", CommitRequest),
        Field(12, "list_snapshots", "message", ListSnapshotsRequest),
        Field(13, "offer_snapshot", "message", OfferSnapshotRequest),
        Field(14, "load_snapshot_chunk", "message", LoadSnapshotChunkRequest),
        Field(15, "apply_snapshot_chunk", "message", ApplySnapshotChunkRequest),
        Field(16, "prepare_proposal", "message", PrepareProposalRequest),
        Field(17, "process_proposal", "message", ProcessProposalRequest),
        Field(18, "extend_vote", "message", ExtendVoteRequest),
        Field(19, "verify_vote_extension", "message", VerifyVoteExtensionRequest),
        Field(20, "finalize_block", "message", FinalizeBlockRequest),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None

    def value(self):
        w = self.which()
        return getattr(self, w) if w else None


class Response(Message):
    """oneof wrapper (types.proto Response; 5,8,10,11 reserved)."""

    FIELDS = [
        Field(1, "exception", "message", ExceptionResponse),
        Field(2, "echo", "message", EchoResponse),
        Field(3, "flush", "message", FlushResponse),
        Field(4, "info", "message", InfoResponse),
        Field(6, "init_chain", "message", InitChainResponse),
        Field(7, "query", "message", QueryResponse),
        Field(9, "check_tx", "message", CheckTxResponse),
        Field(12, "commit", "message", CommitResponse),
        Field(13, "list_snapshots", "message", ListSnapshotsResponse),
        Field(14, "offer_snapshot", "message", OfferSnapshotResponse),
        Field(15, "load_snapshot_chunk", "message", LoadSnapshotChunkResponse),
        Field(16, "apply_snapshot_chunk", "message", ApplySnapshotChunkResponse),
        Field(17, "prepare_proposal", "message", PrepareProposalResponse),
        Field(18, "process_proposal", "message", ProcessProposalResponse),
        Field(19, "extend_vote", "message", ExtendVoteResponse),
        Field(20, "verify_vote_extension", "message", VerifyVoteExtensionResponse),
        Field(21, "finalize_block", "message", FinalizeBlockResponse),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None

    def value(self):
        w = self.which()
        return getattr(self, w) if w else None
