"""ABCI protobuf messages needed for persistence and the socket protocol
(field layout mirrors proto/cometbft/abci/v1/types.proto of the reference).
"""

from __future__ import annotations

from .proto import Message, Field
from .types_pb import ConsensusParamsProto, Duration


class EventAttribute(Message):
    FIELDS = [
        Field(1, "key", "string"),
        Field(2, "value", "string"),
        Field(3, "index", "bool"),
    ]


class Event(Message):
    FIELDS = [
        Field(1, "type", "string"),
        Field(2, "attributes", "message", EventAttribute, repeated=True),
    ]


class ExecTxResult(Message):
    FIELDS = [
        Field(1, "code", "varint"),
        Field(2, "data", "bytes"),
        Field(3, "log", "string"),
        Field(4, "info", "string"),
        Field(5, "gas_wanted", "varint"),
        Field(6, "gas_used", "varint"),
        Field(7, "events", "message", Event, repeated=True),
        Field(8, "codespace", "string"),
    ]


class TxResult(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "index", "varint"),
        Field(3, "tx", "bytes"),
        Field(4, "result", "message", ExecTxResult, emit_default=True),
    ]


class ValidatorUpdate(Message):
    FIELDS = [
        Field(2, "power", "varint"),
        Field(3, "pub_key_bytes", "bytes"),
        Field(4, "pub_key_type", "string"),
    ]


class FinalizeBlockResponse(Message):
    FIELDS = [
        Field(1, "events", "message", Event, repeated=True),
        Field(2, "tx_results", "message", ExecTxResult, repeated=True),
        Field(3, "validator_updates", "message", ValidatorUpdate, repeated=True),
        Field(4, "consensus_param_updates", "message", ConsensusParamsProto),
        Field(5, "app_hash", "bytes"),
        Field(6, "next_block_delay", "message", Duration, emit_default=True),
    ]
