"""Remote-signer wire messages (field layout mirrors
proto/cometbft/privval/v1/types.proto of the reference).
"""

from __future__ import annotations

from .proto import Field, Message
from .types_pb import Proposal, Vote


class RemoteSignerError(Message):
    FIELDS = [
        Field(1, "code", "varint"),
        Field(2, "description", "string"),
    ]


class PubKeyRequest(Message):
    FIELDS = [Field(1, "chain_id", "string")]


class PubKeyResponse(Message):
    FIELDS = [
        Field(2, "error", "message", RemoteSignerError),
        Field(3, "pub_key_bytes", "bytes"),
        Field(4, "pub_key_type", "string"),
    ]


class SignVoteRequest(Message):
    FIELDS = [
        Field(1, "vote", "message", Vote),
        Field(2, "chain_id", "string"),
        Field(3, "skip_extension_signing", "bool"),
    ]


class SignedVoteResponse(Message):
    FIELDS = [
        Field(1, "vote", "message", Vote, emit_default=True),
        Field(2, "error", "message", RemoteSignerError),
    ]


class SignProposalRequest(Message):
    FIELDS = [
        Field(1, "proposal", "message", Proposal),
        Field(2, "chain_id", "string"),
    ]


class SignedProposalResponse(Message):
    FIELDS = [
        Field(1, "proposal", "message", Proposal, emit_default=True),
        Field(2, "error", "message", RemoteSignerError),
    ]


class SignBytesRequest(Message):
    FIELDS = [Field(1, "value", "bytes")]


class SignBytesResponse(Message):
    FIELDS = [
        Field(1, "signature", "bytes"),
        Field(2, "error", "message", RemoteSignerError),
    ]


class PingRequest(Message):
    FIELDS = []


class PingResponse(Message):
    FIELDS = []


class PrivvalMessage(Message):
    """The oneof envelope on the signer socket."""

    FIELDS = [
        Field(1, "pub_key_request", "message", PubKeyRequest),
        Field(2, "pub_key_response", "message", PubKeyResponse),
        Field(3, "sign_vote_request", "message", SignVoteRequest),
        Field(4, "signed_vote_response", "message", SignedVoteResponse),
        Field(5, "sign_proposal_request", "message", SignProposalRequest),
        Field(6, "signed_proposal_response", "message", SignedProposalResponse),
        Field(7, "ping_request", "message", PingRequest),
        Field(8, "ping_response", "message", PingResponse),
        Field(9, "sign_bytes_request", "message", SignBytesRequest),
        Field(10, "sign_bytes_response", "message", SignBytesResponse),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None
