"""Data-companion service wire messages (field layouts mirror
proto/cometbft/services/{block,block_results,version,pruning}/v1 of the
reference).  Served over BOTH companion transports: the real gRPC
services (rpc/grpc_services.py, the reference's exact service paths)
and the varint-framed socket substitute (rpc/services.py — the framing
the ABCI and privval sidecar protocols use).
"""

from __future__ import annotations

from .proto import Field, Message
from .types_pb import BlockProto, BlockID
from .abci_pb import ExecTxResult, Event, ValidatorUpdate


# ---- block service (services/block/v1/block_service.proto)


class GetByHeightRequest(Message):
    FIELDS = [Field(1, "height", "varint")]


class GetByHeightResponse(Message):
    FIELDS = [
        Field(1, "block_id", "message", BlockID),
        Field(2, "block", "message", BlockProto),
    ]


class GetLatestHeightRequest(Message):
    FIELDS = []


class GetLatestHeightResponse(Message):
    FIELDS = [Field(1, "height", "varint")]


# ---- block-results service (services/block_results/v1)


class GetBlockResultsRequest(Message):
    FIELDS = [Field(1, "height", "varint")]


class GetBlockResultsResponse(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "tx_results", "message", ExecTxResult, repeated=True),
        Field(3, "finalize_block_events", "message", Event, repeated=True),
        Field(4, "validator_updates", "message", ValidatorUpdate, repeated=True),
        Field(5, "app_hash", "bytes"),
    ]


# ---- version service (services/version/v1)


class GetVersionRequest(Message):
    FIELDS = []


class GetVersionResponse(Message):
    FIELDS = [
        Field(1, "node", "string"),
        Field(2, "abci", "string"),
        Field(3, "p2p", "varint"),
        Field(4, "block", "varint"),
    ]


# ---- pruning service (services/pruning/v1) — privileged


class SetBlockRetainHeightRequest(Message):
    FIELDS = [Field(1, "height", "varint")]


class GetBlockRetainHeightResponse(Message):
    FIELDS = [
        Field(1, "app_retain_height", "varint"),
        Field(2, "pruning_service_retain_height", "varint"),
    ]


class SetBlockResultsRetainHeightRequest(Message):
    FIELDS = [Field(1, "height", "varint")]


class GetBlockResultsRetainHeightResponse(Message):
    FIELDS = [Field(1, "pruning_service_retain_height", "varint")]


class SetTxIndexerRetainHeightRequest(Message):
    FIELDS = [Field(1, "height", "varint")]


class GetTxIndexerRetainHeightResponse(Message):
    FIELDS = [Field(1, "height", "varint")]


class SetBlockIndexerRetainHeightRequest(Message):
    FIELDS = [Field(1, "height", "varint")]


class GetBlockIndexerRetainHeightResponse(Message):
    FIELDS = [Field(1, "height", "varint")]


class Empty(Message):
    FIELDS = []


# ---- envelope: method-routed request/response with stream support


class ServiceRequest(Message):
    """One call frame: method name + encoded payload.  id correlates
    responses; a server-streaming method keeps emitting responses with
    the same id until cancel or disconnect."""

    FIELDS = [
        Field(1, "id", "varint"),
        Field(2, "method", "string"),
        Field(3, "payload", "bytes"),
    ]


class ServiceResponse(Message):
    FIELDS = [
        Field(1, "id", "varint"),
        Field(2, "error", "string"),
        Field(3, "payload", "bytes"),
        Field(4, "end_stream", "varint"),
    ]
