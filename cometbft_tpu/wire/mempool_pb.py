"""Mempool wire messages (field layout mirrors
proto/cometbft/mempool/v1/types.proto of the reference).
"""

from __future__ import annotations

from .proto import Field, Message


class Txs(Message):
    FIELDS = [Field(1, "txs", "bytes", repeated=True)]


class MempoolMessage(Message):
    """The oneof envelope carried on the mempool stream."""

    FIELDS = [Field(1, "txs", "message", Txs)]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None
