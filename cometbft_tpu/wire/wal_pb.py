"""Consensus WAL record messages (field layout mirrors
proto/cometbft/consensus/v1/wal.proto of the reference).

Every consensus input is wrapped in a TimedWALMessage and CRC-framed by
consensus/wal.py; EndHeight marks a completed height for replay.
"""

from __future__ import annotations

from .canonical import Timestamp
from .proto import Field, Message
from .types_pb import Part, Proposal, Vote


class MsgInfoProto(Message):
    """A peer message entering the state machine (wal.proto MsgInfo)."""

    FIELDS = [
        Field(1, "vote", "message", Vote),
        Field(2, "proposal", "message", Proposal),
        Field(3, "block_part", "message", Part),
        Field(4, "block_part_height", "varint"),
        Field(5, "block_part_round", "varint"),
        Field(6, "peer_id", "string"),
        # PBTS: proposal timeliness is judged by receive time, so replay
        # must restore it (the reference persists ReceiveTime in its WAL
        # msgInfo for the same reason)
        Field(7, "receive_time_ns", "varint"),
    ]


class TimeoutInfoProto(Message):
    FIELDS = [
        Field(1, "duration_ms", "varint"),
        Field(2, "height", "varint"),
        Field(3, "round", "varint"),
        Field(4, "step", "varint"),
    ]


class EndHeightProto(Message):
    FIELDS = [Field(1, "height", "varint")]


class EventDataRoundStateProto(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "step", "string"),
    ]


class WALMessageProto(Message):
    """oneof wrapper (wal.proto WALMessage)."""

    FIELDS = [
        Field(1, "event_data_round_state", "message", EventDataRoundStateProto),
        Field(2, "msg_info", "message", MsgInfoProto),
        Field(3, "timeout_info", "message", TimeoutInfoProto),
        Field(4, "end_height", "message", EndHeightProto),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None


class TimedWALMessageProto(Message):
    FIELDS = [
        Field(1, "time", "message", Timestamp, emit_default=True),
        Field(2, "msg", "message", WALMessageProto),
    ]
