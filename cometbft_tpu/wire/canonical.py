"""Canonical sign-bytes messages (reference: proto/cometbft/types/v1/
canonical.proto; serialization entry points types/vote.go VoteSignBytes and
types/proposal.go ProposalSignBytes).

These byte strings are what validators sign and what the TPU batch
verifier hashes — they are consensus-critical and must be deterministic:
sfixed64 height/round (fixed-size, canonical), ascending field order,
non-nullable timestamps always emitted (gogoproto semantics), and the
whole message varint-length-delimited (protoio MarshalDelimited).
"""

from __future__ import annotations

from .proto import Message, Field, encode_delimited, encode_varint

# SignedMsgType enum (types.proto SIGNED_MSG_TYPE_*)
UNKNOWN_TYPE = 0
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


class Timestamp(Message):
    """google.protobuf.Timestamp: UTC wall time as (seconds, nanos)."""

    FIELDS = [
        Field(1, "seconds", "varint"),
        Field(2, "nanos", "varint"),
    ]

    @classmethod
    def from_unix_ns(cls, ns: int) -> "Timestamp":
        return cls(seconds=ns // 1_000_000_000, nanos=ns % 1_000_000_000)

    def unix_ns(self) -> int:
        return self.seconds * 1_000_000_000 + self.nanos

    @classmethod
    def now(cls) -> "Timestamp":
        import time

        return cls.from_unix_ns(time.time_ns())

    def __lt__(self, other):
        return self.unix_ns() < other.unix_ns()

    def __le__(self, other):
        return self.unix_ns() <= other.unix_ns()

    def __hash__(self):
        return hash(self.unix_ns())


class CanonicalPartSetHeader(Message):
    FIELDS = [
        Field(1, "total", "varint"),
        Field(2, "hash", "bytes"),
    ]


class CanonicalBlockID(Message):
    FIELDS = [
        Field(1, "hash", "bytes"),
        Field(2, "part_set_header", "message", CanonicalPartSetHeader, emit_default=True),
    ]


class CanonicalVote(Message):
    FIELDS = [
        Field(1, "type", "varint"),
        Field(2, "height", "sfixed64"),
        Field(3, "round", "sfixed64"),
        Field(4, "block_id", "message", CanonicalBlockID),  # nil when voting nil
        Field(5, "timestamp", "message", Timestamp, emit_default=True),
        Field(6, "chain_id", "string"),
    ]


class CanonicalProposal(Message):
    FIELDS = [
        Field(1, "type", "varint"),
        Field(2, "height", "sfixed64"),
        Field(3, "round", "sfixed64"),
        Field(4, "pol_round", "varint"),
        Field(5, "block_id", "message", CanonicalBlockID),
        Field(6, "timestamp", "message", Timestamp, emit_default=True),
        Field(7, "chain_id", "string"),
    ]


class CanonicalVoteExtension(Message):
    FIELDS = [
        Field(1, "extension", "bytes"),
        Field(2, "height", "sfixed64"),
        Field(3, "round", "sfixed64"),
        Field(4, "chain_id", "string"),
    ]


def vote_sign_bytes(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: CanonicalBlockID | None,
    timestamp: Timestamp,
) -> bytes:
    """The exact bytes a validator signs for a vote (types/vote.go:VoteSignBytes)."""
    cv = CanonicalVote(
        type=msg_type,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp=timestamp,
        chain_id=chain_id,
    )
    return encode_delimited(cv)


class _CanonicalVotePrefix(Message):
    """Fields 1-4 of CanonicalVote — everything before the timestamp.
    Derived from CanonicalVote.FIELDS so an edit there cannot silently
    diverge this consensus-critical fast path."""

    FIELDS = [f for f in CanonicalVote.FIELDS if f.num < 5]


class _CanonicalVoteSuffix(Message):
    FIELDS = [f for f in CanonicalVote.FIELDS if f.num > 5]


_TS_TAG = bytes([5 << 3 | 2])  # field 5, length-delimited


def make_vote_sign_bytes_batch(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: CanonicalBlockID | None,
):
    """Returns sign_bytes(timestamp) closing over the once-encoded
    prefix (fields 1-4) and suffix (chain_id): only the ~13-byte
    timestamp message re-encodes per signature.  For a 10k-validator
    commit this is the difference between 10k full canonical encodes
    and 10k tiny splices on the batch-assembly hot path
    (types/validation.go:324 does the full encode per sig).
    Byte-identical to vote_sign_bytes (differential-tested)."""
    prefix = _CanonicalVotePrefix(
        type=msg_type, height=height, round=round_, block_id=block_id
    ).encode()
    suffix = _CanonicalVoteSuffix(chain_id=chain_id).encode()

    def sign_bytes(timestamp: Timestamp) -> bytes:
        ts_payload = timestamp.encode()
        body = (
            prefix
            + _TS_TAG
            + encode_varint(len(ts_payload))
            + ts_payload
            + suffix
        )
        return encode_varint(len(body)) + body

    return sign_bytes


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: CanonicalBlockID | None,
    timestamp: Timestamp,
) -> bytes:
    """Bytes signed for a proposal (types/proposal.go:ProposalSignBytes)."""
    cp = CanonicalProposal(
        type=PROPOSAL_TYPE,
        height=height,
        round=round_,
        pol_round=pol_round,
        block_id=block_id,
        timestamp=timestamp,
        chain_id=chain_id,
    )
    return encode_delimited(cp)


def vote_extension_sign_bytes(
    chain_id: str, height: int, round_: int, extension: bytes
) -> bytes:
    """Bytes signed for a vote extension (types/vote.go:VoteExtensionSignBytes)."""
    ve = CanonicalVoteExtension(
        extension=extension, height=height, round=round_, chain_id=chain_id
    )
    return encode_delimited(ve)
