"""Domain protobuf messages (field layout mirrors the public definitions in
proto/cometbft/{types,crypto,version}/v1/*.proto of the reference).

Only the messages the framework needs are declared; the declarative codec
in wire/proto.py replaces gogoproto codegen.  `emit_default=True` marks
gogoproto.nullable=false embedded messages (always serialized).
"""

from __future__ import annotations

from .proto import Message, Field
from .canonical import Timestamp


class Duration(Message):
    """google.protobuf.Duration."""

    FIELDS = [
        Field(1, "seconds", "varint"),
        Field(2, "nanos", "varint"),
    ]

    @classmethod
    def from_ns(cls, ns: int) -> "Duration":
        return cls(seconds=ns // 1_000_000_000, nanos=ns % 1_000_000_000)

    def ns(self) -> int:
        return self.seconds * 1_000_000_000 + self.nanos


class Int64Value(Message):
    """google.protobuf.Int64Value wrapper."""

    FIELDS = [Field(1, "value", "varint")]


class StringValue(Message):
    FIELDS = [Field(1, "value", "string")]


class BytesValue(Message):
    FIELDS = [Field(1, "value", "bytes")]


# ------------------------------------------------------- version/v1


class Consensus(Message):
    """cometbft.version.v1.Consensus (block protocol + app version)."""

    FIELDS = [
        Field(1, "block", "varint"),
        Field(2, "app", "varint"),
    ]


# ------------------------------------------------------- crypto/v1


class PublicKey(Message):
    """cometbft.crypto.v1.PublicKey — oneof over key types; at most one of
    the fields is non-empty."""

    FIELDS = [
        Field(1, "ed25519", "bytes"),
        Field(2, "secp256k1", "bytes"),
        Field(3, "bls12381", "bytes"),
        Field(4, "secp256k1eth", "bytes"),
    ]


class Proof(Message):
    FIELDS = [
        Field(1, "total", "varint"),
        Field(2, "index", "varint"),
        Field(3, "leaf_hash", "bytes"),
        Field(4, "aunts", "bytes", repeated=True),
    ]


class ValueOpProto(Message):
    FIELDS = [
        Field(1, "key", "bytes"),
        Field(2, "proof", "message", Proof),
    ]


class ProofOpProto(Message):
    FIELDS = [
        Field(1, "type", "string"),
        Field(2, "key", "bytes"),
        Field(3, "data", "bytes"),
    ]


class ProofOps(Message):
    FIELDS = [Field(1, "ops", "message", ProofOpProto, repeated=True)]


# ------------------------------------------------------- types/v1 core


class PartSetHeader(Message):
    FIELDS = [
        Field(1, "total", "varint"),
        Field(2, "hash", "bytes"),
    ]


class Part(Message):
    FIELDS = [
        Field(1, "index", "varint"),
        Field(2, "bytes", "bytes"),
        Field(3, "proof", "message", Proof, emit_default=True),
    ]


class BlockID(Message):
    FIELDS = [
        Field(1, "hash", "bytes"),
        Field(2, "part_set_header", "message", PartSetHeader, emit_default=True),
    ]


class Header(Message):
    FIELDS = [
        Field(1, "version", "message", Consensus, emit_default=True),
        Field(2, "chain_id", "string"),
        Field(3, "height", "varint"),
        Field(4, "time", "message", Timestamp, emit_default=True),
        Field(5, "last_block_id", "message", BlockID, emit_default=True),
        Field(6, "last_commit_hash", "bytes"),
        Field(7, "data_hash", "bytes"),
        Field(8, "validators_hash", "bytes"),
        Field(9, "next_validators_hash", "bytes"),
        Field(10, "consensus_hash", "bytes"),
        Field(11, "app_hash", "bytes"),
        Field(12, "last_results_hash", "bytes"),
        Field(13, "evidence_hash", "bytes"),
        Field(14, "proposer_address", "bytes"),
    ]


class Data(Message):
    FIELDS = [Field(1, "txs", "bytes", repeated=True)]


class Vote(Message):
    FIELDS = [
        Field(1, "type", "varint"),
        Field(2, "height", "varint"),
        Field(3, "round", "varint"),
        Field(4, "block_id", "message", BlockID, emit_default=True),
        Field(5, "timestamp", "message", Timestamp, emit_default=True),
        Field(6, "validator_address", "bytes"),
        Field(7, "validator_index", "varint"),
        Field(8, "signature", "bytes"),
        Field(9, "extension", "bytes"),
        Field(10, "extension_signature", "bytes"),
    ]


class CommitSig(Message):
    FIELDS = [
        Field(1, "block_id_flag", "varint"),
        Field(2, "validator_address", "bytes"),
        Field(3, "timestamp", "message", Timestamp, emit_default=True),
        Field(4, "signature", "bytes"),
    ]


class Commit(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "block_id", "message", BlockID, emit_default=True),
        Field(4, "signatures", "message", CommitSig, repeated=True),
    ]


class ExtendedCommitSig(Message):
    FIELDS = [
        Field(1, "block_id_flag", "varint"),
        Field(2, "validator_address", "bytes"),
        Field(3, "timestamp", "message", Timestamp, emit_default=True),
        Field(4, "signature", "bytes"),
        Field(5, "extension", "bytes"),
        Field(6, "extension_signature", "bytes"),
    ]


class ExtendedCommit(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "block_id", "message", BlockID, emit_default=True),
        Field(4, "extended_signatures", "message", ExtendedCommitSig, repeated=True),
    ]


class Proposal(Message):
    FIELDS = [
        Field(1, "type", "varint"),
        Field(2, "height", "varint"),
        Field(3, "round", "varint"),
        Field(4, "pol_round", "varint"),
        Field(5, "block_id", "message", BlockID, emit_default=True),
        Field(6, "timestamp", "message", Timestamp, emit_default=True),
        Field(7, "signature", "bytes"),
    ]


# ------------------------------------------------------- validator/v1

# BlockIDFlag enum
BLOCK_ID_FLAG_UNKNOWN = 0
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3


class Validator(Message):
    FIELDS = [
        Field(1, "address", "bytes"),
        Field(2, "pub_key", "message", PublicKey),
        Field(3, "voting_power", "varint"),
        Field(4, "proposer_priority", "varint"),
        Field(5, "pub_key_bytes", "bytes"),
        Field(6, "pub_key_type", "string"),
    ]


class ValidatorSet(Message):
    FIELDS = [
        Field(1, "validators", "message", Validator, repeated=True),
        Field(2, "proposer", "message", Validator),
        Field(3, "total_voting_power", "varint"),
    ]


class SimpleValidator(Message):
    """Hashed into Header.validators_hash (validator.proto SimpleValidator)."""

    FIELDS = [
        Field(1, "pub_key", "message", PublicKey),
        Field(2, "voting_power", "varint"),
    ]


# ------------------------------------------------------- composite


class SignedHeader(Message):
    FIELDS = [
        Field(1, "header", "message", Header),
        Field(2, "commit", "message", Commit),
    ]


class LightBlockProto(Message):
    FIELDS = [
        Field(1, "signed_header", "message", SignedHeader),
        Field(2, "validator_set", "message", ValidatorSet),
    ]


class BlockMeta(Message):
    FIELDS = [
        Field(1, "block_id", "message", BlockID, emit_default=True),
        Field(2, "block_size", "varint"),
        Field(3, "header", "message", Header, emit_default=True),
        Field(4, "num_txs", "varint"),
    ]


class TxProof(Message):
    FIELDS = [
        Field(1, "root_hash", "bytes"),
        Field(2, "data", "bytes"),
        Field(3, "proof", "message", Proof),
    ]


# ------------------------------------------------------- evidence/v1


class DuplicateVoteEvidenceProto(Message):
    FIELDS = [
        Field(1, "vote_a", "message", Vote),
        Field(2, "vote_b", "message", Vote),
        Field(3, "total_voting_power", "varint"),
        Field(4, "validator_power", "varint"),
        Field(5, "timestamp", "message", Timestamp, emit_default=True),
    ]


class LightClientAttackEvidenceProto(Message):
    FIELDS = [
        Field(1, "conflicting_block", "message", LightBlockProto),
        Field(2, "common_height", "varint"),
        Field(3, "byzantine_validators", "message", Validator, repeated=True),
        Field(4, "total_voting_power", "varint"),
        Field(5, "timestamp", "message", Timestamp, emit_default=True),
    ]


class EvidenceProto(Message):
    """oneof sum — exactly one field set."""

    FIELDS = [
        Field(1, "duplicate_vote_evidence", "message", DuplicateVoteEvidenceProto),
        Field(2, "light_client_attack_evidence", "message", LightClientAttackEvidenceProto),
    ]


class EvidenceListProto(Message):
    FIELDS = [Field(1, "evidence", "message", EvidenceProto, repeated=True)]


class BlockProto(Message):
    FIELDS = [
        Field(1, "header", "message", Header, emit_default=True),
        Field(2, "data", "message", Data, emit_default=True),
        Field(3, "evidence", "message", EvidenceListProto, emit_default=True),
        Field(4, "last_commit", "message", Commit),
    ]


# ------------------------------------------------------- params/v1


class BlockParams(Message):
    FIELDS = [
        Field(1, "max_bytes", "varint"),
        Field(2, "max_gas", "varint"),
    ]


class EvidenceParams(Message):
    FIELDS = [
        Field(1, "max_age_num_blocks", "varint"),
        Field(2, "max_age_duration", "message", Duration, emit_default=True),
        Field(3, "max_bytes", "varint"),
    ]


class ValidatorParams(Message):
    FIELDS = [Field(1, "pub_key_types", "string", repeated=True)]


class VersionParams(Message):
    FIELDS = [Field(1, "app", "varint")]


class ABCIParams(Message):
    FIELDS = [Field(1, "vote_extensions_enable_height", "varint")]


class SynchronyParams(Message):
    FIELDS = [
        Field(1, "precision", "message", Duration),
        Field(2, "message_delay", "message", Duration),
    ]


class FeatureParams(Message):
    FIELDS = [
        Field(1, "vote_extensions_enable_height", "message", Int64Value),
        Field(2, "pbts_enable_height", "message", Int64Value),
    ]


class ConsensusParamsProto(Message):
    FIELDS = [
        Field(1, "block", "message", BlockParams),
        Field(2, "evidence", "message", EvidenceParams),
        Field(3, "validator", "message", ValidatorParams),
        Field(4, "version", "message", VersionParams),
        Field(5, "abci", "message", ABCIParams),
        Field(6, "synchrony", "message", SynchronyParams),
        Field(7, "feature", "message", FeatureParams),
    ]


class HashedParams(Message):
    """Subset hashed into Header.consensus_hash (params.proto HashedParams)."""

    FIELDS = [
        Field(1, "block_max_bytes", "varint"),
        Field(2, "block_max_gas", "varint"),
    ]
