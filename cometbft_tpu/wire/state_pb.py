"""State persistence protos (layout mirrors proto/cometbft/state/v1/types.proto)."""

from __future__ import annotations

from .proto import Message, Field
from .canonical import Timestamp
from .types_pb import (
    BlockID,
    Consensus,
    ConsensusParamsProto,
    Duration,
    ValidatorSet,
)
from .abci_pb import FinalizeBlockResponse


class Version(Message):
    FIELDS = [
        Field(1, "consensus", "message", Consensus, emit_default=True),
        Field(2, "software", "string"),
    ]


class StateProto(Message):
    FIELDS = [
        Field(1, "version", "message", Version, emit_default=True),
        Field(2, "chain_id", "string"),
        Field(3, "last_block_height", "varint"),
        Field(4, "last_block_id", "message", BlockID, emit_default=True),
        Field(5, "last_block_time", "message", Timestamp, emit_default=True),
        Field(6, "next_validators", "message", ValidatorSet),
        Field(7, "validators", "message", ValidatorSet),
        Field(8, "last_validators", "message", ValidatorSet),
        Field(9, "last_height_validators_changed", "varint"),
        Field(10, "consensus_params", "message", ConsensusParamsProto, emit_default=True),
        Field(11, "last_height_consensus_params_changed", "varint"),
        Field(12, "last_results_hash", "bytes"),
        Field(13, "app_hash", "bytes"),
        Field(14, "initial_height", "varint"),
        Field(15, "next_block_delay", "message", Duration, emit_default=True),
    ]


class ValidatorsInfo(Message):
    FIELDS = [
        Field(1, "validator_set", "message", ValidatorSet),
        Field(2, "last_height_changed", "varint"),
    ]


class ConsensusParamsInfo(Message):
    FIELDS = [
        Field(1, "consensus_params", "message", ConsensusParamsProto, emit_default=True),
        Field(2, "last_height_changed", "varint"),
    ]


class ABCIResponsesInfo(Message):
    FIELDS = [
        Field(2, "height", "varint"),
        Field(3, "finalize_block", "message", FinalizeBlockResponse),
    ]
