"""Minimal deterministic protobuf wire codec.

Implements the subset of proto3 + gogoproto semantics the framework's
messages use (reference wire behavior: gogoproto-generated Marshal in
/root/reference/api/, framing in libs/protoio/{writer,reader}.go):

  - varint / zigzag / fixed64 / sfixed64 / fixed32 scalars
  - length-delimited bytes / string / embedded messages
  - repeated fields (unpacked for messages/bytes, packed for scalars)
  - zero scalars and nil submessages are omitted; fields marked
    emit_default (gogoproto.nullable=false embedded messages) are always
    written; output is in ascending field order — byte-deterministic,
    which sign-bytes and hashing require
  - varint-length-delimited framing (MarshalDelimited) for streams

Messages are declared as dataclass-like classes with a FIELDS spec; this
replaces the reference's 173k LoC of generated Go with ~300 lines.
"""

from __future__ import annotations

import struct
from typing import Any, NamedTuple

# ---------------------------------------------------------------- varint


def encode_varint(n: int) -> bytes:
    if n < 0:
        n &= (1 << 64) - 1  # two's-complement, 10 bytes, like protobuf int64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(buf: bytes, pos: int = 0) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 64:
                raise ValueError("varint overflows 64 bits")
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n >= 0 else ((-n) << 1) - 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _to_signed64(n: int) -> int:
    return n - (1 << 64) if n >= 1 << 63 else n


# ------------------------------------------------------------- field spec

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_BYTES = 2
_WIRE_FIXED32 = 5

_WIRETYPE = {
    "varint": _WIRE_VARINT,
    "bool": _WIRE_VARINT,
    "zigzag": _WIRE_VARINT,
    "fixed64": _WIRE_FIXED64,
    "sfixed64": _WIRE_FIXED64,
    "double": _WIRE_FIXED64,
    "fixed32": _WIRE_FIXED32,
    "bytes": _WIRE_BYTES,
    "string": _WIRE_BYTES,
    "message": _WIRE_BYTES,
}


class Field(NamedTuple):
    num: int
    name: str
    kind: str  # key of _WIRETYPE
    msg: Any = None  # Message subclass when kind == "message"
    repeated: bool = False
    packed: bool = False  # packed repeated scalars
    emit_default: bool = False  # gogoproto.nullable=false embedded msg


def _default_for(f: Field):
    if f.repeated:
        return []
    return {
        "varint": 0,
        "zigzag": 0,
        "fixed64": 0,
        "sfixed64": 0,
        "fixed32": 0,
        "double": 0.0,
        "bool": False,
        "bytes": b"",
        "string": "",
        "message": None,
    }[f.kind]


def _encode_scalar(kind: str, v) -> bytes:
    if kind in ("varint",):
        return encode_varint(int(v))
    if kind == "bool":
        return encode_varint(1 if v else 0)
    if kind == "zigzag":
        return encode_varint(_zigzag(int(v)))
    if kind == "fixed64":
        return struct.pack("<Q", int(v) & ((1 << 64) - 1))
    if kind == "sfixed64":
        return struct.pack("<q", int(v))
    if kind == "double":
        return struct.pack("<d", float(v))
    if kind == "fixed32":
        return struct.pack("<I", int(v) & 0xFFFFFFFF)
    raise ValueError(f"not a scalar kind: {kind}")


def _is_default(f: Field, v) -> bool:
    if f.repeated:
        return not v
    if f.kind == "message":
        return v is None
    if f.kind in ("bytes", "string"):
        return len(v) == 0
    if f.kind == "bool":
        return not v
    return v == 0


class Message:
    """Base class; subclasses set FIELDS: list[Field]."""

    FIELDS: list[Field] = []

    def __init__(self, **kwargs):
        spec = {f.name: f for f in self.FIELDS}
        for f in self.FIELDS:
            setattr(self, f.name, _default_for(f))
        for k, v in kwargs.items():
            if k not in spec:
                raise TypeError(f"{type(self).__name__} has no field {k!r}")
            setattr(self, k, v)

    def __eq__(self, other):
        return type(self) is type(other) and all(
            getattr(self, f.name) == getattr(other, f.name) for f in self.FIELDS
        )

    def __repr__(self):
        kv = ", ".join(
            f"{f.name}={getattr(self, f.name)!r}"
            for f in self.FIELDS
            if not _is_default(f, getattr(self, f.name))
        )
        return f"{type(self).__name__}({kv})"

    # ------------------------------------------------------------ encode

    def encode(self) -> bytes:
        out = bytearray()
        for f in sorted(self.FIELDS, key=lambda f: f.num):
            v = getattr(self, f.name)
            if not f.emit_default and _is_default(f, v):
                continue
            key = encode_varint(f.num << 3 | _WIRETYPE[f.kind])
            if f.repeated:
                if f.packed and f.kind not in ("bytes", "string", "message"):
                    payload = b"".join(_encode_scalar(f.kind, x) for x in v)
                    out += encode_varint(f.num << 3 | _WIRE_BYTES)
                    out += encode_varint(len(payload)) + payload
                else:
                    for x in v:
                        out += key + self._encode_one(f, x)
            else:
                if f.emit_default and v is None and f.kind == "message":
                    v = f.msg()
                out += key + self._encode_one(f, v)
        return bytes(out)

    @staticmethod
    def _encode_one(f: Field, v) -> bytes:
        if f.kind == "message":
            payload = v.encode()
            return encode_varint(len(payload)) + payload
        if f.kind == "string":
            payload = v.encode("utf-8")
            return encode_varint(len(payload)) + payload
        if f.kind == "bytes":
            return encode_varint(len(v)) + bytes(v)
        return _encode_scalar(f.kind, v)

    # ------------------------------------------------------------ decode

    @classmethod
    def decode(cls, buf: bytes):
        msg = cls()
        by_num = {f.num: f for f in cls.FIELDS}
        pos = 0
        while pos < len(buf):
            key, pos = decode_varint(buf, pos)
            num, wt = key >> 3, key & 7
            f = by_num.get(num)
            if f is None:
                pos = _skip(buf, pos, wt)
                continue
            if wt == _WIRE_BYTES and f.kind not in ("bytes", "string", "message"):
                if not f.repeated:
                    raise ValueError(
                        f"field {f.name}: length-delimited data for scalar field"
                    )
                # packed repeated scalars
                ln, pos = decode_varint(buf, pos)
                end = pos + ln
                if end > len(buf):
                    raise ValueError("truncated packed field")
                vals = getattr(msg, f.name)
                while pos < end:
                    v, pos = _decode_scalar(f, buf, pos)
                    vals.append(v)
                if pos != end:
                    raise ValueError("packed field overran its length")
                continue
            v, pos = cls._decode_one(f, buf, pos, wt)
            if f.repeated:
                getattr(msg, f.name).append(v)
            else:
                setattr(msg, f.name, v)
        return msg

    @staticmethod
    def _decode_one(f: Field, buf: bytes, pos: int, wt: int):
        if f.kind in ("bytes", "string", "message"):
            if wt != _WIRE_BYTES:
                raise ValueError(f"field {f.name}: bad wire type {wt}")
            ln, pos = decode_varint(buf, pos)
            if pos + ln > len(buf):
                raise ValueError("truncated length-delimited field")
            payload = buf[pos : pos + ln]
            pos += ln
            if f.kind == "message":
                return f.msg.decode(payload), pos
            if f.kind == "string":
                return payload.decode("utf-8"), pos
            return bytes(payload), pos
        return _decode_scalar(f, buf, pos)


def _decode_scalar(f: Field, buf: bytes, pos: int):
    if f.kind in ("varint", "bool", "zigzag"):
        v, pos = decode_varint(buf, pos)
        if f.kind == "bool":
            return bool(v), pos
        if f.kind == "zigzag":
            return _unzigzag(v), pos
        return _to_signed64(v), pos
    width = 4 if f.kind == "fixed32" else 8
    if pos + width > len(buf):
        raise ValueError("truncated fixed-width field")
    if f.kind == "fixed64":
        return struct.unpack_from("<Q", buf, pos)[0], pos + 8
    if f.kind == "sfixed64":
        return struct.unpack_from("<q", buf, pos)[0], pos + 8
    if f.kind == "double":
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if f.kind == "fixed32":
        return struct.unpack_from("<I", buf, pos)[0], pos + 4
    raise ValueError(f"bad scalar kind {f.kind}")


def _skip(buf: bytes, pos: int, wt: int) -> int:
    if wt == _WIRE_VARINT:
        _, pos = decode_varint(buf, pos)
        return pos
    elif wt == _WIRE_FIXED64:
        pos += 8
    elif wt == _WIRE_FIXED32:
        pos += 4
    elif wt == _WIRE_BYTES:
        ln, pos = decode_varint(buf, pos)
        pos += ln
    else:
        raise ValueError(f"unsupported wire type {wt}")
    if pos > len(buf):
        raise ValueError("truncated field")
    return pos


# ----------------------------------------------------------- stream framing


def encode_delimited(msg: Message) -> bytes:
    """Varint-length-prefixed encoding (libs/protoio/writer.go:103)."""
    payload = msg.encode()
    return encode_varint(len(payload)) + payload


def decode_delimited(cls, buf: bytes, pos: int = 0):
    """Returns (message, new_pos) (libs/protoio/reader.go:107)."""
    ln, pos = decode_varint(buf, pos)
    if pos + ln > len(buf):
        raise ValueError("truncated delimited message")
    return cls.decode(buf[pos : pos + ln]), pos + ln
