"""L2 wire format: deterministic protobuf codec + canonical sign-bytes.

The reference's wire layer is 104 .proto files compiled by gogoproto into
173k LoC of generated Go (SURVEY.md §2.2).  Here the same wire format is
produced by a compact declarative codec (wire/proto.py) — field numbers
and types mirror the public proto definitions (proto/cometbft/...), and
encoding follows gogoproto Marshal semantics: zero scalars omitted,
nil submessages omitted, non-nullable submessages always emitted, fields
written in ascending tag order (deterministic — sign-bytes depend on it).
"""

from .proto import (
    Message,
    Field,
    encode_varint,
    decode_varint,
    encode_delimited,
    decode_delimited,
)
from .canonical import Timestamp
