"""Statesync wire messages (field layout mirrors
proto/cometbft/statesync/v1/types.proto of the reference).
"""

from __future__ import annotations

from .proto import Field, Message


class SnapshotsRequest(Message):
    FIELDS = []


class SnapshotsResponse(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "format", "varint"),
        Field(3, "chunks", "varint"),
        Field(4, "hash", "bytes"),
        Field(5, "metadata", "bytes"),
    ]


class ChunkRequest(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "format", "varint"),
        Field(3, "index", "varint"),
    ]


class ChunkResponse(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "format", "varint"),
        Field(3, "index", "varint"),
        Field(4, "chunk", "bytes"),
        Field(5, "missing", "bool"),
    ]


class StatesyncMessage(Message):
    """The oneof envelope carried on the statesync streams."""

    FIELDS = [
        Field(1, "snapshots_request", "message", SnapshotsRequest),
        Field(2, "snapshots_response", "message", SnapshotsResponse),
        Field(3, "chunk_request", "message", ChunkRequest),
        Field(4, "chunk_response", "message", ChunkResponse),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None
