"""Blocksync wire messages (field layout mirrors
proto/cometbft/blocksync/v2/types.proto of the reference).
"""

from __future__ import annotations

from .proto import Field, Message
from .types_pb import BlockProto, ExtendedCommit


class BlockRequest(Message):
    FIELDS = [Field(1, "height", "varint")]


class NoBlockResponse(Message):
    FIELDS = [Field(1, "height", "varint")]


class StatusRequest(Message):
    FIELDS = []


class StatusResponse(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "base", "varint"),
    ]


class BlockResponse(Message):
    FIELDS = [
        Field(1, "block", "message", BlockProto, emit_default=True),
        Field(2, "ext_commit", "message", ExtendedCommit),
    ]


class BlocksyncMessage(Message):
    """The oneof envelope carried on the blocksync stream."""

    FIELDS = [
        Field(1, "block_request", "message", BlockRequest),
        Field(2, "no_block_response", "message", NoBlockResponse),
        Field(3, "block_response", "message", BlockResponse),
        Field(4, "status_request", "message", StatusRequest),
        Field(5, "status_response", "message", StatusResponse),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None
