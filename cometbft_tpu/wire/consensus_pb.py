"""Consensus gossip wire messages (field layout mirrors
proto/cometbft/consensus/v1/types.proto of the reference).
"""

from __future__ import annotations

from .proto import Field, Message
from .types_pb import BlockID, Part, PartSetHeader, Proposal, Vote


class BitArrayProto(Message):
    """libs/bits BitArray: size in bits + u64 words (little-endian bits)."""

    FIELDS = [
        Field(1, "bits", "varint"),
        Field(2, "elems", "fixed64", repeated=True, packed=True),
    ]

    @classmethod
    def from_bools(cls, bools: list[bool]) -> "BitArrayProto":
        words = [0] * ((len(bools) + 63) // 64)
        for i, b in enumerate(bools):
            if b:
                words[i // 64] |= 1 << (i % 64)
        return cls(bits=len(bools), elems=words)

    def to_bools(self) -> list[bool]:
        # allocation is sized by the wire-supplied ``bits``: refuse any
        # claim beyond the words actually carried, so a decoded
        # BitArrayProto(bits=10**9, elems=[]) cannot become a memory
        # bomb (validate_consensus_message checks this too; this guard
        # covers every other caller)
        if self.bits < 0 or self.bits > 64 * len(self.elems):
            raise ValueError(
                f"bit array claims {self.bits} bits but carries "
                f"{len(self.elems)} words"
            )
        out = []
        for i in range(self.bits):
            w = self.elems[i // 64]
            out.append(bool(w >> (i % 64) & 1))
        return out


class NewRoundStep(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "step", "varint"),
        Field(4, "seconds_since_start_time", "varint"),
        Field(5, "last_commit_round", "varint"),
    ]


class NewValidBlock(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "block_part_set_header", "message", PartSetHeader, emit_default=True),
        Field(4, "block_parts", "message", BitArrayProto),
        Field(5, "is_commit", "bool"),
    ]


class ProposalMsg(Message):
    FIELDS = [Field(1, "proposal", "message", Proposal, emit_default=True)]


class ProposalPOL(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "proposal_pol_round", "varint"),
        Field(3, "proposal_pol", "message", BitArrayProto, emit_default=True),
    ]


class BlockPartMsg(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "part", "message", Part, emit_default=True),
    ]


class VoteMsg(Message):
    FIELDS = [Field(1, "vote", "message", Vote)]


class HasVote(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "type", "varint"),
        Field(4, "index", "varint"),
    ]


class VoteSetMaj23(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "type", "varint"),
        Field(4, "block_id", "message", BlockID, emit_default=True),
    ]


class VoteSetBits(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "type", "varint"),
        Field(4, "block_id", "message", BlockID, emit_default=True),
        Field(5, "votes", "message", BitArrayProto, emit_default=True),
    ]


class HasProposalBlockPart(Message):
    FIELDS = [
        Field(1, "height", "varint"),
        Field(2, "round", "varint"),
        Field(3, "index", "varint"),
    ]


class ConsensusMessage(Message):
    """oneof wrapper (types.proto Message)."""

    FIELDS = [
        Field(1, "new_round_step", "message", NewRoundStep),
        Field(2, "new_valid_block", "message", NewValidBlock),
        Field(3, "proposal", "message", ProposalMsg),
        Field(4, "proposal_pol", "message", ProposalPOL),
        Field(5, "block_part", "message", BlockPartMsg),
        Field(6, "vote", "message", VoteMsg),
        Field(7, "has_vote", "message", HasVote),
        Field(8, "vote_set_maj23", "message", VoteSetMaj23),
        Field(9, "vote_set_bits", "message", VoteSetBits),
        Field(10, "has_proposal_block_part", "message", HasProposalBlockPart),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None
