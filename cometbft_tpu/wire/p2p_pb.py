"""P2P wire messages (field layout mirrors proto/cometbft/p2p/v1 of the
reference: conn.proto Packet/PacketMsg/PacketPing/PacketPong, types.proto
NodeInfo, pex.proto).
"""

from __future__ import annotations

from .proto import Field, Message


class PacketPing(Message):
    FIELDS = []


class PacketPong(Message):
    FIELDS = []


class PacketMsg(Message):
    FIELDS = [
        Field(1, "channel_id", "varint"),
        Field(2, "eof", "bool"),
        Field(3, "data", "bytes"),
    ]


class Packet(Message):
    FIELDS = [
        Field(1, "ping", "message", PacketPing),
        Field(2, "pong", "message", PacketPong),
        Field(3, "msg", "message", PacketMsg),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None


class ProtocolVersion(Message):
    FIELDS = [
        Field(1, "p2p", "varint"),
        Field(2, "block", "varint"),
        Field(3, "app", "varint"),
    ]


class NodeInfoOther(Message):
    FIELDS = [
        Field(1, "tx_index", "string"),
        Field(2, "rpc_address", "string"),
    ]


class NodeInfoProto(Message):
    FIELDS = [
        Field(1, "protocol_version", "message", ProtocolVersion, emit_default=True),
        Field(2, "node_id", "string"),
        Field(3, "listen_addr", "string"),
        Field(4, "network", "string"),
        Field(5, "version", "string"),
        Field(6, "channels", "bytes"),
        Field(7, "moniker", "string"),
        Field(8, "other", "message", NodeInfoOther, emit_default=True),
    ]


class PexAddress(Message):
    FIELDS = [Field(3, "url", "string")]


class PexRequest(Message):
    FIELDS = []


class PexAddrs(Message):
    FIELDS = [Field(1, "addrs", "message", PexAddress, repeated=True)]


class PexMessage(Message):
    FIELDS = [
        Field(3, "pex_request", "message", PexRequest),
        Field(4, "pex_addrs", "message", PexAddrs),
    ]

    def which(self) -> str | None:
        for f in self.FIELDS:
            if getattr(self, f.name) is not None:
                return f.name
        return None
