"""Streamed commit-replay pipeline: the blocksync catch-up fast path.

Reference hot path: internal/blocksync/reactor.go:547 — a catching-up
node verifies one historical commit per replayed block with
VerifyCommitLight, serially on CPU.  On TPU the same stream pipelines:
device calls are asynchronous, so while the chip verifies block i the
host assembles block i+1's packed rows, and results are drained a few
blocks behind submission (double buffering).  With the validator set's
comb tables resident (models/comb_verifier.py) each block costs one
~V*130-byte transfer + one kernel dispatch; the doubling chains and
pubkey decompressions that dominate cold verification are gone.

The pipeline is a thin scheduler over the verify service's blocksync
class (verifysvc.ServiceBatchVerifier bound to the stream's comb cache
entry) — all assembly, transfer, and readback logic lives in
models/comb_verifier.py behind the service, so blocksync replay can
never diverge from the consensus verifier's semantics and its batches
never cut ahead of consensus-class work.
"""

from __future__ import annotations

from collections import deque
from typing import Iterable, Iterator


class CommitStreamVerifier:
    """Pipelines comb-cached commit verification over a block stream.

    entry: a models/comb_verifier cache entry for the validator set the
    stream's commits were signed by (blocksync knows the set in advance —
    it fetched the headers first).  depth: how many device calls may be
    in flight before the oldest is drained (2 = classic double buffer).
    """

    def __init__(self, entry, depth: int = 2):
        self._entry = entry
        self._depth = max(1, depth)
        self._inflight: deque = deque()

    def run(
        self, commits: Iterable[list[tuple[bytes, bytes, bytes]]]
    ) -> Iterator[tuple[bool, list[bool]]]:
        """Stream commits (each a list of (pubkey, msg, sig)) through the
        pipeline, yielding (all_ok, per_signature) in order."""
        from ..verifysvc.client import ServiceBatchVerifier
        from ..verifysvc.service import Klass

        for items in commits:
            bv = ServiceBatchVerifier(
                Klass.BLOCKSYNC, mode=("comb", self._entry)
            )
            for pub, msg, sig in items:
                bv.add(pub, msg, sig)
            self._inflight.append((bv, bv.submit()))
            while len(self._inflight) > self._depth:
                done, ticket = self._inflight.popleft()
                yield done.collect(ticket)
        while self._inflight:
            done, ticket = self._inflight.popleft()
            yield done.collect(ticket)
