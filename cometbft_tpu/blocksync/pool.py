"""BlockPool: schedules concurrent block downloads across peers
(reference: internal/blocksync/pool.go:93).

Design notes vs the reference: the reference runs one goroutine per
requester (hundreds live at once).  Python threads are far heavier, so the
pool runs ONE scheduler thread that drives every requester as a small
state record — same observable behavior (bounded per-peer pipelines,
second-peer requests near the pool head, retry timers, peer ban/timeout,
rate-based health checks), different concurrency skeleton.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..utils import healthmon
from ..utils.flowrate import Monitor
from ..utils.log import get_logger
from ..utils.service import Service

MAX_PENDING_REQUESTS_PER_PEER = 20  # pool.go:32
REQUEST_RETRY_SECONDS = 30.0  # pool.go:33
MIN_RECV_RATE = 128 * 1024  # bytes/s, pool.go:41
PEER_CONN_WAIT = 3.0  # pool.go:46
MIN_BLOCKS_FOR_SINGLE_REQUEST = 50  # pool.go:52
REQUEST_INTERVAL = 0.01  # pool.go:56
PEER_TIMEOUT = 15.0  # pool.go:57
BAN_DURATION = 60.0  # pool.go isPeerBanned


@dataclass
class BlockRequest:
    height: int
    peer_id: str


@dataclass
class PeerError(Exception):
    err: str
    peer_id: str


@dataclass
class _Peer:
    """pool.go bpPeer."""

    id: str
    base: int
    height: int
    num_pending: int = 0
    did_timeout: bool = False
    cur_rate: float = 0.0
    deadline: float = 0.0  # monotonic time after which the peer timed out
    recv_monitor: Monitor = field(default_factory=lambda: Monitor(window=2.0))

    def incr_pending(self) -> None:
        if self.num_pending == 0:
            self.recv_monitor.reset()
            self.recv_monitor.set_rate(MIN_RECV_RATE * 2.718)
            self.deadline = time.monotonic() + PEER_TIMEOUT
        self.num_pending += 1

    def decr_pending(self, recv_size: int) -> None:
        self.num_pending -= 1
        if self.num_pending == 0:
            self.deadline = 0.0
        else:
            self.recv_monitor.update(recv_size)
            self.deadline = time.monotonic() + PEER_TIMEOUT


@dataclass
class _Requester:
    """pool.go bpRequester, flattened into a record the scheduler drives."""

    height: int
    peer_id: str = ""
    second_peer_id: str = ""
    got_block_from: str = ""
    block: object = None
    ext_commit: object = None
    retry_at: float = 0.0  # monotonic deadline for re-requesting

    def requested_from(self) -> list[str]:
        return [p for p in (self.peer_id, self.second_peer_id) if p]

    def did_request_from(self, peer_id: str) -> bool:
        return peer_id in (self.peer_id, self.second_peer_id)

    def reset_peer(self, peer_id: str) -> bool:
        """Drop the block if it came from peer_id; clear that slot.
        Returns True if a block was removed."""
        removed = False
        if self.got_block_from == peer_id:
            self.block = None
            self.ext_commit = None
            self.got_block_from = ""
            removed = True
        if self.peer_id == peer_id:
            self.peer_id = ""
        elif self.second_peer_id == peer_id:
            self.second_peer_id = ""
        return removed


class BlockPool(Service):
    """Tracks peers, outstanding block requests, and received blocks.

    send_request(BlockRequest) and send_error(PeerError) are callbacks into
    the reactor (the reference uses channels; callbacks avoid a third
    thread).  Both are invoked WITHOUT the pool lock held.
    """

    def __init__(self, start_height: int, send_request, send_error):
        super().__init__("BlockPool")
        self.start_height = start_height
        self.height = start_height  # lowest height not yet popped
        self._send_request = send_request
        self._send_error = send_error
        self._mtx = threading.RLock()
        self.requesters: dict[int, _Requester] = {}
        self.peers: dict[str, _Peer] = {}
        self.banned: dict[str, float] = {}
        self.max_peer_height = 0
        self.logger = get_logger("blockpool")
        self._start_time = 0.0
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def on_start(self) -> None:
        self._start_time = time.monotonic()
        self._thread = threading.Thread(
            target=self._scheduler_routine, name="blockpool", daemon=True
        )
        self._thread.start()

    def on_stop(self) -> None:
        pass

    # ------------------------------------------------------------ scheduler

    def _scheduler_routine(self) -> None:
        """Single loop doing the work of makeRequestersRoutine plus every
        bpRequester.requestRoutine (pool.go:113,805)."""
        while self.is_running():
            healthmon.beat("blockpool")
            if time.monotonic() - self._start_time < PEER_CONN_WAIT:
                time.sleep(0.05)
                continue
            sends: list[BlockRequest] = []
            with self._mtx:
                self._remove_timedout_peers_locked()
                # grow the requester window
                cap = len(self.peers) * MAX_PENDING_REQUESTS_PER_PEER
                next_height = self.height + len(self.requesters)
                while len(self.requesters) < cap and next_height <= self.max_peer_height:
                    self.requesters[next_height] = _Requester(next_height)
                    next_height += 1
                # drive each requester
                now = time.monotonic()
                for req in self.requesters.values():
                    if req.block is not None:
                        continue
                    if req.retry_at and now >= req.retry_at:
                        # retry everything after a timeout (requestRoutine
                        # retryTimer branch)
                        for pid in req.requested_from():
                            peer = self.peers.get(pid)
                            if peer is not None:
                                peer.num_pending = max(0, peer.num_pending - 1)
                        req.peer_id = ""
                        req.second_peer_id = ""
                        req.retry_at = 0.0
                    if not req.peer_id:
                        peer = self._pick_peer_locked(req.height, req.second_peer_id)
                        if peer is not None:
                            req.peer_id = peer.id
                            req.retry_at = now + REQUEST_RETRY_SECONDS
                            sends.append(BlockRequest(req.height, peer.id))
                    # near the pool head, request from a second peer too
                    # (bpRequester.pickSecondPeerAndSendRequest)
                    if (
                        req.peer_id
                        and not req.second_peer_id
                        and req.height - self.height < MIN_BLOCKS_FOR_SINGLE_REQUEST
                    ):
                        peer = self._pick_peer_locked(req.height, req.peer_id)
                        if peer is not None:
                            req.second_peer_id = peer.id
                            req.retry_at = now + REQUEST_RETRY_SECONDS
                            sends.append(BlockRequest(req.height, peer.id))
            for brq in sends:
                self._send_request(brq)
            time.sleep(REQUEST_INTERVAL if sends else 0.05)
        healthmon.retire("blockpool")

    def _pick_peer_locked(self, height: int, exclude: str) -> _Peer | None:
        """pickIncrAvailablePeer (pool.go:455): best current rate first."""
        best = None
        for peer in self.peers.values():
            if peer.id == exclude or peer.did_timeout:
                continue
            if peer.num_pending >= MAX_PENDING_REQUESTS_PER_PEER:
                continue
            if height < peer.base or height > peer.height:
                continue
            if best is None or peer.cur_rate > best.cur_rate:
                best = peer
        if best is not None:
            best.incr_pending()
        return best

    def _remove_timedout_peers_locked(self) -> None:
        now = time.monotonic()
        errors = []
        for peer in list(self.peers.values()):
            if not peer.did_timeout and peer.num_pending > 0:
                cur_rate = peer.recv_monitor.rate()
                peer.cur_rate = cur_rate
                if cur_rate != 0 and cur_rate < MIN_RECV_RATE:
                    peer.did_timeout = True
                    errors.append(PeerError("peer is not sending us data fast enough", peer.id))
                elif peer.deadline and now > peer.deadline:
                    peer.did_timeout = True
                    errors.append(PeerError("peer did not send us anything", peer.id))
            if peer.did_timeout:
                self._remove_peer_locked(peer.id)
        for pid, when in list(self.banned.items()):
            if time.monotonic() - when >= BAN_DURATION:
                del self.banned[pid]
        for err in errors:
            self._send_error(err)

    # ------------------------------------------------------------- queries

    def is_caught_up(self) -> tuple[bool, int, int]:
        """pool.go:190 IsCaughtUp."""
        with self._mtx:
            if not self.peers:
                return False, self.height, self.max_peer_height
            received_or_timed_out = (
                self.height > self.start_height
                or time.monotonic() - self._start_time > 5.0
            )
            caught_up = received_or_timed_out and (
                self.max_peer_height == 0 or self.height >= self.max_peer_height - 1
            )
            return caught_up, self.height, self.max_peer_height

    def peek_two_blocks(self):
        """Blocks at height and height+1 plus the first's extended commit
        (pool.go:216): the second's LastCommit validates the first."""
        with self._mtx:
            first = second = ext = None
            r = self.requesters.get(self.height)
            if r is not None:
                first, ext = r.block, r.ext_commit
            r2 = self.requesters.get(self.height + 1)
            if r2 is not None:
                second = r2.block
            return first, second, ext

    def peek_block(self, height: int):
        """Block + extended commit buffered at an arbitrary height (the
        reactor's verify-ahead pipeline looks past the head pair).  The
        returned objects may be dropped from the pool at any time (peer
        removal); callers must re-check identity at use time."""
        with self._mtx:
            r = self.requesters.get(height)
            if r is None:
                return None, None
            return r.block, r.ext_commit

    def pop_request(self) -> None:
        """Advance past a verified block (pool.go:234)."""
        with self._mtx:
            if self.height not in self.requesters:
                raise RuntimeError(f"no requester at height {self.height}")
            del self.requesters[self.height]
            self.height += 1

    def max_height(self) -> int:
        with self._mtx:
            return self.max_peer_height

    # --------------------------------------------------------------- peers

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        """Record a peer's advertised chain span (pool.go:351)."""
        with self._mtx:
            peer = self.peers.get(peer_id)
            if peer is not None:
                if base < peer.base or height < peer.height:
                    # a shrinking chain is a lying peer
                    self._remove_peer_locked(peer_id)
                    self.banned[peer_id] = time.monotonic()
                    return
                peer.base, peer.height = base, height
            else:
                if self._is_banned_locked(peer_id):
                    return
                self.peers[peer_id] = _Peer(peer_id, base, height)
            if height > self.max_peer_height:
                self.max_peer_height = height

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self._remove_peer_locked(peer_id)

    def _remove_peer_locked(self, peer_id: str) -> None:
        for req in self.requesters.values():
            if req.did_request_from(peer_id):
                self._redo_locked(req, peer_id)
        peer = self.peers.pop(peer_id, None)
        if peer is not None and peer.height == self.max_peer_height:
            self.max_peer_height = max(
                (p.height for p in self.peers.values()), default=0
            )

    def _is_banned_locked(self, peer_id: str) -> bool:
        return time.monotonic() - self.banned.get(peer_id, -1e9) < BAN_DURATION

    def is_peer_banned(self, peer_id: str) -> bool:
        with self._mtx:
            return self._is_banned_locked(peer_id)

    def _redo_locked(self, req: _Requester, peer_id: str) -> None:
        req.reset_peer(peer_id)
        if not req.requested_from():
            req.retry_at = 0.0  # scheduler re-picks immediately

    def redo_request_from(self, height: int, peer_id: str) -> None:
        """Peer answered NoBlockResponse: retry elsewhere (pool.go:284)."""
        with self._mtx:
            req = self.requesters.get(height)
            if req is not None and req.did_request_from(peer_id):
                peer = self.peers.get(peer_id)
                if peer is not None:
                    peer.num_pending = max(0, peer.num_pending - 1)
                self._redo_locked(req, peer_id)

    def remove_peer_and_redo_all(self, height: int) -> str:
        """Block at `height` failed verification: ban its sender and retry
        everything it owed us (pool.go:269)."""
        with self._mtx:
            req = self.requesters.get(height)
            peer_id = req.got_block_from if req is not None else ""
            if peer_id:
                self._remove_peer_locked(peer_id)
                self.banned[peer_id] = time.monotonic()
            return peer_id

    # -------------------------------------------------------------- blocks

    def add_block(self, peer_id: str, block, ext_commit, block_size: int) -> None:
        """Accept a BlockResponse (pool.go:306).  Raises PeerError for
        protocol violations the reactor should disconnect for."""
        if ext_commit is not None and block.header.height != ext_commit.height:
            raise PeerError(
                f"block height {block.header.height} != extCommit height "
                f"{ext_commit.height}",
                peer_id,
            )
        with self._mtx:
            height = block.header.height
            req = self.requesters.get(height)
            if req is None:
                if height > self.height or height < self.start_height:
                    raise PeerError(
                        f"peer sent us block #{height} we didn't expect", peer_id
                    )
                return  # already-processed duplicate from the slower peer
            if not req.did_request_from(peer_id):
                raise PeerError(
                    f"requested block #{height} from {req.requested_from()}, "
                    f"not {peer_id}",
                    peer_id,
                )
            if req.block is None:
                req.block = block
                req.ext_commit = ext_commit
                req.got_block_from = peer_id
            peer = self.peers.get(peer_id)
            if peer is not None:
                peer.decr_pending(block_size)
