"""Blocksync: catch up to the chain head by downloading committed blocks
from peers instead of replaying consensus (reference: internal/blocksync).
"""

from .pool import BlockPool, BlockRequest, PeerError
from .reactor import BlocksyncReactor, BLOCKSYNC_STREAM

__all__ = [
    "BlockPool",
    "BlockRequest",
    "PeerError",
    "BlocksyncReactor",
    "BLOCKSYNC_STREAM",
]
