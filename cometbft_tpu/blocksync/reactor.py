"""Blocksync reactor: serve blocks to catching-up peers and drive our own
catch-up through the BlockPool (reference: internal/blocksync/reactor.go).

The verification hot path — checking `first` against `second.LastCommit`
— goes through ValidatorSet.verify_commit_light, i.e. the batched TPU
Ed25519 seam (reactor.go:547 VerifyCommitLight): a catching-up node
streams thousands of commits through the device verifier, the workload
BASELINE.json's "blocksync replay" config measures.
"""

from __future__ import annotations

import queue
import threading
import time

from ..p2p.conn.connection import StreamDescriptor
from ..p2p.reactor import Reactor
from ..types.block import Block, ExtendedCommit
from ..types.msg_validation import validate_blocksync_message
from ..utils import healthmon, tracing
from ..utils.heightline import registry as _heightline
from ..utils.log import get_logger
from ..wire import blocksync_pb as pb
from .pool import BlockPool, BlockRequest, PeerError

BLOCKSYNC_STREAM = 0x40  # reactor.go:21


class _PendingBlock:
    """One verify-ahead pipeline slot: the exact block/commit objects the
    device verification was submitted for, so _process_block can detect
    pool refetches (object identity) and validator-set changes (hash)
    before trusting the result."""

    __slots__ = ("first", "second", "parts", "block_id", "set_hash", "verification")

    def __init__(self, first, second, parts, block_id, set_hash, verification):
        self.first = first
        self.second = second
        self.parts = parts
        self.block_id = block_id
        self.set_hash = set_hash
        self.verification = verification
TRY_SYNC_INTERVAL = 0.01  # reactor.go:23
STATUS_UPDATE_INTERVAL = 10.0  # reactor.go:30
SWITCH_TO_CONSENSUS_INTERVAL = 1.0  # reactor.go:32
MAX_MSG_SIZE = 10 * 1024 * 1024


class BlocksyncReactor(Reactor):
    def __init__(
        self,
        state,  # sm State at boot
        block_exec,  # BlockExecutor
        store,  # BlockStore
        block_sync: bool,  # start in sync mode?
        local_addr: bytes = b"",
        switch_interval: float = SWITCH_TO_CONSENSUS_INTERVAL,
    ):
        super().__init__("BLOCKSYNC")
        self._switched = False  # one-shot consensus handoff latch
        store_height = store.height
        if store_height and state.last_block_height != store_height:
            raise RuntimeError(
                f"state ({state.last_block_height}) and store ({store_height}) "
                "height mismatch"
            )
        start_height = store_height + 1
        if start_height == 1:
            start_height = state.initial_height
        self.initial_state = state
        self.block_exec = block_exec
        self.store = store
        self.block_sync = block_sync
        self.local_addr = local_addr
        self.switch_interval = switch_interval
        self.logger = get_logger("blocksync")
        self._events: queue.Queue = queue.Queue(maxsize=2000)
        self.pool = BlockPool(
            start_height,
            send_request=lambda rq: self._enqueue(("request", rq)),
            send_error=lambda err: self._enqueue(("error", err)),
        )
        self._pool_thread: threading.Thread | None = None
        self._events_thread: threading.Thread | None = None
        self._synced_callbacks: list = []
        self.blocks_synced = 0
        self._state_synced = False
        # validator-set hash that probed "no async verify path" (small
        # set / cpu backend): skip re-probing — the probe itself costs a
        # make_part_set + hash per block — until the set changes
        self._no_async_for: bytes | None = None

    # -------------------------------------------------------------- wiring

    def stream_descriptors(self) -> list[StreamDescriptor]:
        return [
            StreamDescriptor(
                id=BLOCKSYNC_STREAM, priority=5, send_queue_capacity=1000
            )
        ]

    def _enqueue(self, item) -> None:
        try:
            self._events.put_nowait(item)
        except queue.Full:
            self.logger.error("blocksync event queue full; dropping")

    def on_start(self) -> None:
        if self.block_sync:
            self._start_pool(state_synced=False)

    def switch_to_block_sync(self, state) -> None:
        """Called by statesync once it has bootstrapped state
        (reactor.go:139 SwitchToBlockSync)."""
        self.block_sync = True
        self.initial_state = state
        self.pool.height = state.last_block_height + 1
        self.pool.start_height = self.pool.height
        self._start_pool(state_synced=True)

    def _start_pool(self, state_synced: bool) -> None:
        self._state_synced = state_synced
        self.pool.start()
        self._events_thread = threading.Thread(
            target=self._events_routine, name="blocksync-events", daemon=True
        )
        self._events_thread.start()
        self._pool_thread = threading.Thread(
            target=self._pool_routine, name="blocksync-pool", daemon=True
        )
        self._pool_thread.start()

    def on_stop(self) -> None:
        if self.pool.is_running():
            self.pool.stop()

    # --------------------------------------------------------------- peers

    def add_peer(self, peer) -> None:
        """Send our status so the peer can add us to its pool
        (reactor.go:193 AddPeer)."""
        peer.try_send(
            BLOCKSYNC_STREAM,
            pb.BlocksyncMessage(
                status_response=pb.StatusResponse(
                    height=self.store.height, base=self.store.base
                )
            ).encode(),
        )

    def remove_peer(self, peer, reason: str = "") -> None:
        self.pool.remove_peer(peer.id)

    # -------------------------------------------------------------- receive

    def receive(self, stream_id: int, peer, msg_bytes: bytes) -> None:
        if len(msg_bytes) > MAX_MSG_SIZE:
            self.switch.stop_peer(peer, "oversized blocksync message")
            return
        msg = pb.BlocksyncMessage.decode(msg_bytes)
        # validate-before-use: heights/base bounds before the pool sees
        # them; a raise here makes the switch disconnect the peer
        validate_blocksync_message(msg)
        which = msg.which()
        if which == "block_request":
            self._respond_to_peer(msg.block_request, peer)
        elif which == "block_response":
            self._handle_block_response(msg.block_response, peer, len(msg_bytes))
        elif which == "status_request":
            peer.try_send(
                BLOCKSYNC_STREAM,
                pb.BlocksyncMessage(
                    status_response=pb.StatusResponse(
                        height=self.store.height, base=self.store.base
                    )
                ).encode(),
            )
        elif which == "status_response":
            self.pool.set_peer_range(
                peer.id, msg.status_response.base, msg.status_response.height
            )
        elif which == "no_block_response":
            self.pool.redo_request_from(msg.no_block_response.height, peer.id)
        else:
            self.switch.stop_peer(peer, f"unknown blocksync message {which}")

    def _respond_to_peer(self, msg: pb.BlockRequest, peer) -> None:
        """Serve a stored block, or say we don't have it (reactor.go:211)."""
        block = self.store.load_block(msg.height)
        if block is None:
            peer.try_send(
                BLOCKSYNC_STREAM,
                pb.BlocksyncMessage(
                    no_block_response=pb.NoBlockResponse(height=msg.height)
                ).encode(),
            )
            return
        ext = None
        state = self.block_exec.store.load()
        if state is not None and state.consensus_params.feature.vote_extensions_enabled(
            msg.height
        ):
            ext = self.store.load_block_extended_commit(msg.height)
            if ext is None:
                self.logger.error(
                    f"block {msg.height} in store with no extended commit"
                )
                return
        peer.try_send(
            BLOCKSYNC_STREAM,
            pb.BlocksyncMessage(
                block_response=pb.BlockResponse(
                    block=block.to_proto(),
                    ext_commit=ext.to_proto() if ext is not None else None,
                )
            ).encode(),
        )

    def _handle_block_response(self, msg: pb.BlockResponse, peer, size: int) -> None:
        try:
            block = Block.from_proto(msg.block)
            block.validate_basic()
        except Exception as e:  # noqa: BLE001
            self.switch.stop_peer(peer, f"invalid block: {e}")
            return
        ext = None
        if msg.ext_commit is not None:
            try:
                ext = ExtendedCommit.from_proto(msg.ext_commit)
            except Exception as e:  # noqa: BLE001
                self.switch.stop_peer(peer, f"invalid extended commit: {e}")
                return
        try:
            self.pool.add_block(peer.id, block, ext, size)
        except PeerError as e:
            self.logger.error(f"add block failed: {e.err}")
            self._enqueue(("error", e))

    # ------------------------------------------------------- event routine

    def _events_routine(self) -> None:
        """Dispatch pool-originated requests/errors (reactor.go:454
        handleBlockRequestsRoutine) plus the periodic status broadcast."""
        last_status = 0.0
        while self.is_running() and self.pool.is_running():
            healthmon.beat("blocksync-events")
            now = time.monotonic()
            if now - last_status >= STATUS_UPDATE_INTERVAL:
                last_status = now
                self.broadcast_status_request()
            try:
                kind, item = self._events.get(timeout=0.25)
            except queue.Empty:
                continue
            if kind == "request":
                self._handle_block_request(item)
            elif kind == "error":
                peer = self.switch.peers.get(item.peer_id) if self.switch else None
                if peer is not None:
                    self.switch.stop_peer(peer, item.err)
        healthmon.retire("blocksync-events")

    def _handle_block_request(self, rq: BlockRequest) -> None:
        peer = self.switch.peers.get(rq.peer_id) if self.switch else None
        if peer is None:
            return
        peer.try_send(
            BLOCKSYNC_STREAM,
            pb.BlocksyncMessage(
                block_request=pb.BlockRequest(height=rq.height)
            ).encode(),
        )

    def broadcast_status_request(self) -> None:
        if self.switch is not None:
            self.switch.broadcast(
                BLOCKSYNC_STREAM,
                pb.BlocksyncMessage(status_request=pb.StatusRequest()).encode(),
            )

    # --------------------------------------------------------- pool routine

    # how many commit verifications may be in flight on the device ahead
    # of the apply cursor (2 = double buffer: the chip verifies height
    # h+1's commit while the host saves/applies height h).  submit() is
    # itself asynchronous — payload staging runs on the verifier's
    # background thread (models/comb_verifier) — so at depth 2 the sync
    # thread's store/apply work, height h+1's host assembly, and height
    # h's kernel all genuinely overlap.  COMETBFT_TPU_VERIFY_AHEAD
    # overrides for replay experiments; the comb path's slab pool double
    # buffers, so depths > 2 only add queueing, not memory churn.
    VERIFY_AHEAD_DEPTH = 2

    @classmethod
    def _verify_ahead_depth(cls) -> int:
        from ..utils import envknobs

        v = envknobs.get_opt_int(envknobs.VERIFY_AHEAD)
        if v is not None:
            return max(1, v)
        return cls.VERIFY_AHEAD_DEPTH

    def _pool_routine(self) -> None:
        try:
            self._pool_loop()
        finally:
            # handed off to consensus (or stopped): a finished pool loop
            # must not read as a stalled heartbeat
            healthmon.retire("blocksync-pool")

    def _pool_loop(self) -> None:
        """Apply fetched blocks pairwise; switch to consensus when caught up
        (reactor.go:315 poolRoutine).

        Catch-up replay is the BASELINE "blocksync replay" config: when
        the validator set routes to the device-cached comb verifier, the
        commit checks pipeline ahead of the apply cursor
        (types/validation.submit_verify_commit_light) so the TPU verifies
        height h+1 while the host stores height h — replacing the serial
        verify-per-block CPU pattern of reactor.go:547."""
        state = self.initial_state
        last_switch_check = 0.0
        pending: dict[int, _PendingBlock] = {}
        while self.is_running() and self.pool.is_running():
            healthmon.beat("blocksync-pool")
            now = time.monotonic()
            if now - last_switch_check >= self.switch_interval:
                last_switch_check = now
                if self._check_switch_to_consensus(state):
                    return
            first, second, ext = self.pool.peek_two_blocks()
            if first is None or second is None:
                time.sleep(TRY_SYNC_INTERVAL)
                continue
            if (
                state.last_block_height > 0
                and state.last_block_height + 1 != first.header.height
            ):
                raise RuntimeError(
                    f"peeked first block has unexpected height "
                    f"{first.header.height}, want {state.last_block_height + 1}"
                )
            h = first.header.height
            for ph in [p for p in pending if p < h]:
                del pending[ph]  # heights already applied (or refetched past)
            self._top_up_verify_pipeline(pending, state, h)
            pend = pending.pop(h, None)
            try:
                state = self._process_block(first, second, state, ext, pend)
                self.blocks_synced += 1
            except Exception as e:  # noqa: BLE001
                self.logger.error(
                    f"invalid block at {first.header.height}: {e}"
                )
                # in-flight verifications may reference blocks the redo
                # below is about to drop: discard the whole window
                pending.clear()
                # ban both senders and refetch (reactor.go:565-581)
                for h in (first.header.height, second.header.height):
                    pid = self.pool.remove_peer_and_redo_all(h)
                    peer = self.switch.peers.get(pid) if self.switch else None
                    if peer is not None:
                        self.switch.stop_peer(peer, f"bad block: {e}")

    def _top_up_verify_pipeline(
        self, pending: dict, state, head_height: int
    ) -> None:
        """Submit device commit verifications for up to VERIFY_AHEAD_DEPTH
        buffered heights.  Only heights whose header claims the CURRENT
        validator set are submitted (untrusted hint — cheap skip of
        windows that straddle a set change); the trusted re-check happens
        at use time in _process_block."""
        from ..types.block import BlockID
        from ..types.validation import submit_verify_commit_light
        from ..verifysvc.service import Klass

        vals = state.validators
        if vals is None:
            return
        set_hash = vals.hash()
        if set_hash == self._no_async_for:
            return  # this set probed "no async path"; don't pay the probe again
        chain_id = self.initial_state.chain_id
        for hh in range(head_height, head_height + self._verify_ahead_depth()):
            if hh in pending:
                continue
            blk, _ = self.pool.peek_block(hh)
            nxt, _ = self.pool.peek_block(hh + 1)
            if blk is None or nxt is None or nxt.last_commit is None:
                continue
            if blk.header.validators_hash != set_hash:
                continue
            try:
                with tracing.span(
                    "blocksync.verify_ahead_submit",
                    {"height": hh} if tracing.enabled() else None,
                ):
                    parts = blk.make_part_set()
                    bid = BlockID(
                        hash=blk.hash(), part_set_header=parts.header
                    )
                    p = submit_verify_commit_light(
                        chain_id, vals, bid, hh, nxt.last_commit,
                        klass=Klass.BLOCKSYNC,
                    )
            except Exception as e:  # noqa: BLE001
                # structurally bad / malformed peer data (bad commit, odd
                # sig lengths, ...): leave it for the serial path, which
                # owns the ban/refetch bookkeeping — never kill the sync
                # thread over untrusted bytes
                self.logger.debug(
                    f"verify-ahead skip h={hh}: {e!r} "
                    "(serial path owns ban/refetch)"
                )
                continue
            if p is None:
                self._no_async_for = set_hash
                return  # set doesn't route to the async comb path
            pending[hh] = _PendingBlock(blk, nxt, parts, bid, set_hash, p)

    def _process_block(
        self, first: Block, second: Block, state, ext, pend=None
    ) -> object:
        """reactor.go:536 processBlock: verify w/ second.LastCommit, save,
        apply."""
        from ..types.block import BlockID
        from ..types.validation import verify_commit_light
        from ..verifysvc.service import Klass

        chain_id = self.initial_state.chain_id
        hh = first.header.height
        hl = _heightline()
        # fast-synced heights never see proposals/votes; the timeline is
        # full_block (have the bytes) -> commit (verified+saved) -> apply
        hl.mark(hh, "full_block")
        nsigs = len(second.last_commit.signatures) if second.last_commit else 0
        t_verify = time.monotonic()
        if (
            pend is not None
            and pend.first is first
            and pend.second is second
            and pend.set_hash == state.validators.hash()
        ):
            # verify-ahead hit: the kernel has been running since the
            # pipeline submitted it; collect raises like verify_commit_light
            first_parts = pend.parts
            first_id = pend.block_id
            with tracing.span(
                "blocksync.verify_ahead_collect",
                {"height": first.header.height} if tracing.enabled() else None,
            ):
                pend.verification.collect()
        else:
            first_parts = first.make_part_set()
            first_id = BlockID(hash=first.hash(), part_set_header=first_parts.header)

            # the TPU-batched signature check (types/validation.go VerifyCommitLight)
            with tracing.span(
                "blocksync.verify_sync",
                {"height": first.header.height} if tracing.enabled() else None,
            ):
                verify_commit_light(
                    chain_id,
                    state.validators,
                    first_id,
                    first.header.height,
                    second.last_commit,
                    klass=Klass.BLOCKSYNC,
                )
        # blocksync knows its height — attribute the wait explicitly
        # (the verify-service collector can't; it uses the current height)
        hl.note_verify(nsigs, time.monotonic() - t_verify, height=hh)
        with tracing.span(
            "blocksync.validate",
            {"height": first.header.height} if tracing.enabled() else None,
        ):
            self.block_exec.validate_block(state, first, klass=Klass.BLOCKSYNC)

        extensions_enabled = state.consensus_params.feature.vote_extensions_enabled(
            first.header.height
        )
        if (ext is not None) != extensions_enabled:
            raise ValueError(
                "extended commit present iff extensions enabled violated "
                f"(height {first.header.height})"
            )
        if extensions_enabled:
            ext.ensure_extensions(True)
            self.store.save_block_with_extended_commit(first, first_parts, ext)
        else:
            self.store.save_block(first, first_parts, second.last_commit)
        self.pool.pop_request()
        hl.mark(hh, "commit")

        with tracing.span(
            "blocksync.apply",
            {"height": first.header.height} if tracing.enabled() else None,
        ):
            new_state = self.block_exec.apply_verified_block(
                state, first_id, first, syncing_to_height=self.pool.max_height()
            )
        hl.mark(hh, "apply")
        return new_state

    # ------------------------------------------------- switch to consensus

    def _check_switch_to_consensus(self, state) -> bool:
        """reactor.go:516 isCaughtUp + the SwitchToConsensus handoff.

        Single-shot: the handoff must never run twice (the consensus
        reactor also guards, but the pool stop + mempool enable below
        aren't idempotent either)."""
        if self._switched:
            return True
        caught_up, height, _ = self.pool.is_caught_up()
        blocks_chain = False
        if self.local_addr and state.validators is not None:
            blocks_chain = state.validators.validator_blocks_the_chain(
                self.local_addr
            )
        if not (caught_up or blocks_chain):
            return False
        self._switched = True
        self.logger.info(f"caught up at height {height}; switching to consensus")
        self.pool.stop()
        if self.switch is not None:
            mem = self.switch.reactors.get("MEMPOOL")
            if mem is not None and hasattr(mem, "enable_in_out_txs"):
                mem.enable_in_out_txs()
            con = self.switch.reactors.get("CONSENSUS")
            if con is not None and hasattr(con, "switch_to_consensus"):
                con.switch_to_consensus(
                    state,
                    skip_wal=self.blocks_synced > 0 or self._state_synced,
                )
        for cb in self._synced_callbacks:
            cb(state)
        return True
