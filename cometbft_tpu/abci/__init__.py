"""ABCI: the application-blockchain interface (reference: abci/).

The Application interface (types.Application, 12 methods in 4 groups) is
the process boundary between the consensus engine and the replicated
state machine; clients/servers speak varint-delimited protobuf over a
socket or run in-process.
"""

from .types import Application, BaseApplication, CodeTypeOK
from .client import Client, LocalClient, UnsyncLocalClient, SocketClient
from .server import SocketServer
from .kvstore import KVStoreApplication

__all__ = [
    "Application",
    "BaseApplication",
    "CodeTypeOK",
    "Client",
    "LocalClient",
    "UnsyncLocalClient",
    "SocketClient",
    "SocketServer",
    "KVStoreApplication",
]
