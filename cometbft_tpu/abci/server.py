"""ABCI socket server for out-of-process applications
(reference: abci/server/socket_server.go:334).

Accepts connections, reads varint-delimited Request frames, dispatches to
the Application, and writes Response frames in order.  Each connection
gets its own handler thread — the engine opens four (consensus, mempool,
query, snapshot), which this serves concurrently like the reference.
"""

from __future__ import annotations

import socket
import threading

from ..utils.service import Service
from ..wire import abci_pb as pb
from ..wire.proto import decode_varint, encode_varint
from .types import Application, METHODS


class SocketServer(Service):
    def __init__(self, addr: str, app: Application):
        super().__init__("ABCIServer")
        self.app = app
        host, port = addr.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._listener: socket.socket | None = None
        self._conns: list[socket.socket] = []
        self._app_mtx = threading.RLock()

    @property
    def laddr(self) -> str:
        return f"{self._host}:{self._port}"

    def on_start(self) -> None:
        self._listener = socket.create_server(
            (self._host, self._port), reuse_port=False
        )
        self._port = self._listener.getsockname()[1]
        threading.Thread(
            target=self._accept_routine, name="abci-accept", daemon=True
        ).start()

    def on_stop(self) -> None:
        from ..utils.netutil import close_socket

        close_socket(self._listener)
        for c in self._conns:
            close_socket(c)

    def _accept_routine(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True,
                name="abci-conn",
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        # deliberately blocking: an ABCI connection serves until EOF and
        # is woken at teardown by close_socket()'s shutdown — declared
        # here so the socket-without-timeout check reads the intent
        conn.settimeout(None)
        buf = b""
        out = bytearray()
        try:
            while True:
                chunk = conn.recv(1 << 16)
                if not chunk:
                    return
                buf += chunk
                del out[:]
                bad_frame = False
                while True:
                    try:
                        ln, pos = decode_varint(buf)
                    except ValueError as e:
                        if "truncated" in str(e):
                            break  # need more bytes
                        bad_frame = True  # malformed length prefix
                        break
                    if len(buf) - pos < ln:
                        break
                    frame, buf = buf[pos : pos + ln], buf[pos + ln :]
                    try:
                        req = pb.Request.decode(frame)
                    except ValueError as e:
                        # framing is lost beyond this point: answer what we
                        # already executed, report, and drop the connection
                        # (reference responds with exception then closes)
                        resp = pb.Response(
                            exception=pb.ExceptionResponse(error=f"bad request frame: {e}")
                        )
                        payload = resp.encode()
                        out += encode_varint(len(payload)) + payload
                        bad_frame = True
                        break
                    resp = self._handle_request(req)
                    payload = resp.encode()
                    out += encode_varint(len(payload)) + payload
                if out:
                    conn.sendall(bytes(out))
                if bad_frame:
                    return
        except OSError:
            return
        finally:
            conn.close()

    def _handle_request(self, req: pb.Request) -> pb.Response:
        which = req.which()
        if which is None:
            return pb.Response(exception=pb.ExceptionResponse(error="empty request"))
        if which == "echo":
            return pb.Response(echo=pb.EchoResponse(message=req.echo.message))
        if which == "flush":
            return pb.Response(flush=pb.FlushResponse())
        method = next(m for m, (rq, _) in METHODS.items() if rq == which)
        try:
            with self._app_mtx:
                result = getattr(self.app, method)(req.value())
            return pb.Response(**{METHODS[method][1]: result})
        except Exception as e:  # noqa: BLE001 - app errors cross the wire
            return pb.Response(exception=pb.ExceptionResponse(error=str(e)))
