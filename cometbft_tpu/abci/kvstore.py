"""KVStore demo application (reference: abci/example/kvstore/kvstore.go).

Behavior-compatible with the reference app:
  - txs are "key=value" or "key:value" (exactly one separator, non-empty
    key/value ends)
  - validator-change txs: "val=<keytype>!<base64 pubkey>!<power>"
    (kvstore.go:541-568)
  - mempool lanes: val=9, foo=7, default=3, bar=1, assigned by key modulo
    (DefaultLanes kvstore.go:117, assignLane:208)
  - app hash = signed-varint(state.Size) zero-padded to 8 bytes
    (State.Hash kvstore.go:669-673)
  - Query paths: "/key" (value lookup), "/val" (validator lookup)
  - FinalizeBlock stages; Commit persists — crash between them loses
    nothing because the engine replays the block.

Adds optional whole-state snapshots (one chunk) so statesync paths are
testable against a real app; the reference's kvstore defers that to the
e2e app.
"""

from __future__ import annotations

import base64
import json
import threading

from ..crypto import ed25519
from ..store.db import DB, MemDB, _prefix_end
from ..wire import abci_pb as pb
from .types import Application, CodeTypeOK

CodeTypeInvalidTxFormat = 2

VALIDATOR_PREFIX = "val="  # kvstore.go:29
DEFAULT_LANE = "default"
KV_PREFIX = b"kvPairKey:"
STATE_KEY = b"appstate"

APP_VERSION = 1


def default_lanes() -> dict[str, int]:
    return {"val": 9, "foo": 7, DEFAULT_LANE: 3, "bar": 1}


def is_validator_tx(tx: bytes) -> bool:
    return tx.startswith(VALIDATOR_PREFIX.encode())


def parse_validator_tx(tx: bytes) -> tuple[str, bytes, int]:
    parts = tx[len(VALIDATOR_PREFIX):].decode("utf-8", "replace").split("!")
    if len(parts) != 3:
        raise ValueError(f"expected 'pubkeytype!pubkey!power', got {parts}")
    key_type, pub_b64, power_s = parts
    pubkey = base64.b64decode(pub_b64, validate=True)
    power = int(power_s)
    if power < 0:
        raise ValueError(f"power cannot be negative, got {power}")
    # reject wrong-sized keys HERE, where CheckTx/ProcessProposal already
    # reject on ValueError: a hex-encoded key is valid base64 of the
    # wrong length, and letting it through turns into a
    # validate_validator_updates crash INSIDE block apply — a malformed
    # val tx halting consensus on every node (found by the chaos
    # valset-rotation scenario)
    if (key_type or ed25519.KEY_TYPE) == ed25519.KEY_TYPE and len(pubkey) != 32:
        raise ValueError(
            f"ed25519 pubkey must be 32 bytes, got {len(pubkey)}"
        )
    # empty type means ed25519 everywhere in this app; normalizing HERE
    # keeps a "val:!<key>!5" tx from reaching consensus with a type that
    # validate_validator_updates would reject after the block is decided
    return key_type or ed25519.KEY_TYPE, pubkey, power


def make_val_set_change_tx(pubkey: bytes, power: int, key_type: str = ed25519.KEY_TYPE) -> bytes:
    return (
        VALIDATOR_PREFIX
        + key_type
        + "!"
        + base64.b64encode(pubkey).decode()
        + "!"
        + str(power)
    ).encode()


def is_valid_tx(tx: bytes) -> bool:
    for sep in (b":", b"="):
        other = b"=" if sep == b":" else b":"
        if tx.count(sep) == 1 and tx.count(other) == 0:
            return not (tx.startswith(sep) or tx.endswith(sep))
    return False


def parse_tx(tx: bytes) -> tuple[str, str]:
    parts = tx.split(b"=")
    if len(parts) != 2 or not parts[0]:
        raise ValueError(f"invalid tx format: {tx!r}")
    return parts[0].decode("utf-8", "replace"), parts[1].decode("utf-8", "replace")


def assign_lane(tx: bytes) -> str:
    if is_validator_tx(tx):
        return "val"
    try:
        key, _ = parse_tx(tx)
        key_int = int(key)
    except ValueError:
        return DEFAULT_LANE
    if key_int % 11 == 0:
        return "foo"
    if key_int % 3 == 0:
        return "bar"
    return DEFAULT_LANE


def _iter_prefix(db: DB, prefix: bytes):
    return db.iterator(prefix, _prefix_end(prefix)) if prefix else db.iterator()


def _size_hash(size: int) -> bytes:
    # binary.PutVarint into an 8-byte buffer: zigzag varint, zero-padded
    z = (size << 1) ^ (size >> 63) if size >= 0 else ((-size) << 1) - 1
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    return bytes(out.ljust(8, b"\x00"))


class KVStoreApplication(Application):
    def __init__(
        self,
        db: DB | None = None,
        lanes: dict[str, int] | None = default_lanes(),
        snapshot_interval: int = 0,
        snapshot_keep: int = 4,
        merkle_state: bool = False,
    ):
        # merkle_state=True commits the app hash to a Merkle root over the
        # sorted kv pairs and serves ValueOp proofs on Query(prove=True),
        # so a light client can verify abci_query responses end-to-end
        # (light/rpc.py).  Default off: the plain mode mirrors the
        # reference example app's size-derived 8-byte app hash
        # (abci/example/kvstore/kvstore.go), which ships no proofs.
        self.merkle_state = merkle_state
        self.db = db if db is not None else MemDB()
        self.lane_priorities = dict(lanes) if lanes else {}
        self._mtx = threading.RLock()
        self.size = 0
        self.height = 0
        self.staged_txs: list[bytes] = []
        self.val_updates: list[pb.ValidatorUpdate] = []
        self.val_addr_to_pubkey: dict[bytes, tuple[str, bytes]] = {}
        self.gen_block_events = False
        self.next_block_delay_ms = 0
        self._restoring: pb.Snapshot | None = None
        # periodic snapshots for statesync serving (the reference e2e app
        # pattern): every snapshot_interval heights, keep the last
        # snapshot_keep payloads; 0 = snapshot only the live height
        self.snapshot_interval = snapshot_interval
        self.snapshot_keep = snapshot_keep
        self._snapshots: dict[int, bytes] = {}  # height -> payload
        # merkle-state snapshot caches, keyed by (height, #staged).
        # Within a height the staged-tx list only grows, and committed kv
        # pairs only change at Commit (which bumps height), so the pair
        # is a sound snapshot key.  Root and proofs cache separately:
        # app_hash runs every block and needs only the root; the full
        # proof trails + key index are built lazily on the first proven
        # query against that snapshot.
        self._root_cache: tuple | None = None  # (key, root)
        self._proof_cache: tuple | None = None  # (key, (index, proofs))
        self._load_state()

    # ------------------------------------------------------------- state

    def _load_state(self) -> None:
        raw = self.db.get(STATE_KEY)
        if raw:
            st = json.loads(raw)
            self.size, self.height = st["size"], st["height"]
        for k, v in _iter_prefix(self.db, VALIDATOR_PREFIX.encode()):
            addr = k[len(VALIDATOR_PREFIX):]
            key_type, pub_b64, _ = v.decode().split("!")
            self.val_addr_to_pubkey[addr] = (
                key_type or ed25519.KEY_TYPE, base64.b64decode(pub_b64)
            )

    def _save_state(self) -> None:
        self.db.set(STATE_KEY, json.dumps({"size": self.size, "height": self.height}).encode())

    def app_hash(self) -> bytes:
        if self.merkle_state:
            return self._state_root()
        return _size_hash(self.size)

    def _state_leaves(self) -> list[bytes]:
        """Sorted kv pairs as leaves in ValueOp form: key || sha256(value)
        (crypto/merkle.py ValueOp.run re-derives exactly this), unambiguous
        because the value hash is fixed-width.

        Includes the txs staged by the in-flight FinalizeBlock: the app
        hash returned for block h must commit to block h's writes, which
        only reach the db at Commit (the root would otherwise lag one
        block and no proof would ever match header h+1)."""
        import hashlib

        pairs = {
            k[len(KV_PREFIX):]: v for k, v in _iter_prefix(self.db, KV_PREFIX)
        }
        for tx in self.staged_txs:
            key, value = parse_tx(tx)
            pairs[key.encode()] = value.encode()
        return [
            k + hashlib.sha256(v).digest() for k, v in sorted(pairs.items())
        ]

    def _snap_key(self):
        return (self.height, len(self.staged_txs))

    def _state_root(self) -> bytes:
        from ..crypto import merkle

        key = self._snap_key()
        if self._root_cache is not None and self._root_cache[0] == key:
            return self._root_cache[1]
        root = merkle.hash_from_byte_slices(self._state_leaves(), device=False)
        self._root_cache = (key, root)
        return root

    def _merkle_proofs(self):
        """Cached (key->index, proofs) for the current snapshot — built
        on the first proven query, not on the per-block app_hash path."""
        from ..crypto import merkle

        key = self._snap_key()
        if self._proof_cache is not None and self._proof_cache[0] == key:
            return self._proof_cache[1]
        leaves = self._state_leaves()
        index = {leaf[:-32]: i for i, leaf in enumerate(leaves)}
        _root, proofs = merkle.proofs_from_byte_slices(leaves)
        snap = (index, proofs)
        self._proof_cache = (key, snap)
        return snap

    def _query_proof(self, key: bytes):
        """ValueOp proof that key=value is in the state root.

        The ProofOps chain is one simple:v op (crypto/merkle.py ValueOp);
        the light client verifies it against the NEXT header's app_hash
        (light/rpc.py abci_query)."""
        from ..wire import types_pb as tpb

        index, proofs = self._merkle_proofs()
        target = index.get(key)
        if target is None:
            return None
        p = proofs[target]
        vop = tpb.ValueOpProto(
            key=key,
            proof=tpb.Proof(
                total=p.total,
                index=p.index,
                leaf_hash=p.leaf_hash,
                aunts=list(p.aunts),
            ),
        )
        return tpb.ProofOps(
            ops=[tpb.ProofOpProto(type="simple:v", key=key, data=vop.encode())]
        )

    # -------------------------------------------------------- info/query

    def info(self, req):
        resp = pb.InfoResponse(
            data=json.dumps({"size": self.size}),
            version="kvstore-tpu/0.1",
            app_version=APP_VERSION,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash() if self.height else b"",
            default_lane=DEFAULT_LANE if self.lane_priorities else "",
        )
        if self.lane_priorities:
            resp.set_lane_priorities(self.lane_priorities)
        return resp

    def query(self, req):
        with self._mtx:
            if req.path == "/val":
                v = self.db.get(VALIDATOR_PREFIX.encode() + req.data)
                return pb.QueryResponse(key=req.data, value=v or b"", height=self.height)
            v = self.db.get(KV_PREFIX + req.data)
            if req.prove and self.merkle_state:
                # value and proof must come from one snapshot: between
                # FinalizeBlock(h) and Commit(h) the app hash (and thus
                # _state_leaves) already includes the staged writes, so
                # the served value must too, or the proof can't verify
                for tx in self.staged_txs:
                    key, value = parse_tx(tx)
                    if key.encode() == req.data:
                        v = value.encode()
            if v is None:
                return pb.QueryResponse(code=CodeTypeOK, log="does not exist", height=self.height)
            resp = pb.QueryResponse(
                code=CodeTypeOK, log="exists", key=req.data, value=v, height=self.height
            )
            if req.prove and self.merkle_state:
                resp.proof_ops = self._query_proof(req.data)
            return resp

    # ----------------------------------------------------------- mempool

    def check_tx(self, req):
        tx = req.tx
        if is_validator_tx(tx):
            try:
                parse_validator_tx(tx)
            except ValueError:
                return pb.CheckTxResponse(code=CodeTypeInvalidTxFormat)
        elif not is_valid_tx(tx):
            return pb.CheckTxResponse(code=CodeTypeInvalidTxFormat)
        if not self.lane_priorities:
            return pb.CheckTxResponse(code=CodeTypeOK, gas_wanted=1)
        return pb.CheckTxResponse(code=CodeTypeOK, gas_wanted=1, lane_id=assign_lane(tx))

    # --------------------------------------------------------- consensus

    def init_chain(self, req):
        with self._mtx:
            for v in req.validators:
                self._update_validator(v)
            self.staged_txs = []
            self.val_updates = []
            return pb.InitChainResponse(app_hash=self.app_hash())

    def prepare_proposal(self, req):
        # normalize "key:value" to "key=value" (kvstore.go formatTxs),
        # respecting max_tx_bytes
        total, txs = 0, []
        for tx in req.txs:
            out = tx if is_validator_tx(tx) else tx.replace(b":", b"=")
            total += len(out)
            if req.max_tx_bytes and total > req.max_tx_bytes:
                break
            txs.append(out)
        return pb.PrepareProposalResponse(txs=txs)

    def process_proposal(self, req):
        for tx in req.txs:
            if is_validator_tx(tx):
                try:
                    parse_validator_tx(tx)
                except ValueError:
                    return pb.ProcessProposalResponse(status=pb.PROCESS_PROPOSAL_STATUS_REJECT)
            # proposals must carry normalized "=" txs only
            elif not is_valid_tx(tx) or b"=" not in tx:
                return pb.ProcessProposalResponse(status=pb.PROCESS_PROPOSAL_STATUS_REJECT)
        return pb.ProcessProposalResponse(status=pb.PROCESS_PROPOSAL_STATUS_ACCEPT)

    def finalize_block(self, req):
        with self._mtx:
            self.val_updates = []
            self.staged_txs = []

            # punish double-voters by docking one power (kvstore.go:316-334)
            for ev in req.misbehavior:
                if ev.type != pb.MISBEHAVIOR_TYPE_DUPLICATE_VOTE:
                    continue
                known = self.val_addr_to_pubkey.get(ev.validator.address)
                if known:
                    key_type, pubkey = known
                    self.val_updates.append(
                        pb.ValidatorUpdate(
                            power=max(ev.validator.power - 1, 0),
                            pub_key_type=key_type,
                            pub_key_bytes=pubkey,
                        )
                    )

            tx_results = []
            for tx in req.txs:
                if is_validator_tx(tx):
                    key_type, pubkey, power = parse_validator_tx(tx)
                    self.val_updates.append(
                        pb.ValidatorUpdate(
                            power=power, pub_key_type=key_type, pub_key_bytes=pubkey
                        )
                    )
                    key = value = tx.decode("utf-8", "replace")
                else:
                    # stage normalized to "key=value"; colon-form txs reach
                    # here when the proposer didn't run our prepare_proposal
                    norm = tx if b"=" in tx else tx.replace(b":", b"=")
                    try:
                        key, value = parse_tx(norm)
                        self.staged_txs.append(norm)
                    except ValueError:
                        key = value = tx.decode("utf-8", "replace")
                tx_results.append(
                    pb.ExecTxResult(
                        code=CodeTypeOK,
                        events=[
                            pb.Event(
                                type="app",
                                attributes=[
                                    pb.EventAttribute(key="key", value=key, index=True),
                                    pb.EventAttribute(key="value", value=value, index=True),
                                ],
                            )
                        ],
                    )
                )
                self.size += 1

            self.height = req.height
            return pb.FinalizeBlockResponse(
                tx_results=tx_results,
                validator_updates=list(self.val_updates),
                app_hash=self.app_hash(),
                next_block_delay=pb.Duration.from_ns(self.next_block_delay_ms * 1_000_000)
                if self.next_block_delay_ms
                else None,
            )

    def commit(self, req):
        with self._mtx:
            for v in self.val_updates:
                self._update_validator(v)
            for tx in self.staged_txs:  # staged txs are already normalized
                key, value = parse_tx(tx)
                self.db.set(KV_PREFIX + key.encode(), value.encode())
            self._save_state()
            if (
                self.snapshot_interval > 0
                and self.height > 0
                and self.height % self.snapshot_interval == 0
            ):
                self._snapshots[self.height] = self._snapshot_payload()
                while len(self._snapshots) > self.snapshot_keep:
                    del self._snapshots[min(self._snapshots)]
            return pb.CommitResponse()

    def _update_validator(self, v: pb.ValidatorUpdate) -> None:
        from ..crypto import encoding as keyenc

        # normalize ONCE: an empty type (proto default) means ed25519, and
        # the same normalized name must flow into the address derivation,
        # the stored record, and the in-memory map — a raw "" stored here
        # would crash pubkey reconstruction on replay
        key_type = v.pub_key_type or ed25519.KEY_TYPE
        pub = keyenc.pubkey_from_type_and_bytes(key_type, v.pub_key_bytes)
        addr = pub.address()
        key = VALIDATOR_PREFIX.encode() + addr
        if v.power == 0:
            self.db.delete(key)
            self.val_addr_to_pubkey.pop(addr, None)
        else:
            record = f"{key_type}!{base64.b64encode(v.pub_key_bytes).decode()}!{v.power}"
            self.db.set(key, record.encode())
            self.val_addr_to_pubkey[addr] = (key_type, v.pub_key_bytes)

    def get_validators(self) -> list[pb.ValidatorUpdate]:
        out = []
        for _, v in _iter_prefix(self.db, VALIDATOR_PREFIX.encode()):
            key_type, pub_b64, power = v.decode().split("!")
            key_type = key_type or ed25519.KEY_TYPE  # pre-normalization records
            out.append(
                pb.ValidatorUpdate(
                    power=int(power),
                    pub_key_type=key_type,
                    pub_key_bytes=base64.b64decode(pub_b64),
                )
            )
        return out

    # ---------------------------------------------------------- snapshot

    SNAPSHOT_FORMAT = 1

    def _snapshot_payload(self) -> bytes:
        items = {
            k.decode("latin1"): v.decode("latin1")
            for k, v in _iter_prefix(self.db, b"")
        }
        return json.dumps({"items": items}, sort_keys=True).encode()

    def list_snapshots(self, req):
        from ..crypto import hash as tmhash

        with self._mtx:
            if self._snapshots:
                entries = sorted(self._snapshots.items())
            elif self.height:
                entries = [(self.height, self._snapshot_payload())]
            else:
                entries = []
            return pb.ListSnapshotsResponse(
                snapshots=[
                    pb.Snapshot(
                        height=h,
                        format=self.SNAPSHOT_FORMAT,
                        chunks=1,
                        hash=tmhash.sum_sha256(payload),
                    )
                    for h, payload in entries
                ]
            )

    def offer_snapshot(self, req):
        if req.snapshot is None or req.snapshot.format != self.SNAPSHOT_FORMAT:
            return pb.OfferSnapshotResponse(result=pb.OFFER_SNAPSHOT_RESULT_REJECT_FORMAT)
        self._restoring = req.snapshot
        return pb.OfferSnapshotResponse(result=pb.OFFER_SNAPSHOT_RESULT_ACCEPT)

    def load_snapshot_chunk(self, req):
        with self._mtx:
            if req.chunk != 0:
                return pb.LoadSnapshotChunkResponse()
            if req.height in self._snapshots:
                return pb.LoadSnapshotChunkResponse(
                    chunk=self._snapshots[req.height]
                )
            if req.height != self.height:
                return pb.LoadSnapshotChunkResponse()
            return pb.LoadSnapshotChunkResponse(chunk=self._snapshot_payload())

    def apply_snapshot_chunk(self, req):
        with self._mtx:
            snap = self._restoring
            if snap is None:
                return pb.ApplySnapshotChunkResponse(
                    result=pb.APPLY_SNAPSHOT_CHUNK_RESULT_ABORT
                )
            from ..crypto import hash as tmhash

            if tmhash.sum_sha256(req.chunk) != snap.hash:
                return pb.ApplySnapshotChunkResponse(
                    result=pb.APPLY_SNAPSHOT_CHUNK_RESULT_RETRY,
                    refetch_chunks=[req.index],
                    reject_senders=[req.sender] if req.sender else [],
                )
            st = json.loads(req.chunk)
            for k, v in st["items"].items():
                self.db.set(k.encode("latin1"), v.encode("latin1"))
            self.val_addr_to_pubkey = {}
            self.size = 0
            self.height = 0
            self._load_state()
            self._restoring = None
        return pb.ApplySnapshotChunkResponse(result=pb.APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT)
