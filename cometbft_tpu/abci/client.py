"""ABCI clients (reference: abci/client/).

Three transports:
  LocalClient        in-process, one shared mutex serializing every call
                     (local_client.go) — the default for Python apps.
  UnsyncLocalClient  in-process, no mutex; the app synchronizes itself
                     (unsync_local_client.go).
  SocketClient       pipelined async requests over a TCP socket speaking
                     varint-delimited Request/Response oneof frames with
                     strict FIFO response matching (socket_client.go:515).

All clients expose the 16 methods synchronously plus check_tx_async
(the one call sites issue concurrently: mempool broadcast) returning a
ReqRes future, mirroring abcicli.Client's *Async/*Sync split.
"""

from __future__ import annotations

import socket
import threading
from collections import deque
from typing import Callable

from ..utils.service import Service
from ..wire import abci_pb as pb
from ..wire.proto import decode_varint, encode_varint
from .types import Application, METHODS


class ClientError(Exception):
    pass


class ReqRes:
    """A pending request/response pair (abci/client/client.go ReqRes)."""

    def __init__(self, request: pb.Request):
        self.request = request
        self.response: pb.Response | None = None
        self._done = threading.Event()
        self._cb: Callable[[pb.Response], None] | None = None
        self._mtx = threading.Lock()

    def set_callback(self, cb: Callable[[pb.Response], None]) -> None:
        with self._mtx:
            if self.response is not None:
                cb(self.response)
                return
            self._cb = cb

    def set_done(self, response: pb.Response) -> None:
        with self._mtx:
            self.response = response
            cb = self._cb
        if cb:
            cb(response)
        self._done.set()

    def wait(self, timeout: float | None = None) -> pb.Response:
        if not self._done.wait(timeout):
            raise ClientError("ABCI request timed out")
        assert self.response is not None
        return self.response


class Client(Service):
    """Common sync facade; subclasses implement _do(method, req_msg)."""

    def _do(self, method: str, msg):
        raise NotImplementedError

    def error(self) -> Exception | None:
        return None

    # 16 sync methods
    def echo(self, message: str) -> pb.EchoResponse:
        return self._do("echo", pb.EchoRequest(message=message))

    def flush(self) -> None:
        self._do("flush", pb.FlushRequest())

    def info(self, req: pb.InfoRequest) -> pb.InfoResponse:
        return self._do("info", req)

    def init_chain(self, req: pb.InitChainRequest) -> pb.InitChainResponse:
        return self._do("init_chain", req)

    def query(self, req: pb.QueryRequest) -> pb.QueryResponse:
        return self._do("query", req)

    def check_tx(self, req: pb.CheckTxRequest) -> pb.CheckTxResponse:
        return self._do("check_tx", req)

    def commit(self, req: pb.CommitRequest | None = None) -> pb.CommitResponse:
        return self._do("commit", req or pb.CommitRequest())

    def list_snapshots(self, req: pb.ListSnapshotsRequest) -> pb.ListSnapshotsResponse:
        return self._do("list_snapshots", req)

    def offer_snapshot(self, req: pb.OfferSnapshotRequest) -> pb.OfferSnapshotResponse:
        return self._do("offer_snapshot", req)

    def load_snapshot_chunk(
        self, req: pb.LoadSnapshotChunkRequest
    ) -> pb.LoadSnapshotChunkResponse:
        return self._do("load_snapshot_chunk", req)

    def apply_snapshot_chunk(
        self, req: pb.ApplySnapshotChunkRequest
    ) -> pb.ApplySnapshotChunkResponse:
        return self._do("apply_snapshot_chunk", req)

    def prepare_proposal(
        self, req: pb.PrepareProposalRequest
    ) -> pb.PrepareProposalResponse:
        return self._do("prepare_proposal", req)

    def process_proposal(
        self, req: pb.ProcessProposalRequest
    ) -> pb.ProcessProposalResponse:
        return self._do("process_proposal", req)

    def extend_vote(self, req: pb.ExtendVoteRequest) -> pb.ExtendVoteResponse:
        return self._do("extend_vote", req)

    def verify_vote_extension(
        self, req: pb.VerifyVoteExtensionRequest
    ) -> pb.VerifyVoteExtensionResponse:
        return self._do("verify_vote_extension", req)

    def finalize_block(
        self, req: pb.FinalizeBlockRequest
    ) -> pb.FinalizeBlockResponse:
        return self._do("finalize_block", req)

    # async seam used by the mempool (socket_client pipelining)
    def check_tx_async(self, req: pb.CheckTxRequest) -> ReqRes:
        rr = ReqRes(pb.Request(check_tx=req))
        resp = self._do("check_tx", req)
        rr.set_done(pb.Response(check_tx=resp))
        return rr


def _dispatch(app: Application, method: str, msg):
    if method == "echo":
        return pb.EchoResponse(message=msg.message)
    if method == "flush":
        return pb.FlushResponse()
    return getattr(app, method)(msg)


class LocalClient(Client):
    """In-process client; one mutex serializes all connections' calls
    (local_client.go: shared-mutex semantics)."""

    def __init__(self, app: Application, mtx: threading.RLock | None = None):
        super().__init__("LocalClient")
        self.app = app
        self._app_mtx = mtx or threading.RLock()

    def _do(self, method: str, msg):
        with self._app_mtx:
            return _dispatch(self.app, method, msg)


class UnsyncLocalClient(Client):
    """In-process client without locking (unsync_local_client.go) — for
    applications that manage their own concurrency."""

    def __init__(self, app: Application):
        super().__init__("UnsyncLocalClient")
        self.app = app

    def _do(self, method: str, msg):
        return _dispatch(self.app, method, msg)


class SocketClient(Client):
    """TCP client for out-of-process applications (socket_client.go).

    Requests are written varint-delimited; responses return strictly in
    order, so pending requests live in a FIFO.  A background reader thread
    completes ReqRes futures; sync calls enqueue + wait.
    """

    def __init__(self, addr: str, must_connect: bool = True, timeout: float = 10.0):
        super().__init__("SocketClient")
        self.addr = addr
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._pending: deque[tuple[str, ReqRes]] = deque()
        self._pending_mtx = threading.Lock()
        self._write_mtx = threading.Lock()
        self._err: Exception | None = None
        self._recv_thread: threading.Thread | None = None
        self._must_connect = must_connect

    def error(self) -> Exception | None:
        return self._err

    def on_start(self) -> None:
        import time

        host, port = self.addr.rsplit(":", 1)
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=self.timeout
                )
                break
            except OSError:
                # must_connect=False retries until the app comes up
                # (socket_client.go dial retry loop), bounded by timeout
                if self._must_connect or time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)
        self._sock.settimeout(None)
        self._recv_thread = threading.Thread(
            target=self._recv_routine, name="abci-socket-recv", daemon=True
        )
        self._recv_thread.start()

    def on_stop(self) -> None:
        if self._sock:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()

    def _recv_routine(self) -> None:
        buf = b""
        try:
            while True:
                chunk = self._sock.recv(1 << 16)
                if not chunk:
                    raise ClientError("ABCI socket closed by server")
                buf += chunk
                while True:
                    try:
                        ln, pos = decode_varint(buf)
                    except ValueError as e:
                        if "truncated" in str(e):
                            break  # need more bytes
                        raise ClientError(f"malformed response length prefix: {e}")
                    if len(buf) - pos < ln:
                        break
                    frame, buf = buf[pos : pos + ln], buf[pos + ln :]
                    self._on_response(pb.Response.decode(frame))
        except Exception as e:  # noqa: BLE001 - propagate as client error
            # Set _err and drain under the same lock _queue appends under:
            # any entry appended before this drain is completed here; any
            # append attempted after sees _err and raises — no future can
            # be left dangling between the two.
            with self._pending_mtx:
                self._err = self._err or e
                pending, self._pending = list(self._pending), deque()
            for _, rr in pending:
                rr.set_done(pb.Response(exception=pb.ExceptionResponse(error=str(e))))

    def _on_response(self, resp: pb.Response) -> None:
        which = resp.which()
        with self._pending_mtx:
            if not self._pending:
                self._err = ClientError(f"unexpected response {which}")
                return
            method, rr = self._pending.popleft()
        want = METHODS[method][1]
        if which not in (want, "exception"):
            self._err = ClientError(f"response {which} for request {method}")
        rr.set_done(resp)

    def _queue(self, method: str, msg) -> ReqRes:
        req = pb.Request(**{METHODS[method][0]: msg})
        rr = ReqRes(req)
        with self._write_mtx:
            # error check and append share _pending_mtx with the reader's
            # death path, so a ReqRes can never slip in after the drain
            with self._pending_mtx:
                if self._err:
                    raise ClientError(f"ABCI client failed: {self._err}")
                self._pending.append((method, rr))
            payload = req.encode()
            try:
                self._sock.sendall(encode_varint(len(payload)) + payload)
            except Exception as e:  # noqa: BLE001
                # sendall on a half-closed socket: complete the future we
                # just queued so no caller blocks forever on rr.wait(None)
                with self._pending_mtx:
                    self._err = self._err or e
                    try:
                        self._pending.remove((method, rr))
                    except ValueError:
                        pass  # reader's death path already drained it
                rr.set_done(
                    pb.Response(exception=pb.ExceptionResponse(error=str(e)))
                )
                raise ClientError(f"ABCI socket write failed: {e}")
        return rr

    def _do(self, method: str, msg):
        rr = self._queue(method, msg)
        # flush after every sync request so the server's buffered reader
        # can't hold our frame (reference sends Flush the same way)
        if method != "flush":
            self._queue("flush", pb.FlushRequest())
        # sync calls wait as long as the app takes (a FinalizeBlock on a big
        # block may exceed any fixed timeout; the reference blocks too) —
        # connection death completes the future with an exception instead
        resp = rr.wait(None)
        if resp.exception is not None:
            raise ClientError(resp.exception.error)
        return resp.value()

    def check_tx_async(self, req: pb.CheckTxRequest) -> ReqRes:
        rr = self._queue("check_tx", req)
        self._queue("flush", pb.FlushRequest())
        return rr
