"""gRPC ABCI transport: client + server over the reference's service.

Reference: abci/client/grpc_client.go, abci/server/grpc_server.go, and
proto/cometbft/abci/v1/service.proto — the `cometbft.abci.v1.ABCIService`
unary service whose 16 methods mirror the socket protocol's request
oneof.  Messages ride the framework's own deterministic proto codec
(wire/abci_pb.py — field numbers match the reference protos), plugged
into grpcio as custom (de)serializers via a generic handler, so no
generated stubs are needed and the wire bytes stay byte-compatible with
the reference's generated Go structs.

Transport selection: a `proxy_app` (or kvstore CLI) address of
`grpc://host:port` picks this transport; `tcp://` keeps the
varint-framed socket protocol.
"""

from __future__ import annotations

import threading

from ..utils.service import Service
from ..wire import abci_pb as pb
from .types import Application

_SERVICE = "cometbft.abci.v1.ABCIService"

# python method name -> (gRPC method name, request class, response class)
GRPC_METHODS: dict[str, tuple[str, type, type]] = {
    "echo": ("Echo", pb.EchoRequest, pb.EchoResponse),
    "flush": ("Flush", pb.FlushRequest, pb.FlushResponse),
    "info": ("Info", pb.InfoRequest, pb.InfoResponse),
    "check_tx": ("CheckTx", pb.CheckTxRequest, pb.CheckTxResponse),
    "query": ("Query", pb.QueryRequest, pb.QueryResponse),
    "commit": ("Commit", pb.CommitRequest, pb.CommitResponse),
    "init_chain": ("InitChain", pb.InitChainRequest, pb.InitChainResponse),
    "list_snapshots": (
        "ListSnapshots", pb.ListSnapshotsRequest, pb.ListSnapshotsResponse,
    ),
    "offer_snapshot": (
        "OfferSnapshot", pb.OfferSnapshotRequest, pb.OfferSnapshotResponse,
    ),
    "load_snapshot_chunk": (
        "LoadSnapshotChunk",
        pb.LoadSnapshotChunkRequest,
        pb.LoadSnapshotChunkResponse,
    ),
    "apply_snapshot_chunk": (
        "ApplySnapshotChunk",
        pb.ApplySnapshotChunkRequest,
        pb.ApplySnapshotChunkResponse,
    ),
    "prepare_proposal": (
        "PrepareProposal",
        pb.PrepareProposalRequest,
        pb.PrepareProposalResponse,
    ),
    "process_proposal": (
        "ProcessProposal",
        pb.ProcessProposalRequest,
        pb.ProcessProposalResponse,
    ),
    "extend_vote": ("ExtendVote", pb.ExtendVoteRequest, pb.ExtendVoteResponse),
    "verify_vote_extension": (
        "VerifyVoteExtension",
        pb.VerifyVoteExtensionRequest,
        pb.VerifyVoteExtensionResponse,
    ),
    "finalize_block": (
        "FinalizeBlock", pb.FinalizeBlockRequest, pb.FinalizeBlockResponse,
    ),
}

_BY_GRPC_NAME = {g: (m, rq, rs) for m, (g, rq, rs) in GRPC_METHODS.items()}


def _strip_scheme(addr: str) -> str:
    for scheme in ("grpc://", "tcp://"):
        if addr.startswith(scheme):
            return addr[len(scheme):]
    return addr


class GrpcServer(Service):
    """Serves an Application over `cometbft.abci.v1.ABCIService`
    (abci/server/grpc_server.go).  One mutex serializes application
    calls — same contract the socket server and LocalClient give apps."""

    def __init__(self, app: Application, addr: str, max_workers: int = 8):
        super().__init__("ABCIGrpcServer")
        self.app = app
        self.addr = _strip_scheme(addr)
        self._max_workers = max_workers
        self._server = None
        self.port = 0  # resolved on start (addr may say :0)
        self._app_mtx = threading.RLock()

    def on_start(self) -> None:
        import grpc
        from concurrent import futures

        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                name = details.method.rsplit("/", 1)
                if len(name) != 2 or name[0] != f"/{_SERVICE}":
                    return None
                entry = _BY_GRPC_NAME.get(name[1])
                if entry is None:
                    return None
                method, req_cls, _resp_cls = entry

                def unary(req, _ctx):
                    with outer._app_mtx:
                        if method == "echo":
                            return pb.EchoResponse(message=req.message)
                        if method == "flush":
                            return pb.FlushResponse()
                        return getattr(outer.app, method)(req)

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=req_cls.decode,
                    response_serializer=lambda m: m.encode(),
                )

        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=self._max_workers,
                thread_name_prefix="abci-grpc",
            ),
            handlers=(Handler(),),
        )
        self.port = self._server.add_insecure_port(self.addr)
        if self.port == 0:
            raise OSError(f"grpc server failed to bind {self.addr!r}")
        self._server.start()
        self.logger.info(f"ABCI gRPC server listening on port {self.port}")

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait()
            self._server = None


from .client import Client  # noqa: E402  (Client subclass below)


class GrpcClient(Client):
    """Synchronous unary client for a remote gRPC application
    (abci/client/grpc_client.go).  Implements the same Client interface
    as SocketClient, so proxy.AppConns and the engine are transport-
    agnostic."""

    def __init__(self, addr: str, must_connect: bool = True, timeout: float = 10.0):
        """timeout bounds ONLY the initial channel-ready connect probe.
        Per-call RPCs run with NO deadline: consensus-path methods
        (FinalizeBlock, Commit, PrepareProposal...) legitimately run as
        long as the application needs — a fixed per-call deadline would
        latch a fatal ClientError on a slow block and wedge the node,
        a failure mode the varint-socket transport deliberately avoids
        (its reads block indefinitely).  Liveness is the operator's job,
        exactly as in the reference grpc client (grpc_client.go uses
        context.Background() per call)."""
        super().__init__("ABCIGrpcClient")
        self.addr = _strip_scheme(addr)
        self.must_connect = must_connect
        self.timeout = timeout
        self._channel = None
        self._calls: dict = {}
        self._err: Exception | None = None

    def error(self) -> Exception | None:
        return self._err

    def on_start(self) -> None:
        import grpc

        self._channel = grpc.insecure_channel(self.addr)
        if self.must_connect:
            grpc.channel_ready_future(self._channel).result(
                timeout=self.timeout
            )
        # one multicallable per method, built once — check_tx rides the
        # mempool hot path, so per-call handler construction would be
        # pure overhead
        self._calls = {
            method: self._channel.unary_unary(
                f"/{_SERVICE}/{grpc_name}",
                request_serializer=lambda m: m.encode(),
                response_deserializer=resp_cls.decode,
            )
            for method, (grpc_name, _rq, resp_cls) in GRPC_METHODS.items()
        }

    def on_stop(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._calls = {}

    def _do(self, method: str, msg):
        from .client import ClientError

        if self._channel is None:
            raise ClientError("grpc client not started")
        try:
            # no deadline: see __init__ — a slow FinalizeBlock must block,
            # not latch a fatal transport error
            return self._calls[method](msg)
        except ClientError:
            raise
        except Exception as e:  # noqa: BLE001 — surface as client error
            self._err = e
            raise ClientError(f"grpc {method}: {e}") from e


def grpc_client_creator(addr: str, must_connect: bool = True):
    """proxy.ClientCreator for grpc:// application addresses."""
    return lambda: GrpcClient(addr, must_connect=must_connect)
