"""Application interface + no-op base (reference: abci/types/application.go:11-41,48).

Twelve methods in four connection groups:
  Info/Query:   info, query
  Mempool:      check_tx
  Consensus:    init_chain, prepare_proposal, process_proposal,
                finalize_block, extend_vote, verify_vote_extension, commit
  Statesync:    list_snapshots, offer_snapshot, load_snapshot_chunk,
                apply_snapshot_chunk

Requests/responses are the wire messages themselves (wire/abci_pb.py);
there is no separate domain layer — the reference's generated structs play
both roles too.
"""

from __future__ import annotations

from ..wire import abci_pb as pb

CodeTypeOK = 0


class Application:
    """Any finite deterministic state machine, replicated by the engine."""

    # Info/Query connection
    def info(self, req: pb.InfoRequest) -> pb.InfoResponse:
        raise NotImplementedError

    def query(self, req: pb.QueryRequest) -> pb.QueryResponse:
        raise NotImplementedError

    # Mempool connection
    def check_tx(self, req: pb.CheckTxRequest) -> pb.CheckTxResponse:
        raise NotImplementedError

    # Consensus connection
    def init_chain(self, req: pb.InitChainRequest) -> pb.InitChainResponse:
        raise NotImplementedError

    def prepare_proposal(
        self, req: pb.PrepareProposalRequest
    ) -> pb.PrepareProposalResponse:
        raise NotImplementedError

    def process_proposal(
        self, req: pb.ProcessProposalRequest
    ) -> pb.ProcessProposalResponse:
        raise NotImplementedError

    def finalize_block(
        self, req: pb.FinalizeBlockRequest
    ) -> pb.FinalizeBlockResponse:
        raise NotImplementedError

    def extend_vote(self, req: pb.ExtendVoteRequest) -> pb.ExtendVoteResponse:
        raise NotImplementedError

    def verify_vote_extension(
        self, req: pb.VerifyVoteExtensionRequest
    ) -> pb.VerifyVoteExtensionResponse:
        raise NotImplementedError

    def commit(self, req: pb.CommitRequest) -> pb.CommitResponse:
        raise NotImplementedError

    # Statesync connection
    def list_snapshots(
        self, req: pb.ListSnapshotsRequest
    ) -> pb.ListSnapshotsResponse:
        raise NotImplementedError

    def offer_snapshot(
        self, req: pb.OfferSnapshotRequest
    ) -> pb.OfferSnapshotResponse:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: pb.LoadSnapshotChunkRequest
    ) -> pb.LoadSnapshotChunkResponse:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: pb.ApplySnapshotChunkRequest
    ) -> pb.ApplySnapshotChunkResponse:
        raise NotImplementedError


class BaseApplication(Application):
    """No-op base returning sane defaults (application.go:48-110);
    accept-all proposals, empty results."""

    def info(self, req):
        return pb.InfoResponse()

    def query(self, req):
        return pb.QueryResponse(code=CodeTypeOK)

    def check_tx(self, req):
        return pb.CheckTxResponse(code=CodeTypeOK)

    def init_chain(self, req):
        return pb.InitChainResponse()

    def prepare_proposal(self, req):
        # default: keep txs up to the size limit (application.go:84-96)
        total, txs = 0, []
        for tx in req.txs:
            total += len(tx)
            if req.max_tx_bytes and total > req.max_tx_bytes:
                break
            txs.append(tx)
        return pb.PrepareProposalResponse(txs=txs)

    def process_proposal(self, req):
        return pb.ProcessProposalResponse(status=pb.PROCESS_PROPOSAL_STATUS_ACCEPT)

    def finalize_block(self, req):
        return pb.FinalizeBlockResponse(
            tx_results=[pb.ExecTxResult(code=CodeTypeOK) for _ in req.txs]
        )

    def extend_vote(self, req):
        return pb.ExtendVoteResponse()

    def verify_vote_extension(self, req):
        return pb.VerifyVoteExtensionResponse(
            status=pb.VERIFY_VOTE_EXTENSION_STATUS_ACCEPT
        )

    def commit(self, req):
        return pb.CommitResponse()

    def list_snapshots(self, req):
        return pb.ListSnapshotsResponse()

    def offer_snapshot(self, req):
        return pb.OfferSnapshotResponse()

    def load_snapshot_chunk(self, req):
        return pb.LoadSnapshotChunkResponse()

    def apply_snapshot_chunk(self, req):
        return pb.ApplySnapshotChunkResponse()


# method name -> (request oneof field, response oneof field); used by the
# socket client/server to route oneof frames.
METHODS = {
    "echo": ("echo", "echo"),
    "flush": ("flush", "flush"),
    "info": ("info", "info"),
    "init_chain": ("init_chain", "init_chain"),
    "query": ("query", "query"),
    "check_tx": ("check_tx", "check_tx"),
    "commit": ("commit", "commit"),
    "list_snapshots": ("list_snapshots", "list_snapshots"),
    "offer_snapshot": ("offer_snapshot", "offer_snapshot"),
    "load_snapshot_chunk": ("load_snapshot_chunk", "load_snapshot_chunk"),
    "apply_snapshot_chunk": ("apply_snapshot_chunk", "apply_snapshot_chunk"),
    "prepare_proposal": ("prepare_proposal", "prepare_proposal"),
    "process_proposal": ("process_proposal", "process_proposal"),
    "extend_vote": ("extend_vote", "extend_vote"),
    "verify_vote_extension": ("verify_vote_extension", "verify_vote_extension"),
    "finalize_block": ("finalize_block", "finalize_block"),
}
