"""Sharded verification kernels (shard_map over a device Mesh).

One Commit = N independent signature checks plus a Merkle pass over the
block — embarrassingly parallel across chips.  Shardings:

  - signatures: batch axis sharded over "sig"; each device runs the fused
    Ed25519 kernel on its shard; a psum over invalid counts yields the
    global all-valid bit while the per-signature validity vector stays
    sharded (gathered once at the end for blame, validation.go:384-399).
  - Merkle leaves: leaf axis sharded over "sig" too (leaf counts per
    device stay static); each device reduces its subtree, then the D
    subtree roots are all_gathered and folded level-by-level, replicated.

Everything is jit-compiled once per (shape, mesh) and reused; the commit
verification step is the framework's flagship compiled program.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.7 promotes shard_map out of experimental
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"  # pre-0.7 name for the same switch


def shard_map(f, *, mesh, in_specs, out_specs):
    # Disable the varying-manual-axes checker: the SHA-2 fori_loop carries
    # mix varying/unvarying per-device types; the collectives below
    # establish replication explicitly, so the static check adds nothing.
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{_CHECK_KW: False}
    )

from ..ops import ed25519 as E
from ..ops import merkle as M
from ..utils import tracing
from .mesh import mesh_cache_key

# Compiled sharded programs, keyed on STABLE mesh identity
# (mesh_cache_key: device ids + topology + axis names) plus any
# trace-time knob flag — never on Mesh object identity.  Two equivalent
# meshes built by separate make_mesh calls hand out the SAME program
# object, so nothing re-traces or re-compiles per mesh entry
# (tests/test_shardcheck.py pins one-program-per-equivalent-mesh).
_PROGRAMS: dict[tuple, object] = {}
_PROGRAMS_MTX = threading.Lock()


def _cached_program(key: tuple):
    with _PROGRAMS_MTX:
        return _PROGRAMS.get(key)


def _publish_program(key: tuple, fn):
    """First publisher wins; a racing builder adopts the winner so every
    caller shares one traced/compiled program per key."""
    with _PROGRAMS_MTX:
        return _PROGRAMS.setdefault(key, fn)


def _verify_fn(mesh: Mesh):
    """jit-wrapped sharded verifier, cached per equivalent mesh — without
    the jit every call re-traces the whole kernel and nothing reaches the
    persistent compile cache (this made the un-jitted path effectively
    un-runnable on the CPU backend).

    The jit carries EXPLICIT ``in_shardings``/``out_shardings`` matching
    the shard_map specs: a host batch lands directly in its sharded
    layout (one scatter-free transfer per device), an already-sharded
    device buffer is consumed in place, and a mislaid input can never
    silently reshard at the pjit boundary — the stage-handoff contract
    of docs/sharding_contracts.md.  Every argument is a per-call staging
    transfer, dead after dispatch, so ALL FIVE are donated (the device
    may reuse their HBM for outputs); callers must pass fresh arrays and
    never read them after the call (``donated-read-after-dispatch``
    enforces this statically at declared entrypoints).

    Manifest kernel ``sharded_verify_batch``: the contract checker calls
    this factory with a 1-device CPU mesh and pins the traced program
    (the collective mix — psum/all_gather — is part of the fingerprint);
    analysis/shardcheck.py re-traces it under a real 8-way CPU mesh and
    holds it to the declared shardings/collective census/budgets,
    including the donation vector.
    """
    key = ("verify_batch", mesh_cache_key(mesh))
    cached = _cached_program(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def local(a, r, s, blocks, active):
        ok = E.verify_batch(a, r, s, blocks, active)
        bad = jnp.sum((~ok).astype(jnp.int32))
        total_bad = jax.lax.psum(bad, axis)
        all_ok = jax.lax.all_gather(ok, axis, tiled=True)
        return total_bad == 0, all_ok

    row = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(), P()),
        ),
        in_shardings=(row, row, row, row, row),
        out_shardings=(repl, repl),
        donate_argnums=(0, 1, 2, 3, 4),
    )
    return _publish_program(key, fn)


def sharded_verify_batch(mesh: Mesh, a_enc, r_enc, s_bytes, msg_blocks, msg_active):
    """Batch Ed25519 verify with the batch axis sharded over mesh axis "sig".

    Returns (all_valid: bool scalar, valid: (N,) bool fully replicated).
    N must be divisible by the mesh size (callers pad to bucket sizes).

    ALL FIVE arrays are DONATED to the device program (each is a fresh
    per-call staging transfer): pass fresh arrays and never read them
    after this returns — the ``donated-read-after-dispatch`` check
    enforces it statically at call sites of this entrypoint.
    """
    with tracing.span(
        "verify.shard_dispatch",
        {"devices": int(mesh.devices.size)} if tracing.enabled() else None,
    ):
        return _verify_fn(mesh)(a_enc, r_enc, s_bytes, msg_blocks, msg_active)


def _comb_verify_fn(mesh: Mesh, tree: bool):
    """Sharded comb-cached commit verification — the engine's production
    path (models/comb_verifier.py) over a device mesh.

    Shardings: the comb tables' VALIDATOR axis (their minor lane axis,
    ops/comb.py layout (64, 9, 3, 22, V)) and every per-call row array
    shard over "sig"; the 24 MB base-point table is replicated.  A psum
    over bad counts yields the global all-ok bit; the per-validator
    bitmap is all_gathered and packed on every device (replicated).
    A 10k-validator set's 1.5 GB of tables become ~190 MB per chip on an
    8-chip mesh — the component that most needs sharding.

    tree selects the accumulation path (ops/comb tree_enabled) and is
    part of the cache key, so flipping COMETBFT_TPU_COMB_TREE between
    calls never serves a stale compiled program.  Both paths are
    lane-local over the validator axis, so sharding is unaffected.

    The per-call payload rows are DONATED (donate_argnums=(3,)): the
    staging buffer's device copy is consumed by the dispatch and its HBM
    is reusable for the outputs — host code must never touch the device
    payload after submit (models/comb_verifier stages a fresh
    ``jnp.asarray`` per call and recycles only the HOST slab; the
    ``donated-read-after-dispatch`` lint check and shardcheck's donation
    contract keep it that way).  Tables/valid/pubs persist across calls
    in the cache entry and are never donated.

    Manifest kernel ``sharded_verify_cached`` (traced with tree=True).
    """
    key = ("verify_cached", mesh_cache_key(mesh), "tree" if tree else "seq")
    cached = _cached_program(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]
    import jax.numpy as jnp

    from ..ops import comb, sha2

    bt = comb.get_b_tables()

    def local(tables, valid, pubs, payload):
        r, s, blocks, active, live = sha2.parse_verify_payload(payload, pubs)
        dig = sha2.sha512_blocks(blocks, active)
        ok = comb.verify_cached(tables, valid, r, s, dig, bt, tree=tree)
        bad = jnp.sum((~(ok | ~live)).astype(jnp.int32))
        total_bad = jax.lax.psum(bad, axis)
        ok_all = jax.lax.all_gather(ok & live, axis, tiled=True)
        # one replicated [bitmap | all_ok] array — a single host fetch
        return jnp.concatenate(
            [jnp.packbits(ok_all), (total_bad == 0).astype(jnp.uint8)[None]]
        )

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(None, None, None, None, axis),  # tables: validator lanes
                P(axis),
                P(axis, None),  # pubs
                P(axis, None),  # payload rows
            ),
            out_specs=P(),
        ),
        # explicit shardings = the stage-handoff contract: the cache
        # entry's device-resident tables/valid/pubs (placed by
        # _finish_entry with these exact NamedShardings) are consumed in
        # place — no resharding copy at the pjit boundary — and the
        # host-staged payload transfers straight into its row layout
        in_shardings=(
            NamedSharding(mesh, P(None, None, None, None, axis)),
            NamedSharding(mesh, P(axis)),
            NamedSharding(mesh, P(axis, None)),
            NamedSharding(mesh, P(axis, None)),
        ),
        out_shardings=NamedSharding(mesh, P()),
        # the payload is a per-call staging transfer, dead after dispatch
        donate_argnums=(3,),
    )
    return _publish_program(key, fn)


def sharded_verify_cached(mesh: Mesh, tables, valid, pubs, payload):
    """Comb-cached VerifyCommit with validators sharded over the mesh.

    payload: (V, 68 + maxm) uint8 tight rows (R | s | mlen 3B LE | live |
    msg) — SHA blocks are assembled on device (ops/sha2) so only
    irreducible bytes cross the host->device link.  V must be divisible
    by the mesh size (the comb cache pads entries to lane buckets).
    Returns one uint8 array [packbits(ok & live) | all_ok byte] — the
    same single-fetch contract as models/comb_verifier._device_verify.

    ``payload`` is DONATED to the device program: pass a fresh per-call
    array and never read it again after this returns.  The
    donated-read-after-dispatch check flags violations statically at
    direct and same-scope partial-bound call sites; for handles that
    cross a function boundary (models/comb_verifier stores the partial
    on its cache entry), stage the donated value inline in the call
    expression — never bind it — as stage() does.
    """
    from ..ops import comb

    with tracing.span(
        "verify.shard_dispatch",
        {"devices": int(mesh.devices.size)} if tracing.enabled() else None,
    ):
        return _comb_verify_fn(mesh, comb.tree_enabled())(
            tables, valid, pubs, payload
        )


def _merkle_fn(mesh: Mesh):
    # Manifest kernel ``sharded_merkle_root``.  Explicit shardings +
    # donation like the verify stages: the leaf blocks are a per-call
    # staging transfer, dead after dispatch.
    key = ("merkle_root", mesh_cache_key(mesh))
    cached = _cached_program(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def local(blocks, active):
        sub = M.root_from_leaves(blocks, active)  # (32,)
        roots = jax.lax.all_gather(sub, axis)  # (D, 32)
        return M.root_from_leaf_hashes(roots)

    row = NamedSharding(mesh, P(axis))
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=P(),
        ),
        in_shardings=(row, row),
        out_shardings=NamedSharding(mesh, P()),
        donate_argnums=(0, 1),
    )
    return _publish_program(key, fn)


def sharded_merkle_root(mesh: Mesh, leaf_blocks, leaf_active):
    """Merkle root with leaves sharded over the mesh's first axis.

    Each device leaf-hashes and reduces its (n/D)-leaf subtree, then the D
    subtree roots are all_gathered and folded on every device (replicated
    result).  Exactly the reference's power-of-two split (tree.go:101)
    when n/D is a power of two — which callers guarantee by padding.

    Both arrays are DONATED (per-call staging transfers): pass fresh
    arrays and never read them after this returns.
    """
    return _merkle_fn(mesh)(leaf_blocks, leaf_active)


def _merkle_proofs_fn(mesh: Mesh):
    """Sharded batched proof generation — the QUERY axis shards, the tree
    replicates.  Each device recomputes every reduction level from the
    replicated leaf blocks (cheap: the tree is one batched SHA-256 pass)
    and one-hot-gathers audit paths for its own query shard, so the
    kernel needs ZERO collectives — the per-query outputs come back
    sharded exactly as the queries went in, and the root is replicated
    by construction.

    Only the query arrays are donated: they are per-call staging
    transfers, while callers may legitimately reuse the (replicated)
    leaf blocks across several proof dispatches against the same tree.

    Manifest kernel ``sharded_merkle_proofs``.
    """
    key = ("merkle_proofs", mesh_cache_key(mesh))
    cached = _cached_program(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def local(blocks, active, indices, sib_pos):
        return M.proofs_from_leaves(blocks, active, indices, sib_pos)

    repl = NamedSharding(mesh, P())
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(axis), P(axis, None)),
            out_specs=(P(), P(axis), P(axis, None, None)),
        ),
        in_shardings=(repl, repl, NamedSharding(mesh, P(axis)),
                      NamedSharding(mesh, P(axis, None))),
        out_shardings=(repl, NamedSharding(mesh, P(axis)),
                       NamedSharding(mesh, P(axis, None, None))),
        donate_argnums=(2, 3),
    )
    return _publish_program(key, fn)


def sharded_merkle_proofs(mesh: Mesh, blocks, active, indices, sib_pos):
    """Batched audit paths with the query axis sharded over the mesh.

    blocks/active: host-padded leaves (ops/merkle.pad_leaves), replicated;
    indices (K,) i32 and sib_pos (K, D) i32 (crypto/merkle.proof_plan)
    shard over the mesh's first axis — K must be divisible by the mesh
    size (callers pad the query list; index-0 padding rows are harmless
    extra gathers the host slices away).  Returns (root (32,) replicated,
    leaf_sel (K, 32), aunts (K, D, 32)) with per-query outputs sharded
    like the queries.

    ``indices`` and ``sib_pos`` are DONATED (per-call staging transfers):
    pass fresh arrays and never read them after this returns.
    """
    with tracing.span(
        "verify.shard_dispatch",
        {"devices": int(mesh.devices.size)} if tracing.enabled() else None,
    ):
        return _merkle_proofs_fn(mesh)(blocks, active, indices, sib_pos)


def commit_verification_step(
    mesh: Mesh, a_enc, r_enc, s_bytes, msg_blocks, msg_active, leaf_blocks, leaf_active
):
    """The flagship step: verify a Commit's signature batch and recompute
    the block's Merkle root, both sharded over the mesh.

    Mirrors what finalizeCommit does per height on the host reference
    (state/validation.go:94 VerifyCommit + types/block.go hashing).
    """
    all_ok, valid = sharded_verify_batch(
        mesh, a_enc, r_enc, s_bytes, msg_blocks, msg_active
    )
    root = sharded_merkle_root(mesh, leaf_blocks, leaf_active)
    return all_ok, valid, root
