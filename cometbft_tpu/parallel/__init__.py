"""Device-mesh parallelism for the verification plane.

The reference's only data-parallel kernel is commit batch verification
(types/validation.go:265), plus per-block Merkle hashing.  Here both are
sharded across a `jax.sharding.Mesh` with `shard_map`: signatures shard
across the "sig" axis the way sequence parallelism shards tokens, Merkle
leaves across the "leaf" axis, and ICI collectives (psum / all_gather)
combine per-shard results into the global verdict.
"""

from .mesh import make_mesh, device_count
from .verify import sharded_verify_batch, sharded_merkle_root, commit_verification_step
