"""Mesh construction helpers.

The ``np.array`` calls below wrap the host device list — they never
materialize a device array; the host-sync-in-hot-path check recognizes
``jax.devices()`` dataflow and does not flag them."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices: int | None = None, axis: str = "sig") -> Mesh:
    """1-D mesh over the first n devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_mesh_2d(n_sig: int, n_leaf: int) -> Mesh:
    """2-D mesh: signature-parallel x leaf-parallel."""
    devs = np.array(jax.devices()[: n_sig * n_leaf]).reshape(n_sig, n_leaf)
    return Mesh(devs, ("sig", "leaf"))


def mesh_cache_key(mesh: Mesh) -> tuple:
    """Stable identity of a mesh for compiled-program caches.

    Two meshes over the same devices (by id), same topology, and same
    axis names run the SAME compiled program — but they are not
    guaranteed to be the same (or even equal) Python objects across
    `make_mesh` calls, and a cache keyed on object identity re-traces
    and re-compiles per equivalent mesh (minutes on a cold pod).  Every
    program cache in parallel/verify.py keys on this tuple instead;
    analysis/shardcheck.py enforces the sharded plane's contracts on the
    programs those caches hand out."""
    return (
        tuple(int(d.id) for d in mesh.devices.flat),
        mesh.devices.shape,
        tuple(mesh.axis_names),
    )
