"""Vectorized Edwards25519 group operations for TPU.

Points are batches in extended twisted-Edwards coordinates (X:Y:Z:T),
a = -1, held as four GF(2^255-19) limb arrays in the limbs-first layout of
ops/field.py: each coordinate is (..., 22, L) with the lane/batch axis
minor (full 128-lane utilization on the VPU) and the 22 limbs on
sublanes.  The a=-1 addition law is complete on this curve, so every
operation below is branch-free — no exceptional cases, no data-dependent
control flow — exactly what XLA needs to tile the 10k-signature batch
onto the vector unit.

Scalar multiplication uses Straus/Shamir interleaving with 4-bit windows:
one shared doubling chain evaluates [s]B + [k]A' per signature with 256
doublings + 2x64 window additions.  Window lookups are one-hot
multiply-reduce (16-way select) rather than gathers — on TPU a masked
reduction vectorizes; a gather would serialize.

Verification semantics are ZIP-215 / cofactored, matching the reference
validator hot path (crypto/ed25519/ed25519.go:36-42, verified against
types/validation.go:265 verifyCommitBatch expectations):
  - non-canonical y encodings accepted (y >= p reduces mod p),
  - x = 0 with sign bit 1 accepted,
  - s < L enforced (checked in ops/scalar.py),
  - equation checked with cofactor 8: [8][s]B == [8]R + [8][k]A.

Range contract (proved by analysis/rangecheck.py, pinned in
analysis/range_fingerprints.json entry ``ed25519_verify_batch``): with
inputs at their manifest-declared ranges, every int32 intermediate of
the full verify walk stays within |x| <= 1,252,794,005 — about 0.78
bits of int32 headroom at the tightest point (the field-mul conv
partial sums).  The contract leans on two limb invariants from
ops/field.py: TIGHT (|limb0| <= 3584, others <= 2051) out of carry,
and MULIN (|limb0| <= 14336, others <= 8204) into mul — any point sum
wider than MULIN must pass through F.carry before the next mul (see
niels_to_extended for the one production site where this bit).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax.numpy as jnp
from jax import lax

from . import field as F
from ..crypto import _ref25519 as ref


class Point(NamedTuple):
    """Batched extended coordinates; each field is (..., 22, L) int32 limbs."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


# ---------------------------------------------------------------- constants

_D_L = F.to_limbs(ref.D)
_D2_L = F.to_limbs(ref.D2)
_SQRT_M1_L = F.to_limbs(ref.SQRT_M1)


def _c(limbs: np.ndarray):
    """(22,) host constant -> (22, 1) broadcastable device constant."""
    return jnp.asarray(limbs[:, None])


def identity(batch_shape=()) -> Point:
    return Point(
        F.zero(batch_shape), F.one(batch_shape), F.one(batch_shape), F.zero(batch_shape)
    )


def neg(p: Point) -> Point:
    return Point(-p.x, p.y, p.z, -p.t)


def select(cond, p: Point, q: Point) -> Point:
    """Branch-free point select: cond ? p : q (cond = batch-shaped bool)."""
    return Point(
        F.select(cond, p.x, q.x),
        F.select(cond, p.y, q.y),
        F.select(cond, p.z, q.z),
        F.select(cond, p.t, q.t),
    )


# ---------------------------------------------------------------- group law


def add(p: Point, q: Point) -> Point:
    """Unified complete addition (9 field muls)."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q.y, q.x))
    b = F.mul(F.add(p.y, p.x), F.add(q.y, q.x))
    c = F.mul(F.mul(p.t, q.t), _c(_D2_L))
    d = F.mul(p.z, q.z)
    d = F.add(d, d)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def double(p: Point) -> Point:
    """Dedicated doubling (4 squares + 4 muls), complete for all inputs."""
    a = F.square(p.x)
    b = F.square(p.y)
    zz = F.square(p.z)
    e = F.sub(F.sub(F.square(F.add(p.x, p.y)), a), b)
    g = F.sub(b, a)
    f = F.sub(F.sub(g, zz), zz)  # G - 2Z^2
    h = F.sub(F.neg(a), b)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


class Niels(NamedTuple):
    """Precomputed affine point: (y+x, y-x, 2d*x*y); Z is implicitly 1."""

    yplusx: jnp.ndarray
    yminusx: jnp.ndarray
    t2d: jnp.ndarray


def add_niels(p: Point, n: Niels) -> Point:
    """Mixed addition with a precomputed affine point (7 field muls)."""
    a = F.mul(F.sub(p.y, p.x), n.yminusx)
    b = F.mul(F.add(p.y, p.x), n.yplusx)
    c = F.mul(p.t, n.t2d)
    d = F.add(p.z, p.z)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def niels_identity_like(n: Niels) -> Niels:
    """The identity in Niels form: (1, 1, 0)."""
    shape = n.yplusx.shape[:-2] + n.yplusx.shape[-1:]
    return Niels(F.one(shape), F.one(shape), F.zero(shape))


_INV_D_L = F.to_limbs(pow(ref.D, ref.P - 2, ref.P))


def niels_to_extended(n: Niels) -> Point:
    """Niels (y+x, y-x, 2dxy) -> extended (2x : 2y : 2 : 2xy).

    One field mul (t2d * d^-1); the uniform projective scale by 2 is
    free.  Lets precomputed table entries join unified additions — in
    particular the log-depth tree fold of tree_reduce_points, whose
    inputs must be full extended points.  Works for the identity
    ((1,1,0) -> (0:2:2:0)) and for sign-flipped entries
    ((y-x, y+x, -2dxy) -> (-2x : 2y : 2 : -2xy)).
    """
    # Carry the lifted sums back into the TIGHT profile: for canonical
    # table entries the raw y+x +/- y-x limbs reach +/-8190, and the
    # FIRST tree fold adds two lifted points — its F.add(p.y, p.x) would
    # hit +/-12285 per limb, past the MULIN contract (|limb|<=8204), and
    # the mul conv partial sums would clear 2^31 on adversarial
    # (attacker-chosen pubkey) tables.  One carry pass is elementwise
    # shifts, noise next to the fold's 9 muls; the range certificate
    # (analysis/range_fingerprints.json, comb_verify_cached_tree) pins
    # the proof.
    x2 = F.carry(F.sub(n.yplusx, n.yminusx))
    y2 = F.carry(F.add(n.yplusx, n.yminusx))
    batch = x2.shape[:-2] + x2.shape[-1:]
    one = F.one(batch)
    return Point(x2, y2, F.add(one, one), F.mul(n.t2d, _c(_INV_D_L)))


def tree_reduce_points(p: Point) -> Point:
    """Sum a stacked (N, ..., 22, L) Point along its leading axis with a
    binary tree of batched unified additions: ceil(log2(N)) dependent
    rounds instead of an (N-1)-deep sequential accumulation chain.  The
    addition law is complete, so identity entries and odd-level
    carry-overs are safe anywhere in the tree.  This is the comb verify
    kernel's accumulation primitive (ops/comb._accumulate_tree): its
    87-point stack folds in 7 rounds instead of 86.
    """
    n = p.x.shape[0]
    while n > 1:
        half = n // 2
        a = Point(*(c[:half] for c in p))
        b = Point(*(c[half : 2 * half] for c in p))
        s = add(a, b)
        if n & 1:
            s = Point(
                *(
                    jnp.concatenate([cs, cp[2 * half :]], axis=0)
                    for cs, cp in zip(s, p)
                )
            )
        p = s
        n = (n + 1) // 2
    return Point(*(c[0] for c in p))


# ------------------------------------------------------------ (de)compress


def decompress(enc):
    """(..., 32) uint8 -> (Point, ok).  ZIP-215 semantics (see module doc).

    The Point's lane axis is enc's last batch axis; ok keeps enc's batch
    shape.  Invalid encodings yield ok=False and an arbitrary (but
    well-formed) point so downstream arithmetic stays branch-free.
    """
    sign = (lax.shift_right_logical(enc[..., 31].astype(jnp.int32), 7) & 1).astype(
        jnp.int32
    )
    masked = enc.at[..., 31].set(enc[..., 31] & jnp.uint8(0x7F))
    y = F.from_bytes(masked)
    batch = y.shape[:-2] + y.shape[-1:]
    yy = F.square(y)
    u = F.sub(yy, F.one(batch))
    v = F.add(F.mul(yy, _c(_D_L)), F.one(batch))
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    x = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))
    vxx = F.mul(v, F.square(x))
    ok_direct = F.eq(vxx, u)
    ok_flipped = F.eq(vxx, F.neg(u))
    x = F.select(ok_flipped, F.mul(x, _c(_SQRT_M1_L)), x)
    ok = ok_direct | ok_flipped
    # Match the requested sign bit (x = 0, sign = 1 stays x = 0: accepted).
    flip = F.is_negative(x) != (sign == 1)
    x = F.select(flip, F.neg(x), x)
    pt = Point(x, y, F.one(batch), F.mul(x, y))
    return pt, ok


def compress(p: Point):
    """Point -> canonical (..., L, 32) uint8 encoding (batch-first bytes)."""
    zi = F.invert(p.z)
    x = F.mul(p.x, zi)
    y = F.mul(p.y, zi)
    b = F.to_bytes(y)
    signbit = (F.freeze(x)[..., 0, :] & 1).astype(jnp.uint8)
    return b.at[..., 31].set(b[..., 31] | (signbit << 7))


def is_identity(p: Point):
    """x == 0 and y == z (projective identity test)."""
    return F.is_zero(p.x) & F.eq(p.y, p.z)


def pt_eq(p: Point, q: Point):
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    return F.eq(F.mul(p.x, q.z), F.mul(q.x, p.z)) & F.eq(
        F.mul(p.y, q.z), F.mul(q.y, p.z)
    )


# ----------------------------------------------------- fixed-base B tables


def _host_niels(pt) -> np.ndarray:
    """Host: reference affine point -> (3, 22) niels limbs."""
    x, y, z, _ = pt
    zi = pow(z, ref.P - 2, ref.P)
    x, y = x * zi % ref.P, y * zi % ref.P
    return np.stack(
        [
            F.to_limbs((y + x) % ref.P),
            F.to_limbs((y - x) % ref.P),
            F.to_limbs(2 * ref.D * x % ref.P * y % ref.P),
        ]
    )


def _build_base_window_table() -> np.ndarray:
    """(16, 3, 22): j*B for j = 0..15 in Niels form (j=0 -> identity)."""
    out = np.zeros((16, 3, 22), dtype=np.int32)
    out[0] = np.stack([F.to_limbs(1), F.to_limbs(1), F.to_limbs(0)])
    acc = ref.BASE
    for j in range(1, 16):
        out[j] = _host_niels(acc)
        acc = ref.pt_add(acc, ref.BASE)
    return out


_B_WINDOW = _build_base_window_table()
# (66, 16): flattened (3*22)-coord rows by entry, for the one-hot matmul
_B_WINDOW_FLAT = _B_WINDOW.reshape(16, 66).T.copy()


def lookup_niels(table_flat, idx) -> Niels:
    """One-hot select from a host table (66, 16) by (..., L) int32 idx.

    Returns Niels coords (..., 22, L): (66,16) @ onehot(..., 16, L)."""
    # int32 one-hot against the int32 host table: the lookup never
    # leaves the limb dtype (audited — only the radix-4096 B comb in
    # ops/comb.py takes the f32 MXU round trip, where it is exact)
    onehot = (
        idx[..., None, :] == jnp.arange(16, dtype=jnp.int32)[:, None]
    ).astype(jnp.int32)  # (..., 16, L)
    sel = jnp.matmul(jnp.asarray(table_flat), onehot)  # (..., 66, L)
    return Niels(sel[..., 0:22, :], sel[..., 22:44, :], sel[..., 44:66, :])


def build_var_table(a: Point) -> Point:
    """Stacked window table [0..15]*A with a new leading axis of size 16.

    1 double + 13 unified adds; entry j holds j*A.
    """
    batch = a.x.shape[:-2] + a.x.shape[-1:]
    entries = [identity(batch), a, double(a)]
    for j in range(3, 16):
        entries.append(add(entries[j - 1], a))
    return Point(
        jnp.stack([e.x for e in entries], axis=0),
        jnp.stack([e.y for e in entries], axis=0),
        jnp.stack([e.z for e in entries], axis=0),
        jnp.stack([e.t for e in entries], axis=0),
    )


def lookup_point(table: Point, idx) -> Point:
    """One-hot select from a stacked (16, ..., 22, L) point table by
    (..., L) idx."""
    onehot = (
        idx == jnp.arange(16, dtype=jnp.int32)[(...,) + (None,) * idx.ndim]
    ).astype(jnp.int32)[..., None, :]  # (16, ..., 1, L)

    def pick(coord):
        return jnp.sum(coord * onehot, axis=0)

    return Point(pick(table.x), pick(table.y), pick(table.z), pick(table.t))


# ------------------------------------------------------------ verification


def verify_prepared(a_enc, r_enc, s_windows, k_windows, s_ok):
    """Core batched verifier.

    Inputs (batch shape (..., L); byte arrays batch-first):
      a_enc, r_enc : (..., L, 32) uint8 — compressed pubkey / R point
      s_windows    : (..., 64, L) int32 — 4-bit windows of s, MSB first
      k_windows    : (..., 64, L) int32 — 4-bit windows of k = H(R,A,M) mod L
      s_ok         : (..., L) bool — s < L precondition (ops/scalar.s_lt_l)

    Returns (..., L) bool: [8]([s]B - [k]A - R) == identity, with decompress
    failures and s >= L forced to False.

    Straus interleave: acc := 16*acc + s_i*B + k_i*(-A) per window step,
    sharing one doubling chain; the per-signature (-A) window table is
    built once (1 dbl + 13 adds).  The step loop is a lax.fori_loop so the
    compiled graph is one window body regardless of scalar length.
    """
    a_pt, a_valid = decompress(a_enc)
    r_pt, r_valid = decompress(r_enc)
    neg_a = neg(a_pt)
    table = build_var_table(neg_a)  # windows of -A

    def step(i, acc):
        acc = double(double(double(double(acc))))
        acc = add(acc, lookup_point(table, k_at(i)))  # k_i * (-A)
        return add_niels(acc, lookup_niels(_B_WINDOW_FLAT, s_at(i)))  # s_i * B

    # fori_loop with dynamic window indexing along the window axis (-2).
    def k_at(i):
        return lax.dynamic_index_in_dim(k_windows, i, axis=-2, keepdims=False)

    def s_at(i):
        return lax.dynamic_index_in_dim(s_windows, i, axis=-2, keepdims=False)

    batch = a_enc.shape[:-1]
    acc = lax.fori_loop(0, 64, step, identity(batch))
    acc = add(acc, neg(r_pt))
    acc = double(double(double(acc)))
    return is_identity(acc) & a_valid & r_valid & s_ok


def verify_batch(a_enc, r_enc, s_bytes, msg_blocks, msg_active):
    """Full on-device batch verification.

    a_enc      : (N, 32) uint8 compressed pubkeys
    r_enc      : (N, 32) uint8 R points (first half of each signature)
    s_bytes    : (N, 32) uint8 s scalars (second half of each signature)
    msg_blocks : (N, nblocks, 128) uint8 — SHA-512-padded R || A || M
                 (host-assembled; see ops/sha2.pad_messages_sha512)
    msg_active : (N,) int32 per-row live block count

    Returns (N,) bool.  The entire pipeline — challenge hash, mod-L
    reduction, window extraction, double-scalar multiplication, cofactored
    identity check — runs as one fused XLA program on device; the reference
    does the same work per signature on CPU via curve25519-voi
    (crypto/ed25519/ed25519.go:220 BatchVerifier.Verify).

    Manifest kernel ``ed25519_verify_batch`` (jitted from
    models/verifier.py — the manifest, not a per-module scan, is what
    keeps this body visible to the static checks).  Also the lane-local
    shard_map body of ``sharded_verify_batch``: the sharded census
    (analysis/shardcheck) pins it to zero collectives of its own.
    """
    from . import sha2, scalar

    # RFC 8032 interprets the 64-byte digest as a little-endian integer.
    k_digest = sha2.sha512_blocks(msg_blocks, msg_active)  # (N, 64)
    k_limbs = scalar.reduce_mod_l(scalar.bytes_to_limbs(k_digest, scalar.NL_X))
    k_windows = scalar.limbs_to_windows(k_limbs)  # (64, N)
    s_windows = scalar.bytes_to_windows(s_bytes)  # (64, N)
    s_ok = scalar.s_lt_l(s_bytes)  # (N,)
    return verify_prepared(a_enc, r_enc, s_windows, k_windows, s_ok)
