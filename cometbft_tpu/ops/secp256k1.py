"""Vectorized secp256k1 ECDSA batch verification for TPU
(ROADMAP item 4; the FPGA verification-engine staging of PAPERS.md
arXiv:2112.02229: deep batching + amortized modular inversion +
parallel point multiplication, re-targeted at the vector unit).

This generalizes the word-wise Montgomery limb arithmetic proven for
BLS12-381 in ops/bls381.py to the secp256k1 base field AND its scalar
field: p256k1 = 2^256 - 2^32 - 977 is (like p381, unlike 2^255-19)
not close enough to a power of two for the ops/field.py carry-fold, so
field elements are 22 signed 12-bit limbs in int32 (batch axis
leading, limbs minor), R = 2^264, and every op returns canonical limbs
in [0, m).  The 44-limb product is one outer-product + one constant
anti-diagonal matmul; the reduction is a fori_loop (O(1) jaxpr in the
limb count).  int32 bounds: conv sums <= 22*4095^2 ~ 3.7e8, reduction
adds <= the same again — peak < 7.4e8 < 2^31.

The ECDSA batch (one fused program per bucket shape):

* **range / low-s validation on device** — r, s enter as raw 256-bit
  limb vectors; 1 <= r < n, 1 <= s < n and the Cosmos/Ethereum low-s
  rule s <= n/2 are borrow-chain compares over the batch.
* **Montgomery batch inversion** — the per-signature s^-1 (mod n) and
  the final affine normalization z^-1 (mod p) are amortized across the
  whole batch: log-depth Hillis-Steele prefix/suffix products, ONE
  Fermat inversion chain of the total product, two muls per row —
  instead of a 256-step exponentiation ladder of full-width batched
  muls per modulus.  Rows that would poison the shared product (s = 0,
  z = 0 from invalid inputs) are sanitized to 1 BEFORE the prefix
  products — the exact latent bug PR 11 found in the ed25519 comb
  table build; a malformed row can never corrupt a valid row's
  inverse (pinned by tests/test_secp_ops.py).
* **Shamir's-trick double-scalar multiplication** — u1*G + u2*Q with
  one shared doubling chain over 66 4-bit windows: per window 4
  doublings + one add from the fixed G window table + one add from the
  per-signature Q table (built on device, 1 dbl + 13 adds).  The G
  table (j*G for j = 0..15, Jacobian Montgomery limbs) is precomputed
  host-side and `jax.device_put` once per process — the PR-11
  table-residency pattern: no table-build program ever compiles, and
  the resident buffer is passed as a kernel argument, never re-staged
  per call.  Lookups are one-hot matmuls (gathers serialize on TPU).
* **verdict** — cosmos rows check x(R') mod n == r (x == r or
  x == r + n when r + n < p, exactly the host's `pt[0] % N == r`);
  eth rows (65-byte R||S||V signatures) check x(R') == r exactly plus
  the recovery-id parity y(R') & 1 == v, which is equivalent to
  Ecrecover(h, sig) == Q (s*R == e*G + r*Q  <=>  R == u1*G + u2*Q).

All paths are branch-free selects, so the verdict is bit-identical to
the pure-host crypto/secp256k1 / crypto/secp256k1eth lane in every
edge (tampered rows, high-s, r/s = 0, off-curve keys, infinity
results) — the host lane is the fallback verdict oracle of the
MODE_SECP verify-service lane (models/secp_verifier).
"""

from __future__ import annotations

import threading

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..crypto import secp256k1 as host_secp

NLIMBS = 22
BITS = 12
RADIX = 1 << BITS
MASK = RADIX - 1
NWINDOWS = NLIMBS * BITS // 4  # 66 4-bit windows span the 264 limb bits

P = host_secp.P  # 2^256 - 2^32 - 977
N = host_secp.N  # the group order (the ECDSA scalar field)
R_MONT = 1 << (NLIMBS * BITS)  # 2^264


def _int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    assert x == 0, "value too wide for limb count"
    return out


class _Mod:
    """Host-side constant bundle for one odd modulus m < 2^264: the limb
    decompositions and Montgomery constants the device ops close over."""

    def __init__(self, m: int):
        self.m = m
        self.limbs = _int_to_limbs(m)
        self.limbs23 = _int_to_limbs(m, NLIMBS + 1)
        self.prime = (-pow(m, -1, RADIX)) % RADIX  # -m^-1 mod 2^12
        self.r2 = _int_to_limbs(R_MONT * R_MONT % m)  # to-Montgomery mul
        self.one_plain = _int_to_limbs(1)  # from-Montgomery mul
        self.one_mont = _int_to_limbs(R_MONT % m)
        # m - 2 bits MSB-first: the Fermat inversion ladder of the ONE
        # total-product inverse in the batch-inversion trick
        self.inv_bits = np.array(
            [b == "1" for b in bin(m - 2)[2:]], dtype=bool
        )

    def to_mont(self, x: int) -> int:
        return x * R_MONT % self.m

    def from_mont(self, x: int) -> int:
        return x * pow(R_MONT, self.m - 2, self.m) % self.m


FP = _Mod(P)
FN = _Mod(N)

# anti-diagonal collector: outer(a, b).reshape @ _DIAG == conv(a, b)
_DIAG = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _DIAG[_i * NLIMBS + _j, _i + _j] = 1


# ------------------------------------------------------------- primitives
# Identical staging to ops/bls381 (the proven idiom), parameterized by
# the modulus bundle: lax.scan carries keep the jaxpr O(1) in the limb
# count, the Montgomery reduction is a fori_loop of dynamic slices.
# Representation: canonical digits everywhere — every op returns limbs
# in [0, 2^12) with value in [0, m), so limb-wise equality IS value
# equality and window extraction reads digits directly.
#
# Compile-cost note: like the bls381 kernels, the rolled Montgomery
# graphs are expensive to compile cold on the CPU backend (one bucket
# shape ~2 min); the persistent XLA compile cache
# (COMETBFT_TPU_COMPILE_CACHE, on by default in tests and bench — the
# same mitigation the ed25519 verify kernel already relies on) makes
# every later process a cache hit, and the power-of-two bucketing
# keeps the shape set small.


def _carry23(a):
    """Carry chain into 23 canonical-width limbs (signed input limbs;
    any value in (-2^264, 2^265) fits)."""
    aT = jnp.moveaxis(a, -1, 0)  # (L, ...)

    def step(c, limb):
        v = limb + c
        return v >> BITS, v & MASK

    c, outT = lax.scan(step, jnp.zeros_like(aT[0]), aT)
    out = jnp.moveaxis(outT, 0, -1)
    if a.shape[-1] < NLIMBS + 1:
        out = jnp.concatenate([out, c[..., None]], axis=-1)
    return out


def _cond_sub_m(a23, mod: _Mod):
    """One round: subtract m if a >= m (borrow-chain compare+select)."""
    aT = jnp.moveaxis(a23, -1, 0)
    ml = jnp.asarray(mod.limbs23)

    def step(borrow, inp):
        limb, m_i = inp
        v = limb - m_i - borrow
        b = (v < 0).astype(v.dtype)
        return b, v + b * RADIX

    borrow, dT = lax.scan(step, jnp.zeros_like(aT[0]), (aT, ml))
    d = jnp.moveaxis(dT, 0, -1)
    ge = borrow == 0  # no final borrow -> a >= m
    return jnp.where(ge[..., None], d, a23)


def _normalize2m(a, mod: _Mod):
    """Limb vector with value in (-m, 2m) -> canonical [0, m)."""
    return _cond_sub_m(_carry23(a), mod)[..., :NLIMBS]


def add(a, b, mod: _Mod):
    return _normalize2m(a + b, mod)


def sub(a, b, mod: _Mod):
    """a - b (canonical inputs): a + m - b lands in (0, 2m); the signed
    carry chain absorbs the negative intermediate limbs."""
    return _normalize2m(a - b + jnp.asarray(mod.limbs), mod)


def mul(a, b, mod: _Mod):
    """Montgomery product a*b*R^-1 mod m.  Canonical output; inputs may
    be any canonical-DIGIT vectors as long as a*b < R*m (both < m, or
    one < m and the other < R — the raw-input to-Montgomery case).

    int32 bounds: conv limbs <= 22*4095^2 ~ 3.7e8; the reduction adds
    <= the same again (limb j is touched by <= 22 of the 22 q*m adds)
    — peak < 7.4e8 < 2^31; forwarded carries are < 2^18 on top."""
    outer = (a[..., :, None] * b[..., None, :]).reshape(
        a.shape[:-1] + (NLIMBS * NLIMBS,)
    )
    t = outer @ jnp.asarray(_DIAG)  # (..., 44) conv limbs
    pl = jnp.asarray(mod.limbs)
    pprime = mod.prime

    # word-wise reduction: clear limb i by adding q*m at weight i.
    def body(i, t):
        ti = lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
        c = ti >> BITS
        low = ti & MASK
        q = (low * pprime) & MASK
        seg = lax.dynamic_slice_in_dim(t, i, NLIMBS, axis=-1)
        seg = seg + q[..., None] * pl
        t = lax.dynamic_update_slice_in_dim(t, seg, i, axis=-1)
        nxt = lax.dynamic_index_in_dim(t, i + 1, axis=-1, keepdims=False)
        # limb i is (c<<12 + low + q*m0); low + q*m0 ≡ 0 mod 2^12 —
        # forward the whole /2^12 quotient, the final slice drops limb i
        nxt = nxt + c + ((low + q * pl[0]) >> BITS)
        return lax.dynamic_update_index_in_dim(t, nxt, i + 1, axis=-1)

    t = lax.fori_loop(0, NLIMBS, body, t)
    return _normalize2m(t[..., NLIMBS:], mod)


def sqr(a, mod: _Mod):
    return mul(a, a, mod)


def to_mont(a, mod: _Mod):
    """Raw canonical-limb value (< 2^264) -> Montgomery domain, reduced
    mod m (the mul's own reduction absorbs values >= m)."""
    return mul(a, jnp.asarray(mod.r2), mod)


def from_mont(a, mod: _Mod):
    """Montgomery domain -> plain canonical value in [0, m)."""
    return mul(a, jnp.asarray(mod.one_plain), mod)


def select(cond, a, b):
    return jnp.where(cond[..., None], a, b)


def is_zero(a) -> jnp.ndarray:
    """(...,) bool — canonical-input zero test (0 is 0 in Montgomery)."""
    return jnp.all(a == 0, axis=-1)


def _lt_const(a, climbs) -> jnp.ndarray:
    """(..., 22) canonical digits < host constant?  Unrolled
    borrow-chain compare."""
    borrow = jnp.zeros(a.shape[:-1], dtype=a.dtype)
    for i in range(NLIMBS):
        d = a[..., i] - jnp.int32(int(climbs[i])) - borrow
        borrow = lax.shift_right_logical(d, 31) & 1
    return borrow == 1


def _add_const(a, climbs):
    """(..., 22) + host constant, carried back to canonical digits (the
    sum must stay < 2^264; used for r + n < 2^257)."""
    return _carry23(a + jnp.asarray(climbs))[..., :NLIMBS]


# ------------------------------------------------ Montgomery batch inverse


def _mont_pow_inv(x, mod: _Mod):
    """x^(m-2) in the Montgomery domain (ONE element, shape (..., 22)):
    the single Fermat chain of the batch-inversion trick.  lax.scan over
    the fixed MSB-first bit vector of m-2 keeps the jaxpr one
    square+conditional-multiply body."""
    one = jnp.broadcast_to(jnp.asarray(mod.one_mont), x.shape)

    def step(acc, bit):
        acc = sqr(acc, mod)
        return jnp.where(bit, mul(acc, x, mod), acc), None

    acc, _ = lax.scan(step, one, jnp.asarray(mod.inv_bits))
    return acc


def _shifted(x, k: int, fill):
    """x shifted k rows toward higher indices along axis 0, `fill` rows
    entering at the top (static k: unrolled at trace time)."""
    pad = jnp.broadcast_to(fill, (k,) + x.shape[1:])
    return jnp.concatenate([pad, x[:-k]], axis=0)


def batch_inverse(x, mod: _Mod):
    """Montgomery batch inversion of a (B, 22) Montgomery-domain batch:
    every row's inverse for the price of ONE Fermat chain.

    Hillis-Steele inclusive prefix and suffix products (log2(B)
    full-width batched muls each, unrolled at trace time), one
    exponentiation of the total product, then
    inv_i = exclusive_prefix_i * exclusive_suffix_i * total^-1.

    EVERY row must be nonzero: callers sanitize poisonable rows to 1
    (with their verdict masked off) BEFORE calling — a zero row would
    zero the total product and corrupt every other row's inverse.
    """
    one = jnp.asarray(mod.one_mont)
    n = x.shape[0]
    pre = x
    suf = x[::-1]
    k = 1
    while k < n:
        pre = mul(pre, _shifted(pre, k, one), mod)
        suf = mul(suf, _shifted(suf, k, one), mod)
        k *= 2
    suf = suf[::-1]  # inclusive suffix products
    total = pre[-1]
    tinv = _mont_pow_inv(total, mod)
    left = jnp.concatenate([one[None], pre[:-1]], axis=0)
    right = jnp.concatenate([suf[1:], one[None]], axis=0)
    part = mul(left, right, mod)  # prod of all rows but i
    return mul(part, jnp.broadcast_to(tinv, x.shape), mod)


# ------------------------------------------------------------- group ops
# y^2 = x^3 + 7, a = 0: the same complete-by-selects Jacobian formulas
# as ops/bls381 (both curves are a = 0 short Weierstrass).  Infinity is
# Z = 0; all coordinates Montgomery-domain canonical limbs mod p.

_B7_M = _int_to_limbs(FP.to_mont(host_secp.B))  # curve b = 7


def pt_double(X, Y, Z):
    A = sqr(X, FP)
    Bb = sqr(Y, FP)
    Cc = sqr(Bb, FP)
    t = sqr(add(X, Bb, FP), FP)
    D = sub(t, add(A, Cc, FP), FP)
    D = add(D, D, FP)
    E = add(add(A, A, FP), A, FP)
    F = sqr(E, FP)
    X3 = sub(F, add(D, D, FP), FP)
    eight_c = add(add(Cc, Cc, FP), add(Cc, Cc, FP), FP)
    eight_c = add(eight_c, eight_c, FP)
    Y3 = sub(mul(E, sub(D, X3, FP), FP), eight_c, FP)
    Z3 = mul(add(Y, Y, FP), Z, FP)
    return X3, Y3, Z3


def pt_add(X1, Y1, Z1, X2, Y2, Z2):
    """Branch-free complete addition over the batch via selects."""
    z1z = sqr(Z1, FP)
    z2z = sqr(Z2, FP)
    U1 = mul(X1, z2z, FP)
    U2 = mul(X2, z1z, FP)
    S1 = mul(mul(Y1, Z2, FP), z2z, FP)
    S2 = mul(mul(Y2, Z1, FP), z1z, FP)
    H = sub(U2, U1, FP)
    Rr = sub(S2, S1, FP)
    h_zero = is_zero(H)
    r_zero = is_zero(Rr)
    inf1 = is_zero(Z1)
    inf2 = is_zero(Z2)

    I = sqr(add(H, H, FP), FP)
    J = mul(H, I, FP)
    r2 = add(Rr, Rr, FP)
    V = mul(U1, I, FP)
    X3 = sub(sqr(r2, FP), add(J, add(V, V, FP), FP), FP)
    Y3 = sub(
        mul(r2, sub(V, X3, FP), FP), mul(add(S1, S1, FP), J, FP), FP
    )
    Z3 = mul(mul(Z1, Z2, FP), H, FP)
    Z3 = add(Z3, Z3, FP)

    dX, dY, dZ = pt_double(X1, Y1, Z1)
    same = h_zero & r_zero & ~inf1 & ~inf2
    neg = h_zero & ~r_zero & ~inf1 & ~inf2
    X3 = select(same, dX, X3)
    Y3 = select(same, dY, Y3)
    Z3 = select(same, dZ, Z3)
    X3 = select(neg, jnp.zeros_like(X3), X3)
    Y3 = select(neg, jnp.zeros_like(Y3), Y3)
    Z3 = select(neg, jnp.zeros_like(Z3), Z3)
    X3 = select(inf1, X2, X3)
    Y3 = select(inf1, Y2, Y3)
    Z3 = select(inf1, Z2, Z3)
    X3 = select(inf2 & ~inf1, X1, X3)
    Y3 = select(inf2 & ~inf1, Y1, Y3)
    Z3 = select(inf2 & ~inf1, Z1, Z3)
    return X3, Y3, Z3


def on_curve(X_m, Y_m) -> jnp.ndarray:
    """(..., 22) affine Montgomery limbs -> (...,) bool: y^2 == x^3 + 7.
    Canonical-limb equality is value equality (both sides in [0, p))."""
    lhs = sqr(Y_m, FP)
    rhs = add(mul(sqr(X_m, FP), X_m, FP), jnp.asarray(_B7_M), FP)
    return jnp.all(lhs == rhs, axis=-1)


# --------------------------------------------------- fixed G window table


def _build_g_table() -> np.ndarray:
    """(16, 66) int32: j*G for j = 0..15 as flattened Jacobian triples
    (X | Y | Z, 22 Montgomery limbs each; j = 0 -> infinity, Z = 0).
    Pure host bigint — the PR-11 residency pattern: NO table-build
    program ever compiles; `g_table()` device_puts this once."""
    out = np.zeros((16, 3 * NLIMBS), dtype=np.int32)
    out[0, :NLIMBS] = _int_to_limbs(FP.to_mont(1))
    out[0, NLIMBS : 2 * NLIMBS] = _int_to_limbs(FP.to_mont(1))
    acc = None
    for j in range(1, 16):
        acc = host_secp._add(acc, host_secp.G)
        out[j, :NLIMBS] = _int_to_limbs(FP.to_mont(acc[0]))
        out[j, NLIMBS : 2 * NLIMBS] = _int_to_limbs(FP.to_mont(acc[1]))
        out[j, 2 * NLIMBS :] = _int_to_limbs(FP.to_mont(1))
    return out


_G_TABLE_NP = _build_g_table()
_G_TABLE_DEV = None
_G_TABLE_MTX = threading.Lock()


def g_table():
    """The resident device copy of the G window table: host-precomputed,
    `device_put` once per process, passed to the kernel as an argument
    so it is never re-staged per dispatch (PR-11 table residency)."""
    global _G_TABLE_DEV
    if _G_TABLE_DEV is None:
        with _G_TABLE_MTX:
            if _G_TABLE_DEV is None:
                import jax

                _G_TABLE_DEV = jax.device_put(_G_TABLE_NP)
    return _G_TABLE_DEV


def _lookup_g(gtab, idx):
    """One-hot select from the (16, 66) flat G table by (B,) idx."""
    onehot = (
        idx[:, None] == jnp.arange(16, dtype=jnp.int32)[None, :]
    ).astype(jnp.int32)  # (B, 16)
    sel = onehot @ gtab  # (B, 66)
    return (
        sel[:, :NLIMBS],
        sel[:, NLIMBS : 2 * NLIMBS],
        sel[:, 2 * NLIMBS :],
    )


def _build_q_table(Qx, Qy, Qz):
    """Stacked (16, B, 22) Jacobian window table [0..15]*Q, built as a
    14-step lax.scan of one complete add (the addition law's own
    same-point branch makes entry 2 a doubling), so the jaxpr carries
    ONE add body instead of 13 unrolled ones.  Sanitized rows enter
    with Z = 0, so every multiple of them stays infinity."""
    one = jnp.broadcast_to(jnp.asarray(FP.one_mont), Qx.shape)
    inf = (one, one, jnp.zeros_like(Qx))

    def step(acc, _):
        nxt = pt_add(acc[0], acc[1], acc[2], Qx, Qy, Qz)
        return nxt, nxt

    _, tail = lax.scan(step, (Qx, Qy, Qz), None, length=14)  # 2Q..15Q
    return (
        jnp.concatenate([inf[0][None], Qx[None], tail[0]], axis=0),
        jnp.concatenate([inf[1][None], Qy[None], tail[1]], axis=0),
        jnp.concatenate([inf[2][None], Qz[None], tail[2]], axis=0),
    )


def _lookup_q(qtab, idx):
    """One-hot select from a stacked (16, B, 22) table by (B,) idx."""
    onehot = (
        idx[None, :] == jnp.arange(16, dtype=jnp.int32)[:, None]
    ).astype(jnp.int32)[..., None]  # (16, B, 1)
    tX, tY, tZ = qtab
    return (
        jnp.sum(tX * onehot, axis=0),
        jnp.sum(tY * onehot, axis=0),
        jnp.sum(tZ * onehot, axis=0),
    )


def _windows(a):
    """(B, 22) canonical limbs -> (66, B) int32 4-bit windows, MSB
    first (each 12-bit limb is three windows)."""
    w = jnp.stack([a & MASK, a >> 4, a >> 8], axis=-1) & 15  # (B, 22, 3)
    w = w.reshape(a.shape[0], NWINDOWS)
    return w[:, ::-1].T


# ----------------------------------------------------------- verification


def verify_batch(qx, qy, q_valid, e, r, s, is_eth, v, gtab):
    """Batched ECDSA verification, one fused device program.

    qx, qy  : (B, 22) int32 — affine pubkey coordinates, PLAIN canonical
              limbs (host decode/decompress already rejected malformed
              encodings via q_valid; garbage limbs on invalid rows are
              harmless — they feed only multiplications)
    q_valid : (B,) bool — host-side decode verdict
    e       : (B, 22) int32 — raw 256-bit message-hash value (SHA-256
              for cosmos rows, Keccak-256 for eth rows); the Montgomery
              conversion reduces it mod n exactly like the host's % N
    r, s    : (B, 22) int32 — raw signature scalars
    is_eth  : (B,) bool — row wire format: eth R||S||V recovery
              semantics vs cosmos compressed-key semantics
    v       : (B,) int32 — eth recovery id (0/1); ignored on cosmos rows
    gtab    : (16, 66) int32 — the resident G window table
              (:func:`g_table`), an ARGUMENT so the device_put buffer is
              reused across dispatches instead of re-staged as a baked
              constant

    Returns (B,) bool, bit-identical to the host verifiers.

    Manifest kernel ``secp256k1_verify_batch`` (analysis/kernel_manifest):
    eqn-budgeted and fingerprint-pinned; the jit site is the bridge's
    module-cached ``jax.jit(verify_batch)`` registered in JIT_SITES.
    """
    # ---- validation (device half): on-curve + scalar ranges + low-s
    qx_m = to_mont(qx, FP)
    qy_m = to_mont(qy, FP)
    q_ok = q_valid & on_curve(qx_m, qy_m)
    n_l = FN.limbs
    r_ok = ~is_zero(r) & _lt_const(r, n_l)
    s_ok = (
        ~is_zero(s)
        & _lt_const(s, n_l)
        & _lt_const(s, _int_to_limbs(N // 2 + 1))  # low-s: s <= n/2
    )
    v_ok = jnp.where(is_eth, v <= 1, True)
    row_pre = q_ok & r_ok & s_ok & v_ok

    # ---- u1 = e/s, u2 = r/s (mod n), s^-1 amortized across the batch.
    # Sanitize BEFORE the shared product: an s = 0 row would zero the
    # total and poison every valid row's inverse.
    one_plain = jnp.asarray(FN.one_plain)
    s_safe = select(s_ok, s, jnp.broadcast_to(one_plain, s.shape))
    w_m = batch_inverse(to_mont(s_safe, FN), FN)
    e_m = to_mont(e, FN)  # to-Montgomery reduces mod n (host: e % N)
    r_m = to_mont(r, FN)
    u1 = from_mont(mul(e_m, w_m, FN), FN)
    u2 = from_mont(mul(r_m, w_m, FN), FN)

    # ---- Shamir interleave: acc := 16*acc + u1_i*G + u2_i*Q per window
    one_m = jnp.broadcast_to(jnp.asarray(FP.one_mont), qx.shape)
    Qz = select(q_ok, one_m, jnp.zeros_like(qx))
    qtab = _build_q_table(qx_m, qy_m, Qz)
    u1w = _windows(u1)
    u2w = _windows(u2)

    def step(i, acc):
        # 4 doublings as a rolled scan: one doubling body in the jaxpr
        # instead of four (compile cost, not semantics)
        (X, Y, Z), _ = lax.scan(
            lambda p, _: (pt_double(*p), None), acc, None, length=4
        )
        gX, gY, gZ = _lookup_g(
            gtab, lax.dynamic_index_in_dim(u1w, i, axis=0, keepdims=False)
        )
        X, Y, Z = pt_add(X, Y, Z, gX, gY, gZ)
        qX, qY, qZ = _lookup_q(
            qtab, lax.dynamic_index_in_dim(u2w, i, axis=0, keepdims=False)
        )
        X, Y, Z = pt_add(X, Y, Z, qX, qY, qZ)
        return (X, Y, Z)

    inf = (one_m, one_m, jnp.zeros_like(qx))
    X, Y, Z = lax.fori_loop(0, NWINDOWS, step, inf)

    # ---- affine normalization, z^-1 amortized across the batch (the
    # second shared inversion; Z = 0 rows sanitized exactly like s = 0)
    z_nonzero = ~is_zero(Z)
    z_safe = select(z_nonzero, Z, jnp.broadcast_to(jnp.asarray(FP.one_mont), Z.shape))
    zinv = batch_inverse(z_safe, FP)
    zi2 = sqr(zinv, FP)
    x_aff = from_mont(mul(X, zi2, FP), FP)
    y_aff = from_mont(mul(mul(Y, zi2, FP), zinv, FP), FP)

    # ---- verdict
    rn = _add_const(r, n_l)  # r + n (< 2^257, fits the limb vector)
    cosmos_ok = jnp.all(x_aff == r, axis=-1) | (
        _lt_const(rn, FP.limbs) & jnp.all(x_aff == rn, axis=-1)
    )
    eth_ok = jnp.all(x_aff == r, axis=-1) & ((y_aff[:, 0] & 1) == v)
    return row_pre & z_nonzero & jnp.where(is_eth, eth_ok, cosmos_ok)


# ------------------------------------------------------------ host bridge


_VERIFY_JIT = None
_JIT_MTX = threading.Lock()


def ints_to_limbs_np(vals) -> np.ndarray:
    """Vectorized host packer: a sequence of plain ints (< 2^264) ->
    (B, 22) int32 limb array — one numpy pass over the little-endian
    bytes (3 bytes = 2 limbs), same staging as ops/bls381."""
    n = len(vals)
    if n == 0:
        return np.zeros((0, NLIMBS), dtype=np.int32)
    raw = np.frombuffer(
        b"".join(v.to_bytes(33, "little") for v in vals), dtype=np.uint8
    ).reshape(n, 33)
    trip = raw.reshape(n, NLIMBS // 2, 3).astype(np.int32)
    out = np.empty((n, NLIMBS), dtype=np.int32)
    out[:, 0::2] = trip[..., 0] | ((trip[..., 1] & 0xF) << 8)
    out[:, 1::2] = (trip[..., 1] >> 4) | (trip[..., 2] << 4)
    return out


def from_limbs(a) -> np.ndarray:
    """Host-side limb decoder (plain, NON-Montgomery limbs) -> object
    array of Python ints; receives already-fetched device results."""
    a = np.asarray(a)
    flat = a.reshape(-1, a.shape[-1])
    out = np.empty(flat.shape[0], dtype=object)
    for i, row in enumerate(flat):
        val = 0
        for k in range(len(row) - 1, -1, -1):
            val = (val << BITS) + int(row[k])
        out[i] = val
    return out.reshape(a.shape[:-1])


def verify_batch_device(qx, qy, q_valid, e, r, s, is_eth, v) -> np.ndarray:
    """One device dispatch of the batched ECDSA kernel over pre-packed
    host arrays; the blocking result fetch is this bridge's declared
    collect point (analysis/kernel_manifest.COLLECT_BOUNDARIES)."""
    import jax

    global _VERIFY_JIT
    if _VERIFY_JIT is None:
        with _JIT_MTX:
            if _VERIFY_JIT is None:
                _VERIFY_JIT = jax.jit(verify_batch)
    ok = _VERIFY_JIT(
        jnp.asarray(qx),
        jnp.asarray(qy),
        jnp.asarray(q_valid),
        jnp.asarray(e),
        jnp.asarray(r),
        jnp.asarray(s),
        jnp.asarray(is_eth),
        jnp.asarray(v),
        g_table(),
    )
    return np.asarray(ok)
